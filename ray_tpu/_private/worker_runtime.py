"""Core worker — the in-process runtime linked into every worker and driver.

Analog of the reference's CoreWorker
(/root/reference/src/ray/core_worker/core_worker.h:227): task submission with
lease-based scheduling and worker pipelining (direct_task_transport.h:57),
actor creation/submission with per-handle ordering
(direct_actor_task_submitter.h), Put/Get against the node's shared-memory
store plus an in-process memory store for small results
(store_provider/memory_store/), and the execution loop on the worker side
(core_worker.cc:2188 RunTaskExecutionLoop → here an RPC server receiving
pushed tasks).
"""
from __future__ import annotations

import collections
import functools
import itertools
import hashlib
import os
import queue
import threading
import time
import traceback
import uuid
from concurrent.futures import Future as PyFuture

from ray_tpu import exceptions as exc
from ray_tpu._private import events as _events
from ray_tpu._private import fault_injection as _fi
from ray_tpu._private import memory_anatomy as _ma
from ray_tpu._private import serialization as ser
from ray_tpu._private.object_ref import ObjectRef, ReferenceCounter
from ray_tpu._private.protocol import ConnectionLost, RpcClient, RpcServer
from ray_tpu._private.store_client import StoreClient

# Results below this size return inline in the task reply and live in the
# owner's memory store (reference: small returns go to the owner's in-process
# store, core_worker.cc "return inlined"); larger go to the shm store.
INLINE_RESULT_LIMIT = 100 * 1024
# Max tasks pipelined onto one leased worker before requesting another lease
# (reference pipelines to leased workers in OnWorkerIdle,
# direct_task_transport.cc:174).
def _pipeline_depth() -> int:
    from ray_tpu._private.config import get_config

    return int(get_config("max_tasks_in_flight_per_worker"))


def _lease_soft_cap(worker=None) -> int:
    """Soft bound on leases per scheduling key. Scales with CLUSTER CPU
    capacity (reference: per-node worker_pool soft limits sum to cluster
    capacity), not this process's core count — a laptop driver submitting
    to a 100-core cluster must not throttle it. Cached with a TTL on the
    worker; config `lease_soft_cap` / env RAY_TPU_LEASE_SOFT_CAP
    overrides (0 = auto)."""
    from ray_tpu._private.config import get_config

    configured = int(get_config("lease_soft_cap"))
    if configured > 0:
        return configured
    cluster = worker._cluster_cpu_total() if worker is not None else 0
    return max(4, 2 * (os.cpu_count() or 1), int(2 * cluster))


class _PendingValue:
    __slots__ = ("event", "data", "error")

    def __init__(self):
        self.event = threading.Event()
        self.data = None


class FifoSemaphore:
    """Counting semaphore granting slots in enqueue order.

    threading.Semaphore wakes waiters in unspecified order, which would let
    actor call m3 run before m2 even at max_concurrency=1; grant order here
    follows enqueue order, which the per-caller seq gate makes equal to
    submission order (reference: actor_scheduling_queue.h runs client-side
    sequence numbers in order; concurrency groups bound parallelism)."""

    def __init__(self, n: int):
        self._n = max(1, n)
        self._lock = threading.Lock()
        self._active = 0
        self._waiters: "collections.deque[threading.Event]" = \
            collections.deque()

    def enqueue(self):
        """Reserve a place in line without blocking. Returns a ticket to pass
        to wait(); None means the slot was granted immediately."""
        with self._lock:
            if self._active < self._n and not self._waiters:
                self._active += 1
                return None
            ev = threading.Event()
            self._waiters.append(ev)
            return ev

    def wait(self, ticket):
        if ticket is not None:
            ticket.wait()

    def release(self):
        with self._lock:
            if self._waiters:
                # hand the slot to the next in line (active count unchanged)
                self._waiters.popleft().set()
            else:
                self._active -= 1

    def cancel(self, ticket):
        """Back out of the line (task aborted before running)."""
        if ticket is None:
            self.release()
            return
        with self._lock:
            try:
                self._waiters.remove(ticket)
                return
            except ValueError:
                pass  # already granted by a release() — give the slot back
        self.release()


class MemoryStore:
    """Owner-side store for small/inlined results (futures until resolved)."""

    def __init__(self):
        self._values: dict[bytes, _PendingValue] = {}
        self._lock = threading.Lock()

    def entry(self, object_id: bytes) -> _PendingValue:
        with self._lock:
            entry = self._values.get(object_id)
            if entry is None:
                entry = _PendingValue()
                self._values[object_id] = entry
            return entry

    def put(self, object_id: bytes, data: bytes):
        entry = self.entry(object_id)   # ONE lock round, not two
        entry.data = data
        entry.event.set()

    def get_nowait(self, object_id: bytes):
        with self._lock:
            entry = self._values.get(object_id)
        if entry is not None and entry.event.is_set():
            return entry.data
        return None

    def contains_resolved(self, object_id: bytes) -> bool:
        return self.get_nowait(object_id) is not None

    def free(self, object_id: bytes):
        with self._lock:
            self._values.pop(object_id, None)

    def __len__(self):
        return len(self._values)


def _derive_item_id(gen_id: bytes, index: int) -> bytes:
    """Deterministic id for item `index` of a dynamic-returns stream:
    re-executing the producer (lineage reconstruction) regenerates the
    same ids, so existing borrowed refs resolve against the new run."""
    return hashlib.blake2b(gen_id + index.to_bytes(8, "big"),
                           digest_size=16).digest()


class _GenStream:
    """Owner-side record of one dynamic-returns task's item stream.

    The executor announces each yielded item as it is produced
    (rpc_generator_item); the final task reply carries the item count
    (success) or the error payload. Iterators (_gen_next) wait here.
    Reference: the streaming-generator return path in
    python/ray/_raylet.pyx:168 + core_worker task_manager's
    dynamic_return_ids.
    """

    __slots__ = ("items", "total", "error", "cond", "closed")

    def __init__(self):
        self.items: dict[int, bytes] = {}   # index -> object id
        self.total: int | None = None       # known once the task finishes
        self.error: bytes | None = None     # serialize_error payload
        self.closed = False                 # consumer closed early
        self.cond = threading.Condition()

    def add(self, index: int, rid: bytes):
        with self.cond:
            self.items[index] = rid
            self.cond.notify_all()

    def finish(self, total: int):
        with self.cond:
            if self.total is None:
                self.total = total
            self.cond.notify_all()

    def fail(self, error_data: bytes):
        with self.cond:
            if self.error is None:
                self.error = error_data
            self.cond.notify_all()


class _LeasedWorker:
    def __init__(self, grant: dict, client: RpcClient):
        self.lease_id = grant["lease_id"]
        self.worker_id = grant["worker_id"]
        self.addr = tuple(grant["worker_addr"])
        self.node_id = grant["node_id"]
        self.client = client
        self.in_flight = 0
        self.dead = False


class _SchedulingKeyQueue:
    """One background submitter per (function, resources, strategy): acquires
    leases, pipelines tasks onto them, retries on worker death."""

    def __init__(self, worker: "CoreWorker", key, resources: dict,
                 strategy: dict | None):
        self.worker = worker
        self.key = key
        self.resources = resources
        self.strategy = strategy
        self.tasks: queue.Queue = queue.Queue()
        self.leased: list[_LeasedWorker] = []
        self._lock = threading.Lock()
        self._wakeup = threading.Event()
        self._lease_pending = False       # one in-flight lease request max
        self._dispatching = False         # dispatch thread holds a popped spec
        self._lease_error: BaseException | None = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"submit-{key[0][:8].hex() if isinstance(key[0], bytes) else key[0]}")
        self._thread.start()

    def submit(self, spec: dict):
        # Fast path: a leased worker with a free pipeline slot takes the
        # push straight from the submitting thread — no dispatch-thread
        # handoff (queue put + wake + get costs ~50µs of the sync-task
        # budget on the 1-core box). Fairness: the shortcut only fires
        # when nothing is waiting in the queue AND the dispatch thread is
        # not holding a popped spec it is still trying to place (that
        # spec is invisible to qsize(); without the flag a stream of
        # fast-path submits could starve it of freed slots).
        if self.tasks.qsize() == 0 and not self._dispatching \
                and not spec.get("_cancelled"):
            lw = self._pick_worker()
            if lw is not None:
                self._last_dispatch = time.monotonic()
                if self._push(lw, spec):
                    return
        self.tasks.put(spec)
        self._wakeup.set()

    def _run(self):
        """Dispatch loop. NEVER blocks on lease acquisition — a granted lease
        can only be returned from this loop, so blocking here while leases
        idle would deadlock the raylet's resource accounting (the reference
        has the same constraint: lease requests are async callbacks in
        direct_task_transport.cc, dispatch happens in OnWorkerIdle)."""
        while not self.worker.stopped:
            try:
                spec = self.tasks.get(timeout=1.0)
            except queue.Empty:
                self._maybe_return_leases()
                continue
            self._dispatching = True
            dispatched = False
            while not dispatched and not self.worker.stopped:
                if spec.get("_cancelled"):
                    self.worker._fail_task(spec, exc.TaskCancelledError(
                        spec.get("task_desc", "task")))
                    dispatched = True
                    continue
                lw = self._pick_worker()
                if lw is not None:
                    self._last_dispatch = time.monotonic()
                    dispatched = self._push(lw, spec)
                    continue
                if not self._may_grow():
                    # at the soft lease cap with live dispatches — wait for
                    # an in-flight slot instead of growing the fleet
                    self._wakeup.wait(timeout=0.05)
                    self._wakeup.clear()
                    continue
                err = self._maybe_request_lease()
                if err is not None:
                    self.worker._fail_task(spec, err)
                    # the same error condemns everything queued behind it
                    while True:
                        try:
                            pending = self.tasks.get_nowait()
                        except queue.Empty:
                            break
                        self.worker._fail_task(pending, err)
                    dispatched = True
                    continue
                self._wakeup.wait(timeout=0.05)
                self._wakeup.clear()
            self._dispatching = False

    def _pick_worker(self):
        # Depth-1 unless there's real QUEUE pressure: with a short queue,
        # distinct leases maximize cluster parallelism; with a long queue,
        # pipelining depth 2 hides push RTT (execution on the worker is
        # serial either way — a lease represents ONE task's worth of
        # resources). Deliberately NOT counting in-flight work as
        # pressure: queue depth signals the caller is out-running
        # dispatch (pipelining helps), while in-flight-only signals work
        # that may be BLOCKED — stacking a task behind a blocked one on a
        # serial worker deadlocks rendezvous patterns (4 tasks gating on
        # each other inside an actor, test_runtime_fixes). The fleet
        # ratchet this used to cause is bounded by _may_grow instead.
        depth = _pipeline_depth() if self.tasks.qsize() > 2 else 1
        with self._lock:
            alive = [lw for lw in self.leased if not lw.dead]
            self.leased = alive
            candidates = [lw for lw in alive if lw.in_flight < depth]
            if candidates:
                lw = min(candidates, key=lambda w: w.in_flight)
                lw.in_flight += 1
                return lw
            return None

    def _may_grow(self) -> bool:
        """Soft cap on leases per scheduling key: beyond it, prefer waiting
        for an in-flight slot over spawning another worker — one worker
        process per queued zero-cpu task thrashes small hosts (observed:
        18 workers on 1 core). The cap is SOFT for liveness: if nothing
        has dispatched for a second (e.g. every leased worker is blocked
        inside a nested `get`), growth resumes — the reference keeps the
        same escape via worker-pool soft limits + blocked-on-get CPU
        release (worker_pool.h num_workers_soft_limit)."""
        with self._lock:
            n = len(self.leased)
        if n < _lease_soft_cap(self.worker):
            return True
        return time.monotonic() - getattr(self, "_last_dispatch", 0.0) > 1.0

    def _maybe_request_lease(self):
        """Kick off an async lease request if none is in flight. Returns a
        terminal error if the last request failed, else None."""
        with self._lock:
            if self._lease_error is not None:
                err, self._lease_error = self._lease_error, None
                return err
            if self._lease_pending:
                return None
            self._lease_pending = True
        threading.Thread(target=self._lease_request_thread,
                         daemon=True).start()
        return None

    def _lease_request_thread(self):
        try:
            grant = self.worker.request_lease(self.resources, self.strategy)
            client = RpcClient(tuple(grant["worker_addr"]), timeout=None)
            lw = _LeasedWorker(grant, client)
            self._lease_timeouts = 0
            self._lease_conn_failures = 0
            with self._lock:
                self.leased.append(lw)
        except ConnectionLost:
            # Transient: the raylet we were talking to (or spilled to) died
            # mid-request. The cluster view heals within a heartbeat —
            # back off and let the dispatch loop re-request instead of
            # condemning every queued task (chaos-test finding). Pause
            # shape comes from the unified policy (full jitter over
            # consecutive failures) so a fleet of queues doesn't
            # re-request in lockstep.
            from ray_tpu._private.retry import RetryPolicy

            self._lease_timeouts = 0
            self._lease_conn_failures = getattr(
                self, "_lease_conn_failures", 0) + 1
            time.sleep(RetryPolicy(base_backoff_s=0.2, max_backoff_s=2.0)
                       .backoff(self._lease_conn_failures))
        except TimeoutError as e:
            # A full 300s raylet queue timeout is retried (capacity may be
            # coming: autoscaler, chaos replacement) — but not forever: two
            # consecutive exhausted waits mean the demand is going nowhere
            # (e.g. a typo'd resource name) and the tasks should fail
            # loudly rather than hang silently.
            self._lease_timeouts = getattr(self, "_lease_timeouts", 0) + 1
            if self._lease_timeouts >= 2:
                with self._lock:
                    self._lease_error = exc.RayError(
                        f"no capacity for {self.resources} after "
                        f"{self._lease_timeouts} full lease-queue waits: "
                        f"{e}")
        except Exception as e:  # noqa: BLE001
            with self._lock:
                self._lease_error = e
        finally:
            with self._lock:
                self._lease_pending = False
            self._wakeup.set()

    def _push(self, lw: _LeasedWorker, spec: dict) -> bool:
        # LEASE_GRANTED marks the end of this task's queue wait: it is
        # leaving the scheduling queue for a leased worker's pipeline.
        _events.task_event(spec["task_id"], "LEASE_GRANTED",
                           node_id=lw.node_id, worker_id=lw.worker_id,
                           desc=spec.get("task_desc"))
        try:
            fut = lw.client.call_async("push_task", spec=self.worker._strip_spec(spec))
        except ConnectionLost:
            # The task never left this process — the lease was stale (its
            # worker died with a removed node). Requeue WITHOUT charging
            # retries_left: the retry budget is for attempts that may have
            # executed (side effects), not for dispatch failures. Charging
            # here made a task bounce across N stale leases after a node
            # death and exhaust its budget without ever running (chaos
            # suite). Reference: lease invalidation re-requests, it does
            # not count as a task attempt.
            with self._lock:
                lw.dead = True
                lw.in_flight -= 1
            _events.task_event(spec["task_id"], "RESUBMITTED",
                               reason="dispatch connection lost",
                               desc=spec.get("task_desc"))
            self.submit(spec)
            return True
        # Reply lands as a callback on the client's reader/pump thread —
        # no parked thread per in-flight task (the reference's reply path
        # is a ClientCallManager completion-queue callback the same way).
        # _handle_task_reply/_task_done are non-blocking; the death path
        # may make short RPCs on OTHER connections, which is safe there.
        fut.add_done_callback(lambda value: self._on_reply(lw, spec, value))
        return True

    def _on_reply(self, lw: _LeasedWorker, spec: dict, value):
        from ray_tpu._private.protocol import _RemoteError

        if isinstance(value, _RemoteError):
            if isinstance(value.exc, ConnectionLost):
                self._on_worker_death(lw, spec)
            else:
                self.worker._fail_task(spec, value.exc)
                self._task_done(lw)
            return
        self.worker._handle_task_reply(spec, value, lw.node_id)
        self._task_done(lw)

    def _task_done(self, lw: _LeasedWorker):
        with self._lock:
            lw.in_flight -= 1
        self._wakeup.set()

    def _on_worker_death(self, lw: _LeasedWorker, spec: dict):
        with self._lock:
            lw.dead = True
        if spec.get("_cancelled"):
            self.worker._fail_task(spec, exc.TaskCancelledError(
                spec.get("task_desc", "task")))
            return
        retries = spec.get("retries_left", 0)
        if retries > 0:
            spec["retries_left"] = retries - 1
            _events.task_event(spec["task_id"], "RESUBMITTED",
                               reason="worker died",
                               retries_left=spec["retries_left"],
                               desc=spec.get("task_desc"))
            self.submit(spec)
        else:
            self.worker._fail_task(spec, self.worker._worker_death_error(
                lw.worker_id))

    def _maybe_return_leases(self):
        """Return idle leases so the raylet can free resources."""
        to_return = []
        with self._lock:
            keep = []
            for lw in self.leased:
                if lw.in_flight == 0 and self.tasks.empty():
                    to_return.append(lw)
                else:
                    keep.append(lw)
            self.leased = keep
        for lw in to_return:
            self.worker.return_lease(lw)


class _ActorQueue:
    """Client-side submission queue for one actor handle: preserves order,
    handles RESTARTING/DEAD transitions (reference:
    direct_actor_task_submitter.h sequential submit queue)."""

    def __init__(self, worker: "CoreWorker", actor_id: bytes, meta: dict):
        self.worker = worker
        self.actor_id = actor_id
        self.meta = meta
        self.seq = 0
        self.epoch = 0   # bumped on reconnect; scopes seq for the receiver
        self.client: RpcClient | None = None
        self.addr = None
        self._lock = threading.RLock()

    def _on_connection_lost(self):
        with self._lock:
            self.client = None
            self.epoch += 1
            self.seq = 0

    def _connect(self, timeout: float = 60.0):
        """Resolve the actor address (waiting through RESTARTING) and open a
        connection.

        MUST NOT hold self._lock while polling: assign_seq() runs on the
        caller's thread for every handle.method.remote(), and a submit
        thread camped on the lock here (up to 60s while the actor is
        pending) would block the caller — in Tune this deadlocked the
        driver's poll loop against a queued trial actor whose resources
        only free when the poll loop runs. The lock guards only the client
        field handoff.

        A PENDING_CREATION actor does not count against the timeout: like
        the reference (tasks buffer until the actor schedules,
        direct_actor_task_submitter.h), creation may legitimately wait
        behind resource availability for arbitrarily long."""
        with self._lock:
            if self.client is not None:
                if not self.client.closed:
                    return self.client
                # stale connection: new epoch so the replacement actor's
                # receiver doesn't wait for seqs lost with the old process
                self._on_connection_lost()
        deadline = time.time() + timeout
        poll = 0.05
        while True:
            synthetic = False
            try:
                info = self.worker.gcs.call("get_actor",
                                            actor_id=self.actor_id)
            except TimeoutError:
                # GCS overloaded (e.g. hundreds of actors creating at
                # once): a transient RPC timeout is not a verdict on the
                # actor — back off and re-poll instead of killing this
                # submit thread (which would strand its queued call).
                # SYNTHETIC pending: must not extend the deadline, or a
                # permanently-dead GCS would spin this thread forever.
                info = {"state": "PENDING_CREATION", "addr": None}
                synthetic = True
            if info is None:
                raise exc.ActorDiedError(self.actor_id.hex(),
                                         "actor not found")
            if info["state"] == "DEAD":
                raise exc.ActorDiedError(self.actor_id.hex(),
                                         info.get("death_cause") or "dead")
            if info["state"] == "ALIVE" and info["addr"]:
                try:
                    c = RpcClient(tuple(info["addr"]), timeout=None)
                except ConnectionLost:
                    c = None  # raced a death; loop
                if c is not None:
                    with self._lock:
                        if self.client is not None and \
                                not self.client.closed:
                            c.close()  # another submit thread won the race
                            return self.client
                        self.client = c
                        self.addr = tuple(info["addr"])
                        return c
            if info["state"] == "PENDING_CREATION" and not synthetic:
                deadline = time.time() + timeout   # not a failure: queued
            elif time.time() > deadline:
                raise exc.GetTimeoutError(
                    f"actor {self.actor_id.hex()} not ready in {timeout}s")
            time.sleep(poll)
            # with N pending handles this loop is N pollers against one
            # GCS; constant 50 ms polling melted it at N=400 — back off
            from ray_tpu._private.config import get_config

            poll = min(poll * 1.5,
                       float(get_config("actor_resolution_poll_max_s")))

    def assign_seq(self, spec: dict):
        """Must be called in program submission order (caller thread)."""
        with self._lock:
            spec["seq"] = self.seq
            spec["caller_epoch"] = self.epoch
            self.seq += 1

    def submit(self, spec: dict):
        max_retries = spec.get("retries_left", 0)
        if "seq" not in spec:
            self.assign_seq(spec)
        attempt = 0
        while True:
            try:
                client = self._connect()
                with self._lock:
                    if spec.get("caller_epoch") != self.epoch:
                        spec.pop("seq", None)
                        self.assign_seq(spec)
                fut = client.call_async("push_task",
                                        spec=self.worker._strip_spec(spec))
            except (exc.RayTpuError, ValueError, RuntimeError) as e:
                # actor resolved to DEAD / never became ready — resolve the
                # return futures instead of letting this thread die silently
                self.worker._fail_task(spec, e)
                return
            except ConnectionLost:
                self._on_connection_lost()
                spec.pop("seq", None)
                self.assign_seq(spec)
                attempt += 1
                if attempt > max_retries + 1:
                    self.worker._fail_task(spec, exc.ActorUnavailableError(
                        f"actor {self.actor_id.hex()} unavailable"))
                    return
                continue
            # reply runs as a reader/pump-thread callback (no parked thread
            # per in-flight call); the rare failure paths hop to fresh
            # threads because they block (GCS lookup, resubmit)
            fut.add_done_callback(lambda value: self._on_reply(spec, value))
            return

    def _on_reply(self, spec, value):
        from ray_tpu._private.protocol import _RemoteError

        if isinstance(value, _RemoteError):
            if isinstance(value.exc, ConnectionLost):
                self._on_connection_lost()
                retries = spec.get("retries_left", 0)
                if retries > 0:
                    spec["retries_left"] = retries - 1
                    spec.pop("seq", None)   # re-sequenced in the new epoch
                    threading.Thread(target=self.submit, args=(spec,),
                                     daemon=True).start()
                else:
                    threading.Thread(target=self._fail_dead, args=(spec,),
                                     daemon=True).start()
            else:
                self.worker._fail_task(spec, value.exc)
            return
        self.worker._handle_task_reply(spec, value, None)

    def _fail_dead(self, spec):
        # Distinguish died vs restarting for the error type.
        try:
            info = self.worker.gcs.call("get_actor",
                                        actor_id=self.actor_id)
        except ConnectionLost:
            info = None
        reason = (info or {}).get("death_cause") or "connection lost"
        self.worker._fail_task(
            spec, exc.ActorDiedError(self.actor_id.hex(), reason))


# sentinel: a pooled data-plane socket died mid-request — retry once fresh
_RETRY_FRESH = object()


class CoreWorker:
    """One per process (driver or worker)."""

    def __init__(self, gcs_addr, raylet_addr, mode: str,
                 store_name: str | None = None, spill_dir: str | None = None,
                 worker_id: str | None = None, job_id: int | None = None):
        self.mode = mode                      # "driver" | "worker"
        # tag the process for role-scoped fault-injection rules (weak:
        # in-process test clusters keep the subprocess entrypoint's tag)
        from ray_tpu._private import fault_injection

        fault_injection.set_role(mode, weak=True)
        self.worker_id = worker_id or uuid.uuid4().hex[:16]
        self.stopped = False
        # id mint: random 8-byte process prefix + counter. Ids need
        # uniqueness, not unpredictability, and os.urandom is a syscall
        # (~16µs) paid twice per task on the submit hot path.
        self._id_prefix = os.urandom(8)
        self._id_counter = itertools.count(1)
        self.memory_store = MemoryStore()
        self.reference_counter = ReferenceCounter(
            on_zero=self._on_local_refs_zero)
        self._owned: set[bytes] = set()      # ids this process owns
        self._arg_pins: dict[bytes, int] = {}  # in-flight task-arg pins
        self._deferred_free: set[bytes] = set()
        self._actor_concurrency = FifoSemaphore(1)
        self._func_cache: dict[bytes, object] = {}
        self._sched_queues: dict[tuple, _SchedulingKeyQueue] = {}
        self._actor_queues: dict[bytes, _ActorQueue] = {}
        self._task_futures: dict[bytes, PyFuture] = {}
        self._ref_to_task: dict[bytes, tuple] = {}  # rid -> (spec, queue)
        self._gen_streams: dict[bytes, _GenStream] = {}  # gen_id -> stream
        # rid -> (frame bytes, inlinable?) for small resolved args
        # (invalidated on ref-zero with the other per-object state)
        self._inline_frame_cache: dict[bytes, tuple] = {}
        # executor-side twin: rid -> deserialized value for inlined arg
        # frames. Only IMMUTABLE values enter (numpy arrays are marked
        # read-only first — the store's own zero-copy semantics), so
        # sharing one object across tasks is safe. Objects are immutable
        # by id, so entries never go stale; a size cap bounds memory.
        self._inlined_value_cache: dict[bytes, object] = {}
        # Lineage for object reconstruction (reference:
        # core_worker/object_recovery_manager.h:30 + task_manager.h:93-110
        # lineage pinning): completed normal-task specs are retained, keyed
        # by task_id, while any of their return objects is still referenced,
        # so a sealed-then-lost object can be recomputed by re-executing its
        # creating task. Arg pins are held for the lineage's lifetime.
        self._lineage_specs: dict[bytes, tuple] = {}   # task_id -> (spec, q)
        self._lineage_index: dict[bytes, bytes] = {}   # rid -> task_id
        self._lineage_live: dict[bytes, int] = {}      # task_id -> live rids
        self._lineage_bytes = 0
        self._lineage_order: collections.deque = collections.deque()
        # PullManager-lite admission control (reference: pull_manager.h:48):
        # bounds the total bytes of concurrently in-flight remote pulls.
        self._pull_lock = threading.Condition()
        self._pull_inflight_bytes = 0
        self._lock = threading.RLock()
        # __del__-driven frees are deferred to this queue (GC-reentrancy
        # safety — see _on_local_refs_zero)
        self._free_queue: queue.SimpleQueue = queue.SimpleQueue()
        self._free_thread = threading.Thread(
            target=self._free_loop, daemon=True, name="ref-reaper")
        self._free_thread.start()

        # Actor-side state (populated by become_actor)
        self.actor_id: bytes | None = None
        self._actor_instance = None
        self._actor_spec = None
        self._exec_queue: queue.Queue | None = None
        self._exec_threads: list[threading.Thread] = []
        self._async_loop = None
        self._cancelled: set[bytes] = set()
        self._current_task_id = None
        self._current_task_thread = None
        self._next_seq_to_run: dict[str, int] = {}
        self._seq_cond = threading.Condition()
        self._col_mailbox: dict[tuple, object] = {}
        self._col_cond = threading.Condition()
        # gang fault tolerance (see col_set_epoch / col_poison_local):
        # group -> current incarnation epoch, and group -> poison record
        self._col_epochs: dict[str, int] = {}
        self._col_poison: dict[str, tuple[tuple, str]] = {}
        self._ready = threading.Event()
        # Normal tasks execute serially: the lease under which tasks are
        # pushed accounts for exactly one task's resources at a time
        # (pipelined pushes queue here, hiding RTT, not stacking execution).
        self._normal_exec_lock = threading.Lock()
        # main-thread task loop (serve_task_loop) plumbing
        self._main_jobs: queue.Queue = queue.Queue()
        self._main_loop_running = False
        self._main_loop_started = threading.Event()
        # pooled connections to object owners (borrowed-value fetches)
        self._owner_clients: dict[tuple, RpcClient] = {}
        self._owner_client_lock = threading.Lock()

        # Connect out only after all execution state exists: registering with
        # the raylet makes us leasable, and a task can be pushed the moment
        # that happens.
        # Self-healing: GCS table ops are idempotent, so calls retry
        # across a GCS restart instead of surfacing ConnectionLost to
        # the driver (reference: gcs_rpc_client.h reconnection)
        from ray_tpu._private.protocol import ReconnectingRpcClient

        self.gcs = ReconnectingRpcClient(tuple(gcs_addr),
                                         on_push=self._on_gcs_push)
        self._server = RpcServer(self).start()
        self.addr = self._server.addr
        self.raylet = RpcClient(tuple(raylet_addr), timeout=None)
        reg = self.raylet.call("register_worker", worker_id=self.worker_id,
                               addr=self.addr, pid=os.getpid())
        self.node_id = reg["node_id"]
        # Owner-based object directory (reference:
        # src/ray/object_manager/ownership_based_object_directory.h:1 — the
        # OWNER of an object tracks which nodes hold copies; borrowers and
        # the owner itself resolve locations here, with ZERO GCS round
        # trips on the pull path). _my_node is the snapshot shape handed to
        # owners when this node announces a copy.
        self._my_node = reg.get("node") or {"NodeID": self.node_id}
        self._dir_lock = threading.Lock()
        self._obj_locations: dict[bytes, dict[str, dict]] = {}
        self._obj_sizes: dict[bytes, int] = {}
        self.store = StoreClient(store_name or reg["store_name"],
                                 spill_dir=spill_dir or reg["spill_dir"])
        # provenance leak sweep over this process's store traffic
        # (memory_anatomy; no-op under RAY_TPU_INTERNAL_TELEMETRY=0)
        _ma.start_periodic_sweep(self)
        self.job_id = job_id if job_id is not None else (
            self.gcs.call("next_job_id") if mode == "driver" else 0)
        self._ready.set()

    # ------------------------------------------------------------------ utils

    def _new_id(self) -> bytes:
        """16-byte unique id (process-random prefix + counter) — the id
        mint for tasks/objects/actors; see __init__ for why not urandom."""
        return self._id_prefix + next(self._id_counter).to_bytes(8, "big")

    def _on_gcs_push(self, payload):
        pass  # subscriptions are registered lazily where needed

    def _strip_spec(self, spec: dict) -> dict:
        for k in spec:
            if k[0] == "_":
                return {k: v for k, v in spec.items()
                        if not k.startswith("_")}
        return spec   # nothing local: ship as-is (no dict rebuild)

    def _cluster_cpu_total(self) -> float:
        """Sum of CPU across alive nodes, cached for 10 s (feeds the
        per-key lease soft cap — growth decisions tolerate staleness)."""
        now = time.monotonic()
        cached = getattr(self, "_cluster_cpu_cache", None)
        if cached is not None and now - cached[0] < 10.0:
            return cached[1]
        total = 0.0
        try:
            for n in self.gcs.call("get_nodes", timeout=5.0):
                if n.get("Alive"):
                    total += float(n.get("Resources", {}).get("CPU", 0))
        except Exception:
            if cached is not None:
                return cached[1]
        self._cluster_cpu_cache = (now, total)
        return total

    # -------------------------------------------------------- runtime envs

    def _normalize_runtime_env(self, runtime_env: dict | None):
        """Driver-side normalization: local paths (working_dir, py_modules
        dirs, pip sdist dirs/wheel files) are packaged and uploaded to GCS
        KV once, so the spec carries only content keys that any node can
        materialize (reference: runtime_env/packaging.py). Without this,
        a spec naming /home/me/mylib would only work on nodes sharing the
        driver's filesystem. Uploads are content-addressed AND memoized
        per local path for 10 s, so a submit loop doesn't re-zip the tree
        per task."""
        if not runtime_env:
            return None
        runtime_env = dict(runtime_env)
        wd = runtime_env.get("working_dir")
        if wd and not wd.startswith("pkg-"):
            runtime_env["working_dir"] = self._upload_env_path(wd)
        if runtime_env.get("py_modules"):
            # keep_name: a py_module's directory name IS its import name
            runtime_env["py_modules"] = [
                self._upload_env_path(m, keep_name=True)
                if os.path.exists(str(m)) else m
                for m in runtime_env["py_modules"]]
        if runtime_env.get("pip"):
            runtime_env["pip"] = [
                self._upload_env_path(r) if os.path.exists(str(r)) else r
                for r in runtime_env["pip"]]
        return runtime_env

    def _upload_env_path(self, path: str, keep_name: bool = False) -> str:
        path = os.path.abspath(str(path))
        cache = getattr(self, "_env_upload_cache", None)
        if cache is None:
            cache = self._env_upload_cache = {}
        hit = cache.get((path, keep_name))
        if hit is not None and time.monotonic() - hit[0] < 10.0:
            return hit[1]
        if os.path.isdir(path):
            from ray_tpu._private.runtime_env import upload_working_dir

            key = upload_working_dir(self.gcs.call, path)
            if keep_name:
                key = f"{key}/{os.path.basename(path)}"
        else:
            with open(path, "rb") as f:
                data = f.read()
            key = "blob-" + hashlib.sha256(data).hexdigest()[:24]
            if self.gcs.call("kv_get", ns="packages",
                             key=key.encode()) is None:
                self.gcs.call("kv_put", ns="packages", key=key.encode(),
                              value=data)
            key = f"{key}/{os.path.basename(path)}"
        cache[(path, keep_name)] = (time.monotonic(), key)
        return key

    def _apply_runtime_env(self, runtime_env: dict | None):
        """Make `runtime_env` current in THIS process before running its
        task: pip/py_modules site dirs prepend sys.path, env_vars overlay
        os.environ, working_dir materializes and becomes cwd. A worker
        keeps its env between tasks (the scheduling key separates envs,
        so swaps happen only when the raylet reuses an idle worker across
        keys); swapping reverts the previous overlay (incl. cwd) first.
        Failure-safe: all fallible resolution happens BEFORE any state
        mutates, and a failed apply leaves the worker env-less (key None)
        so the next task re-applies from scratch rather than trusting a
        half-applied overlay. Design delta vs the reference's
        dedicated-worker-per-env: modules already imported from a
        previous env stay cached in sys.modules."""
        import sys as _sys

        key = _freeze(runtime_env)
        if key == getattr(self, "_env_applied_key", None):
            return
        # ---- resolve the NEW env fully before touching process state
        paths, uri, cache = [], None, None
        runtime_env = runtime_env or {}
        pip = runtime_env.get("pip")
        py_modules = runtime_env.get("py_modules")
        if pip or py_modules:
            from ray_tpu._private.runtime_env_pip import node_env_cache

            cache = node_env_cache()
            pip = [self._localize_env_entry(e) for e in (pip or [])]
            py_modules = [self._localize_env_entry(m)
                          for m in (py_modules or [])]
            info = cache.get_or_create(pip=pip, py_modules=py_modules)
            uri = info["uri"]
            paths.extend(info["site_dirs"])
        wd = runtime_env.get("working_dir")
        wd_path = None
        if wd:
            wd_path = self._localize_env_entry(wd)
            paths.append(wd_path)
        # ---- point of no return: revert old overlay, install new
        for p in getattr(self, "_env_paths", ()):
            try:
                _sys.path.remove(p)
            except ValueError:
                pass
        for k, old in getattr(self, "_env_vars_prev", {}).items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        if getattr(self, "_env_orig_cwd", None):
            try:
                os.chdir(self._env_orig_cwd)
            except OSError:
                pass
            self._env_orig_cwd = None
        prev_uri = getattr(self, "_env_uri", None)
        self._env_paths = ()
        self._env_vars_prev = {}
        self._env_uri = None
        self._env_applied_key = None
        vars_prev = {}
        for k, v in (runtime_env.get("env_vars") or {}).items():
            vars_prev[k] = os.environ.get(k)
            os.environ[str(k)] = str(v)
        if wd_path:
            try:
                self._env_orig_cwd = os.getcwd()
            except OSError:
                self._env_orig_cwd = None
            try:
                os.chdir(wd_path)
            except OSError:
                pass
        _sys.path[:0] = paths
        if uri is not None:
            cache.acquire(uri)
        if prev_uri:
            from ray_tpu._private.runtime_env_pip import node_env_cache

            node_env_cache().release(prev_uri)
        self._env_paths = paths
        self._env_vars_prev = vars_prev
        self._env_uri = uri
        self._env_applied_key = key

    def _localize_env_entry(self, entry: str) -> str:
        """Turn a runtime-env entry into a path valid on THIS node:
        content keys (pkg-/blob-, uploaded by the driver's normalization)
        materialize from GCS KV into the node's package cache; anything
        else (package names, URLs, paths that exist locally) passes
        through."""
        if not isinstance(entry, str):
            return entry
        dest_root = os.path.join("/tmp/ray_tpu", "pkg_cache")
        if entry.startswith("pkg-"):
            from ray_tpu._private.runtime_env import materialize_working_dir

            os.makedirs(dest_root, exist_ok=True)
            key, _, name = entry.partition("/")
            extracted = materialize_working_dir(self.gcs.call, key,
                                                dest_root)
            if not name:
                return extracted
            # "pkg-<hash>/<name>": the packaged tree must surface under
            # its ORIGINAL directory name (a py_module's dir name is its
            # import name; zipping strips it)
            named_root = os.path.join(dest_root, key + ".named")
            target = os.path.join(named_root, name)
            if not os.path.exists(target):
                os.makedirs(named_root, exist_ok=True)
                try:
                    os.symlink(extracted, target)
                except OSError:
                    pass   # raced another worker: target now exists
            return target
        if entry.startswith("blob-"):
            # "blob-<hash>/<basename>": a single file (e.g. a wheel) —
            # materialized under its REAL basename because pip parses
            # name/version out of wheel filenames
            key, _, basename = entry.partition("/")
            blob_dir = os.path.join(dest_root, key)
            os.makedirs(blob_dir, exist_ok=True)
            path = os.path.join(blob_dir, basename or "blob.bin")
            if not os.path.exists(path):
                data = self.gcs.call("kv_get", ns="packages",
                                     key=key.encode())
                if data is None:
                    raise ValueError(f"package {entry!r} not found in GCS")
                tmp = path + f".tmp{os.getpid()}"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, path)
            return path
        return entry

    def _worker_death_error(self, worker_id: str):
        """Error for a task whose executing worker died. The raylet records
        OOM kills in GCS KV *before* delivering SIGKILL (raylet.py
        _on_memory_pressure), so by the time the owner observes the dropped
        connection the verdict is already readable — an OOM death surfaces
        as a retriable OutOfMemoryError naming the culprit, anything else
        as WorkerCrashedError."""
        try:
            blob = self.gcs.call("kv_get", ns="oom_kill",
                                 key=worker_id.encode(), timeout=5.0)
        except Exception:
            blob = None
        if blob:
            return exc.OutOfMemoryError(
                blob.decode() if isinstance(blob, bytes) else str(blob))
        return exc.WorkerCrashedError(
            f"worker {worker_id} died executing task")

    # ---------------------------------------------------------------- put/get

    def put(self, value) -> ObjectRef:
        # parts path: out-of-band buffers copy straight into the shm
        # segment (or stream to the spill file) — no assembled
        # intermediate frame (one full copy saved per big array)
        parts = ser.serialize_parts(value)
        object_id = self._new_id()
        with _ma.default_tag("task_arg", owner=self.worker_id):
            size = self.store.put_parts(object_id, parts)
        # we own it: record the location in OUR directory — no RPC at all
        self._loc_add(object_id, self._my_node, size)
        self._owned.add(object_id)
        ref = ObjectRef(object_id, self.addr, self)
        return ref

    # ---- distributed release (simplified owner-based protocol; reference:
    # src/ray/core_worker/reference_count.h). The owner frees an object when
    # its own local Python refs hit zero and no in-flight task of this
    # process uses it as an argument. v1 limitation vs the reference's full
    # borrower protocol: a remote process that stashes a deserialized ref
    # beyond its task's lifetime does not extend the object's life.

    def _on_local_refs_zero(self, object_id: bytes):
        """Called from ObjectRef.__del__ — which the GC can run at ANY
        bytecode boundary, including while this thread holds the memory
        store lock or self._lock. Taking any lock here can self-deadlock
        (observed: GC fired inside submit_task's memory_store.entry() and
        the free path re-acquired the store's non-reentrant lock). So:
        only enqueue; the reaper thread does the real work."""
        if self.stopped:
            return
        self._free_queue.put(object_id)

    def _free_loop(self):
        while True:
            object_id = self._free_queue.get()
            if object_id is None or self.stopped:
                return
            try:
                with self._lock:
                    if self._arg_pins.get(object_id):
                        self._deferred_free.add(object_id)
                        continue
                self._free_object(object_id)
            except Exception:
                pass

    def _free_object(self, object_id: bytes):
        self.memory_store.free(object_id)
        to_unpin = None
        with self._lock:
            task_entry = self._ref_to_task.pop(object_id, None)
            gen_stream = self._gen_streams.pop(object_id, None)
            self._inline_frame_cache.pop(object_id, None)
            owned = object_id in self._owned
            self._owned.discard(object_id)
            tid = self._lineage_index.pop(object_id, None)
            if tid is not None:
                self._lineage_live[tid] -= 1
                if self._lineage_live[tid] <= 0:
                    to_unpin = self._drop_lineage_locked(tid)
        if to_unpin is not None:
            self._unpin_args(to_unpin)
        if gen_stream is not None:
            # The generator itself is gone: release stream items nobody
            # ever took a Python ref on (closed early / dropped
            # uniterated) — their refcount is 0 so on_zero can never fire
            # for them. Items the consumer DID take refs on free through
            # the normal refcount path when those refs die. If the
            # producer is still running, cancel it here (we are on the
            # reaper thread, where blocking pushes are allowed —
            # ObjectRefGenerator.__del__ itself must never touch locks
            # or the network, matching _on_local_refs_zero's contract).
            with gen_stream.cond:
                unfinished = (gen_stream.total is None
                              and gen_stream.error is None)
                gen_stream.closed = True
                item_ids = list(gen_stream.items.values())
                gen_stream.cond.notify_all()
            if unfinished and task_entry is not None:
                self._cancel_spec(*task_entry, force=False)
            for rid in item_ids:
                if self.reference_counter.count(rid) == 0:
                    self._free_object(rid)
        if owned:
            # we are the directory: hand the GCS the holder list so it can
            # fan the delete out to those raylets (node connections live
            # there), then drop our entries
            with self._dir_lock:
                holders = list(self._obj_locations.pop(object_id, {}))
                size = self._obj_sizes.pop(object_id, None)   # always pop
                had_copy = bool(holders) or size is not None
            if not had_copy:
                return   # inline-only result: nothing anywhere to delete,
                         # and the per-task free push + GCS handler round
                         # is pure hot-path overhead (profiled round 5)
            try:
                self.gcs.push("free_objects", object_ids=[object_id],
                              locations={object_id: holders})
            except Exception:
                # the free is one-way and now LOST — the object strands
                # on its holder nodes until the leak sweep names it
                _ma.LEDGER.note_free_dropped("owner_push")

    # ------------------------------------------------ lineage reconstruction
    # Reference: object_recovery_manager.h:30 (re-execute the creating task
    # when all copies are lost) with task_manager.h-style lineage pinning.

    def _retain_lineage(self, spec: dict):
        from ray_tpu._private.config import get_config

        cap = int(get_config("max_lineage_bytes"))
        tid = spec["task_id"]
        cost = len(spec.get("args", b"")) + 512
        retained = False
        evicted: list[dict] = []
        with self._lock:
            if tid in self._lineage_specs:     # reconstruction round-trip:
                return                         # already retained, pins held
            live = [r for r in spec["return_ids"] if r in self._owned]
            if (live and spec.get("_queue") is not None and cost <= cap
                    and spec.get("reconstructions_left", 0) > 0):
                self._lineage_specs[tid] = (spec, spec["_queue"])
                self._lineage_live[tid] = len(live)
                for rid in live:
                    self._lineage_index[rid] = tid
                self._lineage_bytes += cost
                self._lineage_order.append(tid)
                retained = True
                while (self._lineage_bytes > cap
                        and len(self._lineage_order) > 1):
                    old_tid = self._lineage_order.popleft()
                    dropped = self._drop_lineage_locked(old_tid)
                    if dropped is not None:
                        evicted.append(dropped)
                # Compact stale tids (dropped via _free_object) so the
                # deque stays O(live lineage), not O(tasks ever submitted).
                if len(self._lineage_order) > 2 * len(self._lineage_specs) + 64:
                    self._lineage_order = collections.deque(
                        t for t in self._lineage_order
                        if t in self._lineage_specs)
        if not retained:
            self._unpin_args(spec)
        for old in evicted:
            self._unpin_args(old)

    def _drop_lineage_locked(self, tid: bytes):
        """Remove a lineage spec (caller holds self._lock). Returns the spec
        whose arg pins should be released, or None."""
        entry = self._lineage_specs.pop(tid, None)
        self._lineage_live.pop(tid, None)
        if entry is None:
            return None
        spec, _q = entry
        for rid in spec["return_ids"]:
            if self._lineage_index.get(rid) == tid:
                del self._lineage_index[rid]
        self._lineage_bytes -= len(spec.get("args", b"")) + 512
        return spec

    def _maybe_reconstruct(self, object_id: bytes) -> bool:
        """If we own lineage for a lost object, re-submit its creating task.
        Returns True when a reconstruction is in flight (caller should keep
        polling), False when the loss is unrecoverable."""
        with self._lock:
            tid = self._lineage_index.get(object_id)
            if tid is None:
                return False
            spec, q = self._lineage_specs[tid]
            if any(rid in self._ref_to_task for rid in spec["return_ids"]):
                return True    # a reconstruction is already in flight
            if spec.get("reconstructions_left", 0) <= 0:
                return False
            spec["reconstructions_left"] -= 1
            for rid in spec["return_ids"]:
                self._ref_to_task[rid] = (spec, q)
        q.submit(spec)
        return True

    def _pin_args(self, spec: dict, args=None, kwargs=None, *, refs=None,
                  skip=None):
        if refs is None:
            if not args and not kwargs:
                return
            refs = ser.contained_refs((args, kwargs))
        ids = [r.id for r in refs
               if skip is None or r.id not in skip]
        if not ids:
            return
        spec["_arg_ids"] = ids   # stripped before the wire (leading _)
        with self._lock:
            for oid in ids:
                self._arg_pins[oid] = self._arg_pins.get(oid, 0) + 1

    def _unpin_args(self, spec: dict):
        to_free = []
        with self._lock:
            for oid in spec.get("_arg_ids", ()):
                n = self._arg_pins.get(oid, 0) - 1
                if n <= 0:
                    self._arg_pins.pop(oid, None)
                    if oid in self._deferred_free and \
                            self.reference_counter.count(oid) == 0:
                        self._deferred_free.discard(oid)
                        to_free.append(oid)
                else:
                    self._arg_pins[oid] = n
        for oid in to_free:
            self._free_object(oid)

    def get(self, refs, timeout: float | None = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        deadline = None if timeout is None else time.time() + timeout
        out = []
        for ref in refs:
            remaining = None if deadline is None else max(
                0.0, deadline - time.time())
            value, raised = self._get_one(ref, remaining)
            if raised and isinstance(value, BaseException):
                raise value
            out.append(value)
        return out[0] if single else out

    def _get_one(self, ref: ObjectRef, timeout: float | None):
        # Only payloads shipped by serialize_error (the task raised) re-raise
        # at get(); a task returning an exception object is a normal value
        # (reference parity: only RayTaskError wrappers re-raise).
        data = self._fetch_bytes(ref, timeout)
        value, meta = ser.deserialize(data, self, with_meta=True)
        return value, meta.get("raised", False)

    def _fetch_bytes(self, ref: ObjectRef, timeout: float | None):
        deadline = None if timeout is None else time.time() + timeout
        poll = 0.001
        while True:
            # 1. owner memory store (we own it or borrowed+cached)
            data = self.memory_store.get_nowait(ref.id)
            if data is not None:
                return data
            # While OUR producing task is still in flight, nothing below
            # can hit: the result announces through the task reply (inline
            # → memory store; stored → directory record), so probing the
            # shm store (a C-lock + spill-stat round, ~100µs on the dev
            # box) or the directory every poll is pure hot-path waste.
            # Skip straight to the wait; the reply or a poll tick re-runs
            # the full path once the task is done.
            in_flight = ref.id in self._ref_to_task
            if not in_flight:
                # 2. local shm store
                buf = self.store.get(ref.id)
                if buf is not None:
                    try:
                        if hasattr(buf, "view"):
                            # spill-backed host buffer (possibly an
                            # mmap): zero-copy view, safe past release
                            return buf.view()
                        return buf.to_bytes()
                    finally:
                        buf.release()
            # 3. resolve through the OWNER-BASED directory — zero GCS calls
            # (reference: ownership_based_object_directory.h).
            we_own = not ref.owner_addr or tuple(ref.owner_addr) == self.addr
            if in_flight:
                pass          # wait below; the reply resolves everything
            elif we_own:
                # we are the owner: our table is the directory
                nodes, created_size = self._loc_snapshot(ref.id)
                for node in nodes:
                    if node["NodeID"] == self.node_id:
                        continue
                    data = self._pull_remote(ref.id, node)
                    if data is not None:
                        return data
                    # the copy is gone with its node — drop the location
                    self._loc_remove(ref.id, node["NodeID"])
                # Sealed once, zero copies left, no producing task in
                # flight → recovery is OUR job (reference:
                # ObjectRecoveryManager runs in the owner's core worker):
                # re-execute the creating task if we hold lineage, else
                # the loss is permanent.
                remote = [n for n in nodes
                          if n["NodeID"] != self.node_id]
                if created_size and not remote \
                        and ref.id not in self._ref_to_task:
                    if not self._maybe_reconstruct(ref.id):
                        raise exc.ObjectLostError(ref.hex())
            else:
                # borrower: ONE owner round trip resolves value (inline),
                # holder nodes ("at" → data-plane pull inside _ask_owner),
                # pending, or lost.
                data = self._ask_owner(ref, deadline)
                if data is not None:
                    # borrower-side cache: repeat gets of this ref skip the
                    # owner round trip. Small values ride the heap memory
                    # store (freed by the same ref-zero path as owned
                    # entries); big ones go to the shm store like remote
                    # pulls, so they stay under shm accounting.
                    from ray_tpu._private.config import get_config

                    if len(data) <= int(get_config(
                            "inline_object_max_size_bytes")):
                        self.memory_store.put(ref.id, data)
                        # put-then-check closes the race with the ref
                        # reaper: if the last local ref died first, the
                        # reaper's free already ran — undo our insert
                        if self.reference_counter.count(ref.id) == 0:
                            self.memory_store.free(ref.id)
                    elif not self.store.contains(ref.id):
                        # (an "at" pull already cached+announced; don't
                        # double-insert)
                        self._cache_local(ref.id, data, ref.owner_addr)
                    return data
            if deadline is not None and time.time() > deadline:
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for {ref.hex()}")
            # The object may simply not be created yet (pending task): if we
            # are the owner, wait on the memory-store future.
            entry = self.memory_store.entry(ref.id)
            wait_t = poll if deadline is None else min(
                poll, max(0.0, deadline - time.time()))
            entry.event.wait(wait_t)
            poll = min(poll * 2, 0.1)

    def _pull_remote(self, object_id: bytes, node_snapshot: dict,
                     owner_addr=None):
        """Chunked node-to-node pull with admission control.

        Reference: PullManager (pull_manager.h:48) bounds in-flight pull
        bytes; PushManager (push_manager.h:29) moves objects as chunks. A
        large object crosses the network in `object_transfer_chunk_bytes`
        frames instead of one pickle frame, and the total bytes being
        pulled concurrently by this worker is capped. owner_addr names the
        object's owner so the cached copy gets announced to its directory
        (None/self → we are the owner)."""
        from ray_tpu._private.config import get_config

        host = node_snapshot["NodeManagerAddress"]
        chunk = int(get_config("object_transfer_chunk_bytes"))
        data = None
        # fast path: the remote raylet's native (C++) data server streams
        # the bytes straight out of its shm segment, GIL-free
        data_port = node_snapshot.get("object_data_port")
        cached = False
        if data_port:
            data, cached = self._pull_native(object_id, (host, data_port),
                                             chunk, owner_addr)
        if data is None:
            data = self._pull_rpc(
                object_id, (host, node_snapshot["NodeManagerPort"]), chunk)
        if data is None:
            return None
        # Cache locally for future gets (reference: pulled chunks land in
        # local plasma) — unless the native path already received the
        # bytes straight into the store and announced the location.
        if not cached:
            self._cache_local(object_id, data, owner_addr)
        return data

    def _cache_local(self, object_id: bytes, data: bytes, owner_addr=None):
        """Cache fetched bytes in the local shm store and register the new
        location with the owner (best-effort; a full store skips the
        cache)."""
        try:
            self.store.put(object_id, data)
            self._announce_copy(object_id, len(data), owner_addr)
        except Exception:
            pass

    def _data_sock_checkout(self, addr, fresh: bool = False):
        """Persistent-connection pool for the native data plane (one
        in-flight request per socket; concurrent pulls each check out
        their own). fresh=True bypasses AND drains the pool for this addr
        — used by the retry after a pooled socket died, since its siblings
        are likely dead too (server restart)."""
        import socket as _socket

        lock = self.__dict__.setdefault("_data_sock_lock",
                                        threading.Lock())
        pool = self.__dict__.setdefault("_data_sock_pool", {})
        with lock:
            socks = pool.get(addr)
            if fresh and socks:
                for s in socks:
                    try:
                        s.close()
                    except OSError:
                        pass
                socks.clear()
            elif socks:
                return socks.pop(), True
        # short connect probe: an unreachable (firewalled) data port must
        # fail over to the RPC plane in seconds, not minutes
        sock = _socket.create_connection(addr, timeout=5.0)
        sock.settimeout(120.0)
        sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        return sock, False

    def _data_sock_checkin(self, addr, sock):
        with self._data_sock_lock:
            socks = self._data_sock_pool.setdefault(addr, [])
            if len(socks) < 4:
                socks.append(sock)
                return
        try:
            sock.close()
        except OSError:
            pass

    def _pull_native(self, object_id: bytes, addr, chunk: int,
                     owner_addr=None):
        """Fetch via the remote store's C++ data server
        (src/store/data_server.cc). Protocol: 32-byte request (id, offset,
        max_len) -> 16-byte header (total_size, payload_len) + payload.
        A pooled (possibly stale) connection gets one retry on a fresh
        socket before giving up."""
        result = self._pull_native_once(object_id, addr, chunk, owner_addr)
        if result is _RETRY_FRESH:
            result = self._pull_native_once(object_id, addr, chunk,
                                            owner_addr, fresh=True)
        if result is _RETRY_FRESH or result is None:
            return None, False
        return result   # (data, cached_in_local_store)

    def _pull_native_once(self, object_id: bytes, addr, chunk: int,
                          owner_addr=None, fresh: bool = False):
        import struct as _struct

        missing = (1 << 64) - 1
        admitted = 0
        sock = None
        pooled = False
        ok = False
        data = None       # heap fallback buffer
        shm_view = None   # zero-copy receive target in the local store
        try:
            sock, pooled = self._data_sock_checkout(addr, fresh=fresh)

            def read_into(view):
                got = 0
                n = len(view)
                while got < n:
                    r = sock.recv_into(view[got:], n - got)
                    if r == 0:
                        raise ConnectionError("data server closed")
                    got += r

            header = bytearray(16)
            size = None
            offset = 0
            while size is None or offset < size:
                sock.sendall(object_id + _struct.pack("<QQ", offset, chunk))
                read_into(memoryview(header))
                total, n = _struct.unpack("<QQ", header)
                if total == missing:
                    ok = True            # healthy conversation, no object
                    if shm_view is not None:
                        # a mid-pull eviction remotely must not leak the
                        # local create reservation (an unsealed entry is
                        # never evictable and poisons the id forever)
                        self.store.abort(object_id)
                    return None
                if size is None:
                    size = total
                    admitted = size
                    self._admit_pull(size)
                    # receive STRAIGHT into the local store's segment —
                    # the old path recv'd into a heap bytearray and then
                    # copied into shm (VERDICT round-3 weak #7). Fall
                    # back to heap when the store is full (spill path)
                    # or the object is already local.
                    try:
                        buf = self.store.create(object_id, size)
                        if buf is not None:
                            shm_view = memoryview(buf).cast("B")
                    except Exception:
                        shm_view = None
                    if shm_view is None:
                        data = bytearray(size)
                    if size == 0:
                        break
                if n == 0:
                    ok = True
                    if shm_view is not None:
                        self.store.abort(object_id)
                    return None          # evicted/shrunk mid-pull
                target = shm_view if shm_view is not None else \
                    memoryview(data)
                read_into(target[offset:offset + n])
                offset += n
            ok = True
            if shm_view is not None:
                # copy out BEFORE seal: sealing makes the entry
                # immediately evictable, and losing a fully-received
                # object to a concurrent eviction would force a full
                # re-download over the slow RPC plane
                payload = bytes(shm_view)
                self.store.seal(object_id)
                self._announce_copy(object_id, size, owner_addr)
                return payload, True
            return (bytes(data), False) if data is not None else None
        except Exception:
            if shm_view is not None:
                try:
                    self.store.abort(object_id)
                except Exception:
                    pass
            # a dead pooled socket deserves one retry on a fresh one
            return _RETRY_FRESH if pooled else None
        finally:
            if admitted:
                self._release_pull(admitted)
            if sock is not None:
                if ok:
                    self._data_sock_checkin(addr, sock)
                else:
                    try:
                        sock.close()
                    except OSError:
                        pass

    def _pull_rpc(self, object_id: bytes, chunk_addr, chunk: int):
        """Fallback chunk fetch over the Python RPC plane. Chunk reads
        are pure (retry-safe), so transient connection loss or a timed-
        out chunk reconnects and resumes AT THE CURRENT OFFSET under the
        unified policy instead of abandoning the whole pull (and with it
        possibly the object's only reachable copy)."""
        from ray_tpu._private.retry import RetryPolicy

        # few, fast attempts: a holder that refuses twice is usually
        # DEAD (node removal), and the caller already falls back to
        # other replicas / the owner poll — don't stall that failover
        policy = RetryPolicy(max_attempts=3, base_backoff_s=0.05,
                             max_backoff_s=0.5, deadline_s=240.0,
                             attempt_timeout_s=120.0)
        clientbox = [None]

        def fetch(offset, attempt_timeout):
            if clientbox[0] is None or clientbox[0].closed:
                # retry=1: re-dialing a refused connect is the POLICY's
                # job here; stacking the constructor's own retry loop
                # under it would triple every failover pause
                clientbox[0] = RpcClient(chunk_addr, timeout=120.0,
                                         retry=1)
            return clientbox[0].call("fetch_object_chunk",
                                     object_id=object_id, offset=offset,
                                     length=chunk, timeout=attempt_timeout)

        admitted = 0
        try:
            first = policy.run(lambda t: fetch(0, t),
                               method="fetch_object_chunk",
                               retry_on=(ConnectionLost, TimeoutError))
            if first is None:
                return None
            size = first["size"]
            admitted = size
            self._admit_pull(size)
            data = bytearray(first["data"])
            while len(data) < size:
                part = policy.run(lambda t: fetch(len(data), t),
                                  method="fetch_object_chunk",
                                  retry_on=(ConnectionLost, TimeoutError))
                if part is None:   # evicted mid-pull
                    return None
                data += part["data"]
            return bytes(data)
        except (ConnectionLost, Exception):  # noqa: BLE001
            return None
        finally:
            if admitted:
                self._release_pull(admitted)
            if clientbox[0] is not None:
                clientbox[0].close()

    def _admit_pull(self, nbytes: int):
        """Block until the pull fits the in-flight budget (always admit when
        nothing else is in flight, so an object larger than the budget can
        still be fetched — same escape hatch as the reference's PullManager)."""
        from ray_tpu._private.config import get_config

        cap = int(get_config("pull_max_inflight_bytes"))
        with self._pull_lock:
            while (self._pull_inflight_bytes > 0
                    and self._pull_inflight_bytes + nbytes > cap):
                self._pull_lock.wait(0.5)
            self._pull_inflight_bytes += nbytes

    def _release_pull(self, nbytes: int):
        with self._pull_lock:
            self._pull_inflight_bytes = max(
                0, self._pull_inflight_bytes - nbytes)
            self._pull_lock.notify_all()

    def _owner_client(self, addr: tuple) -> RpcClient:
        """Pooled connection to an object owner (one multiplexed client per
        owner; a fresh TCP connect per borrowed get was the dominant cost
        of ref-arg tasks in ray_perf). The connect happens OUTSIDE the pool
        lock so one unreachable owner can't stall fetches to healthy ones;
        a losing racer's client is closed, the winner's pooled."""
        with self._owner_client_lock:
            client = self._owner_clients.get(addr)
            if client is not None and not client.closed:
                # LRU reorder: eviction takes the front, so keep hot
                # clients at the back
                self._owner_clients.pop(addr)
                self._owner_clients[addr] = client
                return client
        fresh = RpcClient(addr, timeout=30.0, retry=1)
        with self._owner_client_lock:
            current = self._owner_clients.get(addr)
            if current is not None and not current.closed:
                winner = current
            else:
                # bounded pool: evict the LEAST-RECENTLY-USED entry beyond
                # the cap (checkouts reorder to the back). An evicted
                # client with calls still in flight is left open — its
                # reader thread ends with the connection; closing it would
                # abort healthy calls.
                while len(self._owner_clients) >= 16:
                    oldest = next(iter(self._owner_clients))
                    old = self._owner_clients.pop(oldest)
                    if not old._pending:
                        try:
                            old.close()
                        except Exception:
                            pass
                self._owner_clients[addr] = fresh
                return fresh
        try:
            fresh.close()
        except Exception:
            pass
        return winner

    def _drop_owner_client(self, addr: tuple, client: RpcClient):
        """Evict `client` from the pool — identity-checked, so a healthy
        replacement pooled by another thread is never closed by mistake."""
        with self._owner_client_lock:
            if self._owner_clients.get(addr) is client:
                self._owner_clients.pop(addr, None)
        try:
            client.close()
        except Exception:
            pass

    def _ask_owner(self, ref: ObjectRef, deadline):
        addr = tuple(ref.owner_addr)
        # one retry on a fresh connection: ConnectionLost/timeouts on a
        # POOLED client usually mean the cached socket went stale (owner
        # restart, idle NAT drop), not that the object is gone
        for attempt in range(2):
            try:
                client = self._owner_client(addr)
            except ConnectionLost:
                if attempt == 0:
                    continue
                raise exc.ObjectLostError(ref.hex()) from None
            try:
                reply = client.call("get_owned_value", object_id=ref.id,
                                    timeout=6.0)
                if isinstance(reply, dict) and "status" in reply:
                    if reply["status"] == "lost":
                        raise exc.ObjectLostError(ref.hex())
                    if reply["status"] == "at":
                        # big value: pull over the data plane from a holder
                        # node instead of this pickle channel
                        for node in reply.get("nodes", ()):
                            if node["NodeID"] == self.node_id:
                                # our own cached copy is gone (local store
                                # already missed before we got here) —
                                # retract it or the owner's directory never
                                # drains and lost-detection never fires
                                try:
                                    client.push("object_location_removed",
                                                object_id=ref.id,
                                                node_id=node["NodeID"])
                                except Exception:
                                    pass
                                continue
                            data = self._pull_remote(ref.id, node,
                                                     owner_addr=addr)
                            if data is not None:
                                return data
                            # stale location (holder died): tell the owner
                            try:
                                client.push("object_location_removed",
                                            object_id=ref.id,
                                            node_id=node["NodeID"])
                            except Exception:
                                pass
                        return None   # caller keeps polling; owner recovers
                    return reply.get("data")
                return reply
            except TimeoutError:
                # Possibly half-open: evict from the pool NOW (the next
                # fetch reconnects within one round), but only CLOSE the
                # socket if no other thread has calls in flight on it —
                # closing would abort their healthy calls; an orphaned
                # client dies with its connection.
                with self._owner_client_lock:
                    if self._owner_clients.get(addr) is client:
                        self._owner_clients.pop(addr, None)
                if not client._pending:
                    try:
                        client.close()
                    except Exception:
                        pass
                return None
            except ConnectionLost:
                self._drop_owner_client(addr, client)
                if attempt == 0:
                    continue
                raise exc.ObjectLostError(ref.hex()) from None
        return None

    def rpc_profile_events(self, conn):
        from ray_tpu._private import profiling

        # drop marker included: a merged timeline must surface ring
        # eviction instead of presenting the window as complete
        return profiling.snapshot(with_drop_marker=True)

    def rpc_trace_spans(self, conn):
        from ray_tpu.util import tracing

        return tracing.local_spans(with_drop_marker=True)

    def rpc_metrics_snapshot(self, conn):
        from ray_tpu.util import metrics

        return metrics.registry_snapshot()

    def rpc_events_snapshot(self, conn):
        return _events.snapshot()

    def rpc_step_records(self, conn):
        """This process's step-anatomy export (steps + activities +
        drop counts) for summarize_steps()'s cluster fan-out."""
        from ray_tpu.parallel import step_anatomy

        return [step_anatomy.local_records()]

    def rpc_blackbox_snapshot(self, conn):
        """This process's flight-recorder window (recent spans/events/
        steps/metrics) for a cluster black-box dump."""
        from ray_tpu._private import flight_recorder

        snap = flight_recorder.local_snapshot()
        return [snap] if snap else []

    def rpc_memory_snapshot(self, conn):
        """This process's memory-anatomy ledger (sweep + snapshot) for
        summarize_memory()'s cluster fan-out."""
        snap = _ma.local_snapshot(top_k=10, window_s=None)
        snap["node"] = self.node_id
        return [snap]

    # ------------------------------------------- owner-based object directory
    # Reference: ownership_based_object_directory.h:1 — the owning worker is
    # the source of truth for which nodes hold copies of its objects. Nodes
    # that create a copy (task return, pull-cache) announce to the OWNER;
    # readers resolve through the owner. The GCS keeps no per-get role.

    def _loc_add(self, object_id: bytes, node: dict, size: int = 0):
        with self._dir_lock:
            self._obj_locations.setdefault(
                object_id, {})[node["NodeID"]] = dict(node)
            if size:
                self._obj_sizes[object_id] = size

    def _loc_remove(self, object_id: bytes, node_id: str):
        with self._dir_lock:
            locs = self._obj_locations.get(object_id)
            if locs:
                locs.pop(node_id, None)

    def _loc_snapshot(self, object_id: bytes):
        """(nodes, size) for an owned object — size>0 means a copy was
        sealed somewhere at some point (the was-created signal that arms
        lost-object detection once nodes drains to empty)."""
        with self._dir_lock:
            nodes = [dict(n)
                     for n in self._obj_locations.get(object_id, {}).values()]
            return nodes, self._obj_sizes.get(object_id, 0)

    def _announce_copy(self, object_id: bytes, size: int, owner_addr):
        """This node now holds a sealed copy: register it with the object's
        owner (ourselves → table write; remote → one-way push)."""
        if not owner_addr or tuple(owner_addr) == self.addr:
            self._loc_add(object_id, self._my_node, size)
            return
        try:
            self._owner_client(tuple(owner_addr)).push(
                "object_location_added", object_id=object_id,
                node=self._my_node, size=size)
        except Exception:
            pass   # owner gone: the copy is orphaned; raylet LRU reclaims

    def rpc_object_location_added(self, conn, object_id: bytes, node: dict,
                                  size: int = 0):
        self._loc_add(object_id, node, size)

    def rpc_object_location_removed(self, conn, object_id: bytes,
                                    node_id: str):
        self._loc_remove(object_id, node_id)

    def rpc_locate_object(self, conn, object_id: bytes):
        """Non-blocking readiness+location probe (wait()/_is_ready path).
        INLINE: dict lookups and a shm-index probe only."""
        ready = (self.memory_store.contains_resolved(object_id)
                 or self.store.contains(object_id))
        nodes, size = self._loc_snapshot(object_id)
        return {"ready": ready or bool(nodes), "nodes": nodes, "size": size}

    def rpc_get_owned_value(self, conn, object_id: bytes):
        """Serve a value we own to a borrower. Blocks briefly if the task
        producing it hasn't finished. Small values ride the reply inline;
        big ones return the holder nodes ("at") so the borrower pulls over
        the zero-copy data plane instead of this pickle channel. If every
        copy of a sealed value died, the owner is the one holding lineage —
        kick reconstruction here so borrowers recover too (reference:
        recovery runs in the owner's core worker,
        object_recovery_manager.h)."""
        from ray_tpu._private.config import get_config

        inline_max = int(get_config("inline_object_max_size_bytes"))
        entry = self.memory_store.entry(object_id)
        if entry.event.wait(0.5):
            return {"status": "ok", "data": entry.data}
        buf = self.store.get(object_id)
        if buf is not None:
            try:
                size = len(buf)
                if size <= inline_max:
                    return {"status": "ok", "data": buf.to_bytes()}
            finally:
                buf.release()
            nodes, _ = self._loc_snapshot(object_id)
            nodes = ([dict(self._my_node)]
                     + [n for n in nodes if n["NodeID"] != self.node_id])
            return {"status": "at", "nodes": nodes, "size": size}
        nodes, size = self._loc_snapshot(object_id)
        nodes = [n for n in nodes if n["NodeID"] != self.node_id]
        if nodes:
            return {"status": "at", "nodes": nodes, "size": size}
        if size and object_id not in self._ref_to_task:
            # sealed once, zero live copies → lost unless lineage recovers it
            if not self._maybe_reconstruct(object_id):
                return {"status": "lost"}
        if entry.event.wait(3.0):
            return {"status": "ok", "data": entry.data}
        # pending: task still running / reconstruction in flight
        return {"status": "pending"}

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        if num_returns > len(refs):
            raise ValueError("num_returns > len(refs)")
        deadline = None if timeout is None else time.time() + timeout
        ready: list[ObjectRef] = []
        pending = list(refs)
        poll = 0.001
        while len(ready) < num_returns:
            still = []
            for ref in pending:
                if self._is_ready(ref):
                    ready.append(ref)
                else:
                    still.append(ref)
            pending = still
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.time() >= deadline:
                break
            time.sleep(poll)
            poll = min(poll * 2, 0.05)
        # preserve input order
        ready_set = {r.id for r in ready}
        ordered_ready = [r for r in refs if r.id in ready_set]
        ordered_pending = [r for r in refs if r.id not in ready_set]
        return ordered_ready, ordered_pending

    def _is_ready(self, ref: ObjectRef) -> bool:
        if self.memory_store.contains_resolved(ref.id):
            return True
        if self.store.contains(ref.id):
            return True
        if not ref.owner_addr or tuple(ref.owner_addr) == self.addr:
            with self._dir_lock:
                return bool(self._obj_locations.get(ref.id))
        try:
            reply = self._owner_client(tuple(ref.owner_addr)).call(
                "locate_object", object_id=ref.id, timeout=5.0)
            return bool(reply.get("ready"))
        except Exception:
            return False   # owner unreachable → not fetchable either

    def as_future(self, ref: ObjectRef) -> PyFuture:
        fut = PyFuture()

        def _wait():
            try:
                fut.set_result(self.get(ref))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=_wait, daemon=True).start()
        return fut

    # ------------------------------------------------------------ submission

    def register_function(self, fn) -> bytes:
        blob = ser.dumps_function(fn)
        func_hash = hashlib.sha1(blob).digest()
        if func_hash not in self._func_cache:
            self.gcs.call("kv_put", ns="funcs", key=func_hash, value=blob,
                          overwrite=False)
            self._func_cache[func_hash] = fn
        return func_hash

    def _load_function(self, func_hash: bytes):
        fn = self._func_cache.get(func_hash)
        if fn is None:
            blob = self.gcs.call("kv_get", ns="funcs", key=func_hash)
            if blob is None:
                raise RuntimeError("function not found in GCS function table")
            fn = ser.loads_function(blob)
            self._func_cache[func_hash] = fn
        return fn

    def submit_task(self, func_hash: bytes, args, kwargs, *, num_returns=1,
                    resources=None, strategy=None, max_retries=0,
                    runtime_env=None, task_desc="task",
                    inline_exec=False) -> list[ObjectRef]:
        # {} is a legitimate request (num_cpus=0: schedule anywhere, consume
        # nothing); only None means "default 1 CPU".
        resources = {"CPU": 1.0} if resources is None else dict(resources)
        runtime_env = self._normalize_runtime_env(runtime_env)
        dynamic = num_returns in ("dynamic", "streaming")
        return_ids = [self._new_id()
                      for _ in range(1 if dynamic else num_returns)]
        inlined = None
        arg_refs = ()
        if args or kwargs:
            args, kwargs, inlined = self._inline_small_args(args, kwargs)
            args_blob = ser.serialize((args, kwargs))
            arg_refs = ser.contained_refs((args, kwargs))   # walked ONCE
        else:
            args_blob = ser.serialize_empty_args()   # constant, cached
        spec = {
            "task_id": self._new_id(),
            "func_hash": func_hash,
            "args": args_blob,
            "return_ids": return_ids,
            "owner_addr": self.addr,
            "retries_left": max_retries,
            # budget for re-executing this task after its sealed result is
            # lost (node death). Reference semantics: reconstruction rides
            # the retry budget — max_retries=0 tasks are never re-executed
            # (their loss raises ObjectLostError, see _fetch_bytes).
            "reconstructions_left": max_retries,
            "task_desc": task_desc,
            "job_id": self.job_id,
        }
        if inlined:
            spec["inlined"] = inlined
        if runtime_env:
            spec["runtime_env"] = runtime_env
        if dynamic:
            spec["dynamic_returns"] = True
            with self._lock:
                self._gen_streams[return_ids[0]] = _GenStream()
        if inline_exec and not runtime_env and not dynamic and \
                all(r.id in (inlined or ()) for r in arg_refs):
            # Only pump-safe if no arg resolution can block: a ref that
            # survived small-arg inlining would make the pump fetch it
            # (possibly a cross-node transfer) mid-dispatch. Such tasks
            # silently take the main-loop path instead. (Refs nested deep
            # inside opaque objects can still slip through — the option's
            # contract says don't do that.)
            spec["inline_exec"] = True
        from ray_tpu.util import tracing

        from ray_tpu._private.task_spec import validate_task_spec

        validate_task_spec(spec)
        _events.task_event(spec["task_id"], "SUBMITTED", desc=task_desc)
        with tracing.submit_span(spec, task_desc):
            # refs whose bytes ride the spec need no pin: the task no
            # longer depends on the object outliving the submission
            self._pin_args(spec, refs=arg_refs, skip=inlined)
            self._owned.update(return_ids)
            refs = [ObjectRef(rid, self.addr, self) for rid in return_ids]
            for rid in return_ids:
                self.memory_store.entry(rid)  # pre-create pending futures
            # runtime_env joins the scheduling key: workers apply an env
            # once and keep it (reference: envs bind to dedicated
            # workers), so different envs must not share leases
            key = (func_hash, tuple(sorted(resources.items())),
                   _freeze(strategy), _freeze(runtime_env))
            with self._lock:
                q = self._sched_queues.get(key)
                if q is None:
                    q = _SchedulingKeyQueue(self, key, resources, strategy)
                    self._sched_queues[key] = q
                for rid in return_ids:
                    self._ref_to_task[rid] = (spec, q)
            q.submit(spec)
        return refs

    def _inline_small_args(self, args, kwargs):
        """Attach the serialized bytes of small, locally-resolved
        top-level ObjectRef args to the spec (reference:
        transport/dependency_resolver.h — the local dependency resolver
        inlines small args into the TaskSpec, sparing the executor an
        owner round trip per task). The refs STAY in the arg tree and
        the bytes ride out-of-band in spec["inlined"]: the producer
        never deserializes-then-reserializes the value per submit (the
        old form cost a full pickle round per task for a repeated
        ref-arg — profiled round 5), and the executor deserializes the
        attached frame exactly once. Error payloads are never inlined:
        getting them must raise on the executor."""
        from ray_tpu._private.config import get_config

        limit = int(get_config("inline_object_max_size_bytes"))
        inlined: dict[bytes, bytes] = {}

        def maybe(v):
            if not isinstance(v, ObjectRef):
                return v
            cached = self._inline_frame_cache.get(v.id)
            if cached is not None:
                data, ok = cached
                if ok:
                    inlined[v.id] = data
                return v
            data = self.memory_store.get_nowait(v.id)
            if data is None:
                buf = self.store.get(v.id)     # put() objects live in shm
                if buf is not None:
                    try:
                        if len(buf) <= limit:
                            data = buf.to_bytes()
                            # heap-cache: repeat submits of the same
                            # small ref must not pay a shm probe each
                            # (C lock + spill stat). Freed by ref-zero.
                            if self.reference_counter.count(v.id) > 0:
                                self.memory_store.put(v.id, data)
                    finally:
                        buf.release()
            if data is None or len(data) > limit:
                return v
            # one-time verdict: error payloads must NOT inline (the
            # executor's get must raise). Cached so repeat submits skip
            # the meta parse.
            try:
                _value, meta = ser.deserialize(data, self, with_meta=True)
                ok = not meta.get("raised")
            except Exception:
                ok = False
            data = bytes(data) if not isinstance(data, bytes) else data
            if self.reference_counter.count(v.id) > 0:
                self._inline_frame_cache[v.id] = (data, ok)
            if ok:
                inlined[v.id] = data
            return v

        args = [maybe(a) for a in args]
        kwargs = {k: maybe(v) for k, v in kwargs.items()}
        return args, kwargs, inlined

    def cancel_task(self, ref: ObjectRef, force: bool = False):
        """Best-effort cancel of the normal task producing `ref` (reference:
        CoreWorker::CancelTask). Queued → dropped before dispatch; running →
        flagged, force additionally interrupts the executing thread."""
        with self._lock:
            entry = self._ref_to_task.get(ref.id)
        if entry is None:
            return False
        return self._cancel_spec(*entry, force=force)

    def _cancel_spec(self, spec: dict, q, force: bool = False) -> bool:
        spec["_cancelled"] = True
        if q is None:
            # dynamic-returns actor task: route the cancel through the
            # actor connection (flag-only; the drain loop between yields
            # honors it)
            with self._lock:
                aq = self._actor_queues.get(spec.get("actor_id"))
            client = aq.client if aq is not None else None
            if client is not None:
                try:
                    client.push("cancel_task", task_id=spec["task_id"],
                                force=force)
                except Exception:
                    pass
            return True
        for lw in list(q.leased):
            try:
                lw.client.push("cancel_task", task_id=spec["task_id"],
                               force=force)
            except Exception:
                pass
        return True

    def request_lease(self, resources, strategy, max_spillbacks: int = 16):
        """Walk the spillback chain until granted (reference:
        direct_task_transport RequestNewWorkerIfNeeded + spillback replies)."""
        from ray_tpu._private.task_spec import validate_lease_request

        if strategy is None or "job" not in strategy:
            # multi-tenant label: leases inherit this process's current
            # job so raylet-side quota throttling and the GCS's per-job
            # usage gossip see plain task/actor work, not just PGs
            from ray_tpu.util import jobs as _jobs

            job = _jobs.current_job()
            if job:
                strategy = dict(strategy or {})
                strategy["job"] = job
        # producer-side shape check: a typo'd resource/strategy key fails
        # here, not as an ignored kwarg inside a remote raylet
        validate_lease_request(resources, strategy)
        target = self.raylet
        opened = None
        try:
            for hop in range(max_spillbacks + 1):
                # Saturated cluster: every node keeps redirecting to some
                # other busy node. After max_spillbacks hops, stop bouncing
                # and queue on the current raylet until resources free.
                if hop == max_spillbacks:
                    strategy = dict(strategy or {})
                    strategy["no_spill"] = True
                reply = target.call("request_worker_lease",
                                    resources=resources, strategy=strategy,
                                    lessee=(self.worker_id, self.addr),
                                    timeout=330.0)
                if "granted" in reply:
                    return reply["granted"]
                addr = tuple(reply["spillback"])
                if opened is not None:
                    opened.close()
                opened = RpcClient(addr, timeout=None)
                target = opened
            raise RuntimeError(
                "lease not granted after queueing on a saturated cluster")
        finally:
            # the grant reply carries everything we need (worker addr,
            # node id); the raylet connection is not kept
            if opened is not None:
                opened.close()

    def return_lease(self, lw: _LeasedWorker):
        try:
            if lw.node_id == self.node_id:
                self.raylet.push("return_worker", lease_id=lw.lease_id)
            else:
                # O(1) single-node lookup: returning one spillback lease
                # used to pull the WHOLE node table (O(cluster) payload
                # per return — at 100 nodes, the soak's dominant driver
                # → GCS traffic)
                addr = self.gcs.call("get_node_addr", node_id=lw.node_id)
                if addr is not None:
                    c = RpcClient(tuple(addr), timeout=10.0)
                    try:
                        c.push("return_worker", lease_id=lw.lease_id)
                    finally:
                        c.close()
        except (ConnectionLost, Exception):  # noqa: BLE001
            pass
        finally:
            try:
                lw.client.close()
            except Exception:
                pass

    def _fail_task(self, spec: dict, error: BaseException):
        _events.task_event(spec["task_id"], "FAILED",
                           error=type(error).__name__,
                           desc=spec.get("task_desc"))
        data = ser.serialize_error(error, spec.get("task_desc", "task"))
        if spec.get("dynamic_returns"):
            self._finalize_gen(spec, None, error=data)
        for rid in spec["return_ids"]:
            self.memory_store.put(rid, data)
            with self._lock:
                self._ref_to_task.pop(rid, None)
        # A failed reconstruction arrives here with the spec still retained
        # as lineage. Pins were taken once at submit and are NOT released at
        # retain time, so: drop the lineage bookkeeping (no unpin of its
        # own), then unpin exactly once.
        with self._lock:
            self._drop_lineage_locked(spec["task_id"])
        self._unpin_args(spec)

    def _handle_task_reply(self, spec: dict, reply: dict, node_id):
        q = None
        with self._lock:
            for rid in spec["return_ids"]:
                entry = self._ref_to_task.pop(rid, None)
                if entry is not None:
                    q = entry[1]
        spec["_queue"] = q   # stripped before the wire (leading _)
        if reply.get("cancelled"):
            self._fail_task(spec, exc.TaskCancelledError(
                spec.get("task_desc", "task")))   # _fail_task unpins args
            return
        # Successful completion: keep the spec as lineage (arg pins held)
        # so a lost result can be recomputed; unpin happens at lineage drop.
        if spec.get("dynamic_returns"):
            # BEFORE lineage retention: extends return_ids with the item
            # ids so reconstruction covers every streamed object
            self._finalize_gen(spec, reply)
        if spec.get("reconstructions_left", 0) > 0 or \
                spec["task_id"] in self._lineage_specs:
            # second clause: a reconstruction that just spent its LAST
            # budget unit replies here with the spec already retained —
            # _retain_lineage's in-table guard must run, not an unpin
            # (the pins belong to the lineage entry)
            self._retain_lineage(spec)
        else:
            self._unpin_args(spec)   # never retained: release arg pins now
        results = reply.get("results", {})
        for rid, data in results.items():
            # fire-and-forget: if every ref was dropped while the task was in
            # flight, storing the result would resurrect an unfreeable object
            if self.reference_counter.count(rid) > 0 or rid in self._owned:
                self.memory_store.put(rid, data)
        # returns listed in reply["stored"] live in the executor node's shm
        # store — record them in OUR directory (we own them); _fetch_bytes
        # and borrower queries resolve through it
        exec_node = reply.get("node")
        if exec_node:
            sizes = reply.get("stored_sizes", {})
            for rid in reply.get("stored", ()):
                self._loc_add(rid, exec_node, sizes.get(rid, 0))

    # --------------------------------------------------------------- actors

    def create_actor(self, class_hash: bytes, args, kwargs, *, options):
        actor_id = self._new_id()
        spec = {
            "class_hash": class_hash,
            "class_name": options.get("class_name", "Actor"),
            "args": ser.serialize((args, kwargs)),
            "resources": options.get("resources", {"CPU": 1.0}),
            "strategy": options.get("strategy"),
            "max_restarts": options.get("max_restarts", 0),
            "max_task_retries": options.get("max_task_retries", 0),
            "max_concurrency": options.get("max_concurrency", 1),
            "concurrency_groups": options.get("concurrency_groups") or {},
            "name": options.get("name"),
            "namespace": options.get("namespace", "default"),
            "lifetime": options.get("lifetime"),
            "get_if_exists": options.get("get_if_exists", False),
            "owner_addr": self.addr,
            "job_id": self.job_id,
            "runtime_env": self._normalize_runtime_env(
                options.get("runtime_env")),
        }
        reg = self.gcs.call("register_actor", actor_id=actor_id, spec=spec)
        if reg.get("existing"):
            return bytes.fromhex(reg["existing"]["ActorID"]), True
        import pickle

        self.gcs.call("kv_put", ns="actor_spec", key=actor_id,
                      value=pickle.dumps(spec))
        # Fire creation asynchronously — actor handles are usable immediately;
        # method calls block on ALIVE state.
        threading.Thread(target=self._drive_actor_creation,
                         args=(actor_id, spec), daemon=True).start()
        return actor_id, False

    def _drive_actor_creation(self, actor_id: bytes, spec: dict):
        try:
            target = self.raylet
            opened = None
            for hop in range(17):
                if hop == 16:
                    # saturated cluster: stop bouncing, queue on the current
                    # raylet (same escape valve as the lease path)
                    spec = dict(spec)
                    spec["strategy"] = dict(spec.get("strategy") or {})
                    spec["strategy"]["no_spill"] = True
                from ray_tpu._private.config import get_config

                reply = target.call(
                    "create_actor", actor_id=actor_id, spec=spec,
                    timeout=float(get_config(
                        "actor_creation_rpc_timeout_s")))
                if "granted" in reply:
                    if opened is not None:
                        opened.close()
                    return
                addr = tuple(reply["spillback"])
                if opened is not None:
                    opened.close()
                opened = target = RpcClient(addr, timeout=None)
            raise RuntimeError("actor creation spillback loop")
        except Exception as e:  # noqa: BLE001
            try:
                self.gcs.call_once("actor_failed", actor_id=actor_id,
                              reason=f"creation failed: {e}")
            except ConnectionLost:
                pass

    def submit_actor_task(self, actor_id: bytes, method_name: str, args,
                          kwargs, *, num_returns=1, max_task_retries=0,
                          task_desc=""):
        dynamic = num_returns in ("dynamic", "streaming")
        return_ids = [self._new_id()
                      for _ in range(1 if dynamic else num_returns)]
        spec = {
            "task_id": self._new_id(),
            "actor_id": actor_id,
            "method_name": method_name,
            "args": ser.serialize((args, kwargs)),
            "return_ids": return_ids,
            "owner_addr": self.addr,
            "caller_id": self.worker_id,
            "retries_left": max_task_retries,
            "task_desc": task_desc or f"actor method {method_name}",
            "job_id": self.job_id,
        }
        if dynamic:
            spec["dynamic_returns"] = True
            with self._lock:
                self._gen_streams[return_ids[0]] = _GenStream()
                # registered so _close_gen → cancel_task can find the
                # spec; q is None (actor path has no scheduling queue)
                self._ref_to_task[return_ids[0]] = (spec, None)
        from ray_tpu.util import tracing

        from ray_tpu._private.task_spec import validate_task_spec

        validate_task_spec(spec, actor=True)
        with tracing.submit_span(spec, spec["task_desc"]):
            self._pin_args(spec, args, kwargs)
            self._owned.update(return_ids)
            refs = [ObjectRef(rid, self.addr, self) for rid in return_ids]
            for rid in return_ids:
                self.memory_store.entry(rid)
            with self._lock:
                q = self._actor_queues.get(actor_id)
                if q is None:
                    q = _ActorQueue(self, actor_id, {})
                    self._actor_queues[actor_id] = q
            q.assign_seq(spec)   # in submission order, before going async
            threading.Thread(target=q.submit, args=(spec,),
                             daemon=True).start()
        return refs

    # ----------------------------------------------------- execution (worker)

    def _start_executor(self, n_threads: int):
        self._exec_queue = queue.Queue()
        for i in range(n_threads):
            t = threading.Thread(target=self._exec_loop, daemon=True,
                                 name=f"exec-{i}")
            t.start()
            self._exec_threads.append(t)

    # Hot-path dispatch policy for this process's RpcServer: push_task is
    # handled INLINE on the transport's reader/pump thread (it never
    # blocks — see rpc_push_task) and replies are DEFERRED (sent by
    # whichever thread finishes the task), so a task in flight parks no
    # dispatch thread. This is the split the reference gets from its C++
    # core worker: compiled transport + completion callbacks,
    # interpreter only for execution (core_worker.cc:2188).
    # ping is inline for LIVENESS, not speed: raylets probe lessees with
    # a short deadline (_gc_remote_lessee_leases), and a ping that must
    # win a GIL slot for a fresh dispatch thread under load can miss it —
    # the raylet then "reclaims" a live driver's leases, killing its
    # workers mid-task (observed as WorkerCrashedError storms in the
    # chaos suite).
    INLINE_RPC = frozenset({"push_task", "ping", "task_state",
                            "locate_object", "generator_item"})
    DEFERRED_RPC = frozenset({"push_task"})

    def rpc_push_task(self, conn, seq, spec: dict):
        """Runs inline on the transport pump — MUST NOT block. Normal
        tasks enqueue straight to the main-thread task loop (reference:
        core_worker.cc:2188 RunTaskExecutionLoop is the worker main
        thread; thread-hostile native libraries — pyarrow submodule
        imports — make main-thread execution load-bearing, see CI
        segfault note in serve_task_loop's history). Actor tasks and the
        rare pre-ready window hop to a thread because they gate on seq
        order / concurrency slots / startup events."""
        from ray_tpu._private.protocol import NO_REPLY

        if (spec.get("actor_id") is None and self._ready.is_set()
                and self._main_loop_running):
            if spec.get("inline_exec") and \
                    self._normal_exec_lock.acquire(blocking=False):
                # Caller declared the task pump-safe (never blocks, no
                # thread-hostile native imports): run it RIGHT HERE and
                # skip the main-thread queue handoff + wake entirely.
                # Non-blocking acquire: if the main loop is mid-task we
                # fall through to the queue rather than stall the pump.
                # interruptible=False: a force-cancel KeyboardInterrupt
                # aimed at this THREAD could detonate in the transport
                # reader loop after the task returns; inline tasks are
                # cancel-by-flag only (they are short by contract).
                from ray_tpu._private.protocol import _RemoteError

                try:
                    result = self._exec_task_body(spec,
                                                  interruptible=False)
                except BaseException as e:  # noqa: BLE001
                    result = _RemoteError(e)
                finally:
                    self._normal_exec_lock.release()
                conn.reply(seq, result)
                return NO_REPLY
            self._main_jobs.put(
                (spec, lambda result: conn.reply(seq, result)))
            return NO_REPLY
        threading.Thread(target=self._push_task_thread,
                         args=(conn, seq, spec), daemon=True).start()
        return NO_REPLY

    def _push_task_thread(self, conn, seq, spec: dict):
        from ray_tpu._private.protocol import _RemoteError

        try:
            result = self._push_task_blocking(conn, spec)
        except BaseException as e:  # noqa: BLE001 — ship errors back
            result = _RemoteError(e)
        conn.reply(seq, result)

    def _push_task_blocking(self, conn, spec: dict):
        self._ready.wait(30.0)
        if spec.get("actor_id") is not None and self.actor_id is not None:
            return self._execute_actor_task(spec, conn)
        if self.mode == "worker":
            # a lease can arrive between __init__ registering us and
            # worker_main entering the loop — wait out that window so the
            # FIRST task (likeliest to do native imports) isn't the one
            # that lands on a dispatch thread
            self._main_loop_started.wait(10.0)
        if self._main_loop_running:
            from ray_tpu._private.protocol import _Future

            fut = _Future()
            self._main_jobs.put((spec, fut.set))
            return fut.result(timeout=None)
        return self._execute_normal_task(spec)

    def serve_task_loop(self):
        """Run normal-task execution on the calling thread (the worker
        process's main thread). Each job is (spec, done) where done
        delivers the result — directly to the requester's connection for
        inline-dispatched tasks. Returns when the raylet connection dies."""
        import queue as _q

        self._main_loop_running = True
        self._main_loop_started.set()
        try:
            while not self.stopped:
                try:
                    spec, done = self._main_jobs.get(timeout=0.5)
                except _q.Empty:
                    if self.raylet.closed:
                        return
                    continue
                try:
                    done(self._execute_normal_task(spec))
                except BaseException as e:  # noqa: BLE001 — never wedge
                    from ray_tpu._private.protocol import _RemoteError

                    done(_RemoteError(e))
        finally:
            self._main_loop_running = False

    def _resolve_args(self, spec):
        blob = spec["args"]
        if blob == ser.serialize_empty_args():
            return (), {}        # constant no-arg frame: skip the parse
        inlined = spec.get("inlined")
        args, kwargs = ser.deserialize(blob, self)

        def resolve(v):
            if not isinstance(v, ObjectRef):
                return v
            if inlined is not None:
                data = inlined.get(v.id)
                if data is not None:
                    cached = self._inlined_value_cache.get(v.id)
                    if cached is not None:
                        return cached
                    value = ser.deserialize(data, self)
                    import numpy as _np

                    if isinstance(value, _np.ndarray):
                        value.setflags(write=False)   # plasma semantics
                        cacheable = True
                    else:
                        cacheable = isinstance(
                            value, (int, float, bool, str, bytes,
                                    type(None)))
                    if cacheable:
                        if len(self._inlined_value_cache) > 1024:
                            self._inlined_value_cache.clear()
                        self._inlined_value_cache[v.id] = value
                    return value
            return self.get(v)

        args = [resolve(a) for a in args]
        kwargs = {k: resolve(v) for k, v in kwargs.items()}
        return args, kwargs

    def _execute_normal_task(self, spec: dict) -> dict:
        task_id = spec["task_id"]
        if task_id in self._cancelled:
            self._cancelled.discard(task_id)
            return {"cancelled": True}
        with self._normal_exec_lock:
            return self._exec_task_body(spec)

    def _exec_task_body(self, spec: dict, interruptible: bool = True) -> dict:
        """Execution core; caller holds _normal_exec_lock (main loop via
        _execute_normal_task, or the pump's non-blocking inline_exec
        acquire). interruptible=False leaves _current_task_thread unset so
        force-cancel never aims an async exception at the transport pump."""
        task_id = spec["task_id"]
        if task_id in self._cancelled:       # cancelled while queued here
            self._cancelled.discard(task_id)
            return {"cancelled": True}
        self._current_task_id = task_id
        self._current_task_desc = spec.get("task_desc")
        self._current_task_thread = \
            threading.get_ident() if interruptible else None
        self._current_task_started = time.time()   # OOM victim ranking
        _events.task_event(task_id, "RUNNING",
                           desc=spec.get("task_desc"))
        import contextlib

        from ray_tpu._private.profiling import record_span

        try:
            from ray_tpu.util import tracing

            # skip the span generator entirely when no trace context
            # arrived and tracing is off here — two context managers per
            # task are measurable on the sync hot path
            if spec.get("trace_ctx") is None and not tracing.is_enabled():
                trace_cm = contextlib.nullcontext()
            else:
                trace_cm = tracing.span(
                    f"execute {spec.get('task_desc', 'task')}",
                    "CONSUMER", spec.get("trace_ctx"),
                    {"task_id": task_id.hex()})
            with record_span("task", spec.get("task_desc", "task"),
                             {"task_id": task_id.hex()}), trace_cm:
                if "runtime_env" in spec or \
                        getattr(self, "_env_applied_key", None) is not None:
                    # the second clause REVERTS a previous task's overlay
                    # (env_vars/cwd/sys.path + pip-cache refcount) when
                    # this env-less task reuses the worker
                    self._apply_runtime_env(spec.get("runtime_env"))
                fn = self._load_function(spec["func_hash"])
                args, kwargs = self._resolve_args(spec)
                result = fn(*args, **kwargs)
            out = self._package_results(spec, result)
            _events.task_event(task_id, "FINISHED",
                               desc=spec.get("task_desc"))
            return out
        except BaseException as e:  # noqa: BLE001
            _events.task_event(task_id, "FAILED",
                               error=type(e).__name__,
                               desc=spec.get("task_desc"))
            return self._package_error(spec, e)
        finally:
            self._current_task_id = None
            self._current_task_desc = None
            self._current_task_thread = None
            self._current_task_started = None

    def rpc_task_state(self, conn):
        """Non-blocking probe of what this worker is running (inline —
        the raylet's OOM victim ranking queries it under memory
        pressure; the lease grant time it would otherwise use is the age
        of the LEASE, not of the current task)."""
        tid = getattr(self, "_current_task_id", None)
        return {"task_started_at": getattr(self, "_current_task_started",
                                           None),
                "task_id": tid.hex() if tid else None,
                "task_desc": getattr(self, "_current_task_desc", None)}

    def _execute_actor_task(self, spec: dict, conn=None) -> dict:
        # Per-caller ordering: DISPATCH tasks in seq order for each caller
        # (reference: actor_scheduling_queue.h client-side sequence numbers).
        # The gate orders entry into the FIFO concurrency semaphore, so
        # max_concurrency=1 executes strictly in submission order while
        # max_concurrency>1 pipelines without reordering starts. There is no
        # wall-clock skip-ahead: a successor waits however long its
        # predecessor runs; it only skips when the caller's connection is
        # dead (the predecessor can no longer arrive, and replies would go
        # nowhere anyway — advisor finding on the old 60s deadline).
        caller = f"{spec.get('caller_id', '')}:{spec.get('caller_epoch', 0)}"
        seq = spec.get("seq", 0)
        with self._seq_cond:
            while seq > self._next_seq_to_run.get(caller, 0):
                if conn is not None and not conn.alive:
                    break
                self._seq_cond.wait(timeout=0.5)
            # Resolve the gate INSIDE the seq block: if the lookup fails
            # (undeclared group — normally caught at creation time, api.py
            # _validate_concurrency_groups), the seq must still be consumed
            # or every later call from this caller wedges in the wait loop
            # above (advisor finding, round 3).
            gate_error = None
            try:
                sem = self._actor_semaphore_for(spec["method_name"])
                ticket = sem.enqueue()
            except ValueError as e:
                gate_error = e
            cur = self._next_seq_to_run.get(caller, 0)
            if seq >= cur:
                self._next_seq_to_run[caller] = seq + 1
            self._seq_cond.notify_all()
        if gate_error is not None:
            return self._package_error(spec, gate_error)
        return self._run_actor_method(spec, ticket, sem)

    def _actor_semaphore_for(self, method_name: str) -> FifoSemaphore:
        """The concurrency gate for a method: its declared group's, else
        the actor-wide default (reference: concurrency_group_manager.h)."""
        method = getattr(self._actor_instance, method_name, None)
        group = getattr(method, "__ray_concurrency_group__", None)
        if group is not None:
            sem = (getattr(self, "_actor_groups", None) or {}).get(group)
            if sem is None:
                # a misspelled/undeclared group silently serializing
                # through the default gate would be undebuggable — fail the
                # call instead (the reference validates at definition time)
                raise ValueError(
                    f"method {method_name!r} declares concurrency group "
                    f"{group!r}, but the actor was created with groups "
                    f"{sorted((getattr(self, '_actor_groups', None) or {}))}")
            return sem
        return self._actor_concurrency

    def _run_actor_method(self, spec: dict, ticket=None, sem=None) -> dict:
        import asyncio
        import inspect

        method_name = spec["method_name"]
        sem = sem if sem is not None else self._actor_concurrency
        acquired = False
        try:
            if method_name == "__ray_terminate__":
                threading.Thread(target=self._graceful_exit,
                                 daemon=True).start()
                return self._package_results(spec, None)
            method = getattr(self._actor_instance, method_name)
            # Actor-method dispatch is a fault-injection boundary too:
            # actor calls ride the deferred push_task RPC (replies are
            # written asynchronously), so the transport's on_reply hook
            # never sees them — consult the injector here with the ACTOR
            # method name. This is what lets a seeded schedule like
            # `kill_actor:rank1.next_result:#2` kill one deterministic
            # gang member mid-training (the rank-death chaos the gang-FT
            # tests replay), and lets slow_reply model a stalling actor.
            inj = _fi.ACTIVE
            if inj is not None:
                stall = inj.on_reply(method_name)
                if stall:
                    time.sleep(stall)
            args, kwargs = self._resolve_args(spec)
            # concurrency gate: the method's group semaphore (or the
            # actor-wide default, 1 slot) admits executions in dispatch
            # order (reference: concurrency_group_manager.h).
            sem.wait(ticket)
            acquired = True
            _events.task_event(spec["task_id"], "RUNNING",
                               desc=spec.get("task_desc"),
                               actor_id=(self.actor_id.hex()
                                         if self.actor_id else None))
            from ray_tpu._private.profiling import record_span

            from ray_tpu.util import tracing

            try:
                with record_span(
                        "actor_task",
                        spec.get("task_desc", f"actor.{method_name}"),
                        {"actor_id": (self.actor_id.hex()
                                      if self.actor_id else "")}), \
                     tracing.span(
                         f"execute {spec.get('task_desc', method_name)}",
                         "CONSUMER", spec.get("trace_ctx"),
                         {"task_id": spec["task_id"].hex()}):
                    if inspect.iscoroutinefunction(method):
                        fut = asyncio.run_coroutine_threadsafe(
                            method(*args, **kwargs),
                            self._ensure_async_loop())
                        result = fut.result()
                    else:
                        result = method(*args, **kwargs)
                    if spec.get("dynamic_returns"):
                        # drain INSIDE the concurrency slot: the generator
                        # body is actor code and must not overlap the next
                        # call at max_concurrency=1
                        result = self._package_results(spec, result)
            finally:
                sem.release()
            if spec.get("dynamic_returns"):
                _events.task_event(spec["task_id"], "FINISHED",
                                   desc=spec.get("task_desc"))
                return result
            # package BEFORE recording FINISHED (matching the plain-task
            # path): an unserializable result must yield FAILED alone,
            # not a FINISHED→FAILED pair for one task
            out = self._package_results(spec, result)
            _events.task_event(spec["task_id"], "FINISHED",
                               desc=spec.get("task_desc"))
            return out
        except BaseException as e:  # noqa: BLE001
            _events.task_event(spec["task_id"], "FAILED",
                               error=type(e).__name__,
                               desc=spec.get("task_desc"))
            return self._package_error(spec, e)
        finally:
            if not acquired:
                sem.cancel(ticket)

    def _ensure_async_loop(self):
        import asyncio

        if self._async_loop is None:
            loop = asyncio.new_event_loop()
            threading.Thread(target=loop.run_forever, daemon=True,
                             name="actor-async-loop").start()
            self._async_loop = loop
        return self._async_loop

    def _package_results(self, spec: dict, result) -> dict:
        if spec.get("dynamic_returns"):
            return self._package_generator(spec, result)
        num_returns = len(spec["return_ids"])
        if num_returns == 1:
            values = [result]
        elif num_returns == 0:
            values = []
        else:
            values = list(result)
            if len(values) != num_returns:
                return self._package_error(spec, ValueError(
                    f"task declared num_returns={num_returns} but returned "
                    f"{len(values)} values"))
        inline: dict[bytes, bytes] = {}
        stored: list[bytes] = []
        sizes: dict[bytes, int] = {}
        for rid, value in zip(spec["return_ids"], values):
            if value is None:
                inline[rid] = ser.serialize_none()   # cached frame
                continue
            parts = ser.serialize_parts(value)
            size = ser.parts_size(parts)
            if size <= INLINE_RESULT_LIMIT:
                inline[rid] = ser.assemble_parts(parts)
            else:
                # parts stream straight into the segment/spill file —
                # no assembled intermediate copy for big returns
                with _ma.default_tag("task_return",
                                     owner=spec.get("task_id",
                                                    b"").hex()[:16]):
                    self.store.put_parts(rid, parts)
                stored.append(rid)
                sizes[rid] = size
        # The task REPLY doubles as the location announcement: the owner
        # records (rid → this node) in its directory on receipt — no
        # directory RPC at all on the return path. (node omitted when
        # nothing was stored: it's reply-size dead weight per task.)
        if not stored:
            return {"results": inline, "stored": stored}
        return {"results": inline, "stored": stored, "stored_sizes": sizes,
                "node": self._my_node}

    def _package_generator(self, spec: dict, result) -> dict:
        """Drain a dynamic-returns task's iterator, announcing each item
        to the owner AS IT IS PRODUCED so a streaming consumer can start
        before the task finishes (reference: _raylet.pyx:168
        ObjectRefGenerator; streaming-generator item pushes in
        task_manager's HandleReportGeneratorItemReturns).

        Item ids derive deterministically from (gen_id, index) so a
        lineage re-execution regenerates the SAME ids and announcements
        land idempotently. Announcements are pipelined call_asyncs; the
        final reply waits for their acks, so by the time the owner sees
        the task reply every item it carries is already registered."""
        from ray_tpu._private.object_ref import ObjectRefGenerator

        gen_id = spec["return_ids"][0]
        owner = spec.get("owner_addr")
        local = not owner or tuple(owner) == self.addr
        rids: list[bytes] = []
        stored: list[bytes] = []
        sizes: dict[bytes, int] = {}
        acks = []
        error = None
        try:
            iterator = iter(result)
        except TypeError:
            return self._package_error(spec, TypeError(
                f"num_returns='dynamic' task returned non-iterable "
                f"{type(result).__name__}"))
        while True:
            if spec["task_id"] in self._cancelled:
                self._cancelled.discard(spec["task_id"])
                self._await_gen_acks(acks)
                return {"cancelled": True}
            try:
                value = next(iterator)
            except StopIteration:
                break
            except BaseException as e:  # noqa: BLE001 — partial stream
                error = e
                break
            index = len(rids)
            rid = _derive_item_id(gen_id, index)
            item_parts = ser.serialize_parts(value)
            size = ser.parts_size(item_parts)
            item = {"gen_id": gen_id, "index": index, "object_id": rid}
            if size <= INLINE_RESULT_LIMIT:
                item["data"] = ser.assemble_parts(item_parts)
            else:
                with _ma.default_tag("task_return",
                                     owner=spec.get("task_id",
                                                    b"").hex()[:16]):
                    self.store.put_parts(rid, item_parts)
                stored.append(rid)
                sizes[rid] = size
                item["node"] = self._my_node
                item["size"] = size
            if local:
                self._gen_item_local(**item)
            else:
                try:
                    acks.append(self._owner_client(tuple(owner))
                                .call_async("generator_item", **item))
                except Exception:
                    pass   # owner gone: the reply path will fail too
            rids.append(rid)
        self._await_gen_acks(acks)
        if error is not None:
            # partial stream: the owner already holds items 0..n-1; the
            # reply's error payload finalizes the stream so iteration
            # yields the produced prefix, then raises
            return self._package_error(spec, error)
        gen = ObjectRefGenerator(gen_id, owner, rids)
        reply = {"results": {gen_id: ser.serialize(gen)},
                 "stored": stored, "gen_count": len(rids)}
        if stored:
            reply["stored_sizes"] = sizes
            reply["node"] = self._my_node
        return reply

    @staticmethod
    def _await_gen_acks(acks):
        for fut in acks:
            try:
                fut.result(timeout=30.0)
            except Exception:
                pass   # owner died mid-stream; reply delivery fails too

    def _gen_item_local(self, gen_id: bytes, index: int, object_id: bytes,
                        data: bytes | None = None, node: dict | None = None,
                        size: int = 0):
        """Owner-side registration of one generator item (also the
        executor fast path when the owner is this process)."""
        # Atomic with _free_object's stream pop (one lock): a late item
        # racing the generator's release must either land before the
        # cleanup snapshot or not register at all — registering after it
        # would leak the object for the life of the worker.
        with self._lock:
            stream = self._gen_streams.get(gen_id)
            if stream is None:
                return   # generator already freed: drop late items
            self._owned.add(object_id)
            if data is not None:
                self.memory_store.put(object_id, data)
            elif node is not None:
                self._loc_add(object_id, node, size)
            stream.add(index, object_id)

    def rpc_generator_item(self, conn, gen_id: bytes, index: int,
                           object_id: bytes, data: bytes | None = None,
                           node: dict | None = None, size: int = 0):
        """INLINE: dict inserts + a condition notify only."""
        self._gen_item_local(gen_id, index, object_id, data, node, size)
        return True

    # ---- owner-side stream consumption (ObjectRefGenerator backing) -------

    def _gen_next(self, gen_id: bytes, index: int,
                  timeout: float | None = None):
        """Block until item `index` of the stream exists; returns its
        object id, None past the end, or raises the task's error once
        the produced prefix is consumed."""
        with self._lock:
            stream = self._gen_streams.get(gen_id)
        if stream is None:
            raise exc.RayError(f"unknown generator {gen_id.hex()}")
        deadline = None if timeout is None else time.time() + timeout
        with stream.cond:
            while True:
                rid = stream.items.get(index)
                if rid is not None:
                    return rid
                if stream.total is not None and index >= stream.total:
                    return None
                if stream.error is not None:
                    value, _meta = ser.deserialize(stream.error, self,
                                                   with_meta=True)
                    raise value
                if stream.closed:
                    return None
                wait_t = 0.5 if deadline is None else min(
                    0.5, max(0.0, deadline - time.time()))
                if deadline is not None and time.time() > deadline:
                    raise exc.GetTimeoutError(
                        f"generator item {index} not produced in time")
                stream.cond.wait(wait_t)

    def _gen_total(self, gen_id: bytes):
        with self._lock:
            stream = self._gen_streams.get(gen_id)
        return None if stream is None else stream.total

    def _close_gen(self, gen_ref):
        """Consumer closed a streaming generator early: cancel the
        producer and wake any blocked iterators."""
        with self._lock:
            stream = self._gen_streams.get(gen_ref.id)
        if stream is None:
            return
        with stream.cond:
            already_done = stream.total is not None or \
                stream.error is not None
            stream.closed = True
            stream.cond.notify_all()
        if not already_done:
            try:
                self.cancel_task(gen_ref, force=False)
            except Exception:
                pass

    def _finalize_gen(self, spec: dict, reply: dict | None,
                      error: BaseException | bytes | None = None):
        """Resolve a dynamic task's stream from its final reply (count on
        success, error payload on failure/cancel). On success the item
        ids join the spec's return_ids so lineage reconstruction covers
        them (re-execution re-derives the same ids)."""
        gen_id = spec["return_ids"][0]
        with self._lock:
            stream = self._gen_streams.get(gen_id)
        if stream is None:
            return
        if error is not None:
            data = error if isinstance(
                error, (bytes, bytearray, memoryview)) else \
                ser.serialize_error(error, spec.get("task_desc", "task"))
            stream.fail(data)
            return
        count = reply.get("gen_count")
        if count is None:    # task failed: results[gen_id] is the error
            stream.fail(reply.get("results", {}).get(gen_id))
            return
        item_ids = [_derive_item_id(gen_id, i) for i in range(count)]
        self._owned.update(item_ids)
        if spec.get("_gen_finalized") is None:
            spec["_gen_finalized"] = True
            spec["return_ids"] = list(spec["return_ids"]) + item_ids
        # Backfill any index whose announcement got lost with a dropped
        # owner connection: the ids re-derive, so the consumer still gets
        # its ref; if the item was inline its data died with the push, so
        # resolve it to ObjectLostError — a loud get() failure instead of
        # _gen_next blocking forever on a hole in the stream.
        with stream.cond:
            missing = [(i, rid) for i, rid in enumerate(item_ids)
                       if i not in stream.items]
            for i, rid in missing:
                stream.items[i] = rid
        for _i, rid in missing:
            if not self.memory_store.contains_resolved(rid):
                nodes, _size = self._loc_snapshot(rid)
                if not nodes:
                    self.memory_store.put(rid, ser.serialize_error(
                        exc.ObjectLostError(rid.hex()),
                        spec.get("task_desc", "task")))
        stream.finish(count)

    def _package_error(self, spec: dict, error: BaseException) -> dict:
        if isinstance(error, KeyboardInterrupt):
            return {"cancelled": True}
        data = ser.serialize_error(error, spec.get("task_desc", "task"))
        return {"results": {rid: data for rid in spec["return_ids"]},
                "stored": []}

    def _exec_loop(self):
        while not self.stopped:
            time.sleep(1)  # tasks execute in RPC handler threads (v1)

    # -- become an actor ------------------------------------------------------

    def rpc_become_actor(self, conn, actor_id: bytes, spec: dict,
                         timeout: float = 60.0):
        self._ready.wait(30.0)
        self.actor_id = actor_id
        self._actor_spec = spec
        self._actor_concurrency = FifoSemaphore(
            max(1, int(spec.get("max_concurrency", 1) or 1)))
        # named concurrency groups: independent FIFO gates per group
        # (reference: transport/concurrency_group_manager.h — methods
        # declared in a group don't contend with the default group)
        self._actor_groups = {
            name: FifoSemaphore(max(1, int(n)))
            for name, n in (spec.get("concurrency_groups") or {}).items()
        }
        try:
            self._apply_runtime_env(spec.get("runtime_env"))
        except BaseException as e:  # noqa: BLE001 — env setup is fatal
            self.gcs.call_once("actor_failed", actor_id=actor_id,
                          reason=f"runtime_env setup failed: {e}")
            raise
        cls = self._load_function(spec["class_hash"])
        args, kwargs = ser.deserialize(spec["args"], self)
        args = [self.get(a) if isinstance(a, ObjectRef) else a for a in args]
        kwargs = {k: self.get(v) if isinstance(v, ObjectRef) else v
                  for k, v in kwargs.items()}
        try:
            self._actor_instance = cls(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001
            self.gcs.call_once("actor_failed", actor_id=actor_id,
                          reason=f"__init__ raised: "
                                 f"{type(e).__name__}: {e}")
            raise
        self.gcs.call("actor_started", actor_id=actor_id, addr=self.addr,
                      node_id=self.node_id)
        return True

    def _graceful_exit(self):
        time.sleep(0.1)
        try:
            self.gcs.call("actor_exited", actor_id=self.actor_id)
        except ConnectionLost:
            pass
        os._exit(0)

    def rpc_exit_worker(self, conn):
        os._exit(0)

    def rpc_cancel_task(self, conn, task_id: bytes, force: bool = False):
        self._cancelled.add(task_id)
        if self._current_task_id == task_id:
            if force:
                # A blocking C call (sleep, IO, XLA) can't be interrupted by
                # an async exception — kill the worker, as the reference does
                # for force-cancel (core_worker.cc HandleCancelTask).
                os._exit(137)
            ident = self._current_task_thread
            if ident is not None:
                import ctypes

                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_long(ident), ctypes.py_object(KeyboardInterrupt))
        return True

    # ---------------------------------------------- collective p2p mailbox
    # Direct worker-to-worker data plane for ray_tpu.util.collective's host
    # backend: ring/tree collectives push chunks straight between member
    # processes instead of funnelling every tensor through one rendezvous
    # actor (the reference's gloo backend is likewise peer-to-peer,
    # gloo_collective_group.py; the named actor only rendezvouses metadata).
    # Two ingest paths: rpc_col_push (legacy sync request/reply, payload
    # pickled in the control frame) and rpc_col_push_frame (pipelined
    # one-way PUSH_OOB, payload as a zero-copy OobFrame drawn from the
    # per-(group, nbytes) receive-buffer pool below).

    def col_push_local(self, key: tuple, data):
        with self._col_cond:
            # stale check must happen under the same lock col_set_epoch
            # sweeps under — checked outside, a frame could pass the check
            # concurrently with the sweep and then park AFTER it, stranding
            # its backing shm segment past the reclaim the sweep promised
            if self._col_stale_epoch(key):
                stale = True
            else:
                # traffic from a live incarnation: park it for col_take
                stale = False
                old = self._col_mailbox.get(key)
                self._col_mailbox[key] = data
                self._col_cond.notify_all()
        if stale:
            # traffic from a previous incarnation of this group (the full
            # key carries the incarnation epoch at slot 1): a rebuilt gang
            # must never consume a dead gang's frames — reject instead of
            # parking it where it could masquerade as this epoch's payload
            self._note_stale_epoch(key)
            self._discard_col_msg(data)
            return
        if old is not None and old is not data:
            # a redelivered duplicate (fault plane `dup`, peer retry)
            # overwrote a message nobody consumed — reclaim its backing
            self._discard_col_msg(old, replacement=data)

    def _col_stale_epoch(self, key: tuple) -> bool:
        """True when `key` belongs to an OLDER incarnation of its group
        than the one this process last joined. Only a strictly older
        epoch is rejected: a NEWER one means a peer already joined the
        next incarnation this process hasn't rejoined yet — parking that
        frame is harmless (col_set_epoch's purge or group destroy sweeps
        it if this process never catches up)."""
        if len(key) < 2 or not isinstance(key[1], int):
            return False
        cur = self._col_epochs.get(key[0])
        return cur is not None and key[1] < cur

    def _note_stale_epoch(self, key: tuple):
        from ray_tpu._private import telemetry as _tm

        if _tm.ENABLED:
            try:
                _tm.counter_inc("ray_tpu_collective_stale_epoch_total",
                                tags={"group": str(key[0])})
            except Exception:
                pass

    def col_set_epoch(self, group: str, epoch: int):
        """Register this process's current incarnation epoch for one
        collective group (called at group join). Frames/shm notifies
        stamped with an older epoch are rejected at ingest from now on;
        anything the dead incarnation already parked here — mailbox
        entries AND stranded shm segments (their 4-byte epoch tag rides
        the object id, see col_oid_prefix) — is swept immediately, so a
        rebuilt gang under the same name starts from clean state even
        when the previous gang died too abruptly to destroy itself."""
        with self._col_cond:
            prev = self._col_epochs.get(group)
            self._col_epochs[group] = epoch
            if prev is not None and epoch < prev:
                # never move backwards (a late joiner re-announcing an
                # older incarnation must not resurrect swept traffic)
                self._col_epochs[group] = prev
                return
            self._col_poison.pop(group, None)   # new incarnation: clean
            stale = [k for k in self._col_mailbox
                     if k and k[0] == group and len(k) > 1
                     and isinstance(k[1], int) and k[1] < epoch]
            dropped = [self._col_mailbox.pop(k) for k in stale]
        for msg in dropped:
            self._note_stale_epoch((group, 0))
            self._discard_col_msg(msg)
        # sweep the dead epochs' stranded shm segments: group-prefixed
        # oids whose epoch tag differs from the new epoch's
        try:
            prefix = col_oid_prefix(group)
            tag = col_epoch_tag(epoch)
            for oid, _size in self.store.list_objects():
                if oid.startswith(prefix) and oid[6:10] != tag:
                    self.store.delete_ephemeral(oid)
        except Exception:
            pass

    def col_poison_local(self, group: str, dead_ranks, reason: str,
                         epoch: int | None = None):
        """Poison one collective group in this process: every pending
        col_take wakes and raises CollectiveGroupError immediately, and
        future takes fail the same way until the group is destroyed or
        rejoined under a new epoch. Idempotent; first record wins (it
        names the original dead rank). An epoch-stamped poison from an
        incarnation this process has already left is ignored — a stale
        HostGroup's on_close handler firing after a rejoin would
        otherwise kill the healthy successor gang."""
        with self._col_cond:
            if epoch is not None:
                cur = self._col_epochs.get(group)
                if cur is not None and epoch < cur:
                    return False
            if group in self._col_poison:
                return False
            self._col_poison[group] = (tuple(dead_ranks), str(reason))
            self._col_cond.notify_all()
        from ray_tpu._private import telemetry as _tm

        if _tm.ENABLED:
            try:
                _tm.counter_inc("ray_tpu_collective_groups_poisoned_total",
                                tags={"group": group})
            except Exception:
                pass
        return True

    def rpc_col_poison(self, conn, group: str, dead_ranks, reason: str,
                       epoch: int | None = None):
        """Group-poison ingest (pushed by the group's rendezvous actor on
        member death, or by a member that directly observed a peer's
        connection drop). The epoch guard lives in col_poison_local,
        under the mailbox lock."""
        self.col_poison_local(group, tuple(dead_ranks), reason,
                              epoch=epoch)
        return True

    def col_poisoned(self, group: str):
        """(dead_ranks, reason) if `group` is poisoned in this process."""
        with self._col_cond:
            return self._col_poison.get(group)

    def _discard_col_msg(self, msg, replacement=None):
        """Reclaim an unconsumed mailbox message's backing resource: a
        transport frame's pooled buffer, or a shm segment's store
        object. A duplicate-delivered shm ref (fault plane `dup`) is a
        DISTINCT ColShmRef wrapping the SAME object — deleting the old
        ref's object would tear the store out from under the surviving
        one, so same-oid replacements skip the delete."""
        if isinstance(msg, ColShmRef):
            if isinstance(replacement, ColShmRef) \
                    and replacement.oid == msg.oid:
                return
            try:
                self.store.delete_ephemeral(msg.oid)
            except Exception:
                pass
        else:
            _release_col_msg(msg)

    def col_purge(self, group: str) -> int:
        """Drop every mailbox entry belonging to one collective group
        (keys lead with the group name). Called on group destroy: a
        stale message from a dead incarnation (e.g. a peer's payload
        that landed after an op timeout) would otherwise trip the next
        incarnation's seq validation as a phantom NEWER seq."""
        with self._col_cond:
            stale = [k for k in self._col_mailbox if k and k[0] == group]
            dropped = [self._col_mailbox.pop(k) for k in stale]
            self._col_poison.pop(group, None)
            self._col_epochs.pop(group, None)
        for msg in dropped:
            self._discard_col_msg(msg)
        COL_RECV_POOL.purge(group)
        # sweep STRANDED shm segments too: a dropped col_push_shm notify
        # (or a receiver that died first) leaves the object in the store
        # with no mailbox ref anywhere — reachable only via its group-
        # tagged id prefix
        try:
            prefix = col_oid_prefix(group)
            for oid, _size in self.store.list_objects():
                if oid.startswith(prefix):
                    self.store.delete_ephemeral(oid)
        except Exception:
            pass
        return len(stale)

    def rpc_col_push(self, conn, key: tuple, data):
        self.col_push_local(tuple(key), data)
        return True

    def rpc_col_push_frame(self, conn, key: tuple, frame):
        """PUSH_OOB ingest (runs inline on the transport reader/pump —
        a mailbox store, never blocks). `frame` is the transport's
        OobFrame; the taker deserializes the view in place and releases
        the buffer back to the pool."""
        self.col_push_local(tuple(key), frame)

    def rpc_col_push_shm(self, conn, key: tuple, oid: bytes, nbytes: int):
        """Same-node segment hand-off: the payload already sits in the
        node's shared-memory store under `oid` (the sender put it
        there); only this tiny reference crosses the socket. The taker
        maps the object zero-copy and deletes it once consumed."""
        self.col_push_local(tuple(key), ColShmRef(oid, nbytes))

    def rpc_col_meta(self, conn):
        """Peer identity for the collective data plane: ranks with the
        same node_id share this node's shm store, so segments can move
        as store references instead of socket bytes."""
        return {"node_id": self.node_id}

    def col_take(self, key: tuple, timeout: float = 300.0,
                 seq_pos: int | None = None):
        """Blocking take of one collective message.

        ``seq_pos`` (index of the op sequence number within ``key``)
        arms receiver-side sequence validation: if a message for the
        SAME channel (identical key except the seq slot) carrying a
        NEWER seq shows up while ours never does, the group's op
        ordering has desynchronized — raise a clear mismatch error
        immediately instead of hanging until the watchdog timeout or
        silently pairing wrong payloads. Only a newer seq is proof:
        per-peer delivery is in-order, so a newer message implies ours
        would already have arrived. An OLDER same-channel seq is
        ambiguous (a redelivered duplicate — e.g. the fault plane's
        ``dup`` action — looks identical to a restarted peer), so it
        never raises; it only annotates the eventual timeout. The exact
        key is always preferred when present."""
        key = tuple(key)

        def _same_channel(k):
            return (len(k) == len(key) and k[:seq_pos] == key[:seq_pos]
                    and k[seq_pos + 1:] == key[seq_pos + 1:]
                    and k[seq_pos] != key[seq_pos])

        def _newer(k):
            return _same_channel(k) and k[seq_pos] > key[seq_pos]

        group = key[0] if key else None

        def _ready():
            if group in self._col_poison:
                return True
            if key in self._col_mailbox:
                return True
            return seq_pos is not None and any(
                _newer(k) for k in self._col_mailbox)

        with self._col_cond:
            ok = self._col_cond.wait_for(_ready, timeout=timeout)
            poison = self._col_poison.get(group)
            if poison is not None:
                # a member died: fail fast with the culprit named instead
                # of hanging out the rest of the op timeout (the group is
                # unusable until it is destroyed and rebuilt)
                dead_ranks, reason = poison
                raise exc.CollectiveGroupError(str(group), dead_ranks,
                                               reason)
            if not ok:
                hint = ""
                if seq_pos is not None:
                    stale = sorted(k[seq_pos] for k in self._col_mailbox
                                   if _same_channel(k))
                    if stale:
                        hint = (f" (same-channel messages with older seq "
                                f"{stale} are waiting — a restarted peer "
                                f"resets its op counters)")
                raise TimeoutError(
                    f"collective recv timed out on {key}{hint}")
            if key in self._col_mailbox:
                return self._col_mailbox.pop(key)
            newer = sorted(k[seq_pos] for k in self._col_mailbox
                           if _newer(k))
            raise exc.CollectiveSeqMismatchError(
                f"collective sequence mismatch on channel "
                f"{key[:seq_pos] + key[seq_pos + 1:]}: this rank expects "
                f"seq {key[seq_pos]} but the peer already sent seq "
                f"{newer} — the group's op ordering has desynchronized "
                f"(every rank must issue collective calls in the same "
                f"order; a restarted member resets its counters)")

    def rpc_ping(self, conn):
        return "pong"

    def rpc_actor_state(self, conn):
        return {"actor_id": self.actor_id.hex() if self.actor_id else None,
                "num_pending": self._exec_queue.qsize()
                if self._exec_queue else 0}

    # --------------------------------------------------------------- shutdown

    def shutdown(self):
        self.stopped = True
        _ma.stop_periodic_sweep()
        self._free_queue.put(None)   # unblock the ref reaper
        self.reference_counter.shutdown()   # and the refcount drainer
        self._server.stop()
        with self._owner_client_lock:
            owner_clients = list(self._owner_clients.values())
            self._owner_clients.clear()
        for c in (self.gcs, self.raylet, *owner_clients):
            try:
                c.close()
            except Exception:
                pass
        try:
            self.store.close()
        except Exception:
            pass


class ColShmRef:
    """Mailbox marker for a collective segment parked in the node's shm
    store (see rpc_col_push_shm)."""

    __slots__ = ("oid", "nbytes")

    def __init__(self, oid: bytes, nbytes: int):
        self.oid = oid
        self.nbytes = nbytes


def col_oid_prefix(group: str) -> bytes:
    """6-byte object-id prefix tagging one group's shm segments, so a
    stranded segment (its notify dropped / receiver died before the
    take) is findable: group destroy sweeps the node store for this
    prefix and deletes leftovers — without it, an untagged orphan would
    occupy the bounded segment until eviction pressure."""
    return b"\xc0" + hashlib.blake2b(group.encode(),
                                     digest_size=5).digest()


def col_epoch_tag(epoch: int) -> bytes:
    """4-byte incarnation-epoch tag following the group prefix in a
    collective shm object id (layout: group-prefix(6) + epoch(4) +
    rank(2) + counter(4) — 16 bytes). Lets col_set_epoch sweep a DEAD incarnation's
    stranded segments — including incarnations this process never knew —
    by deleting group-prefixed objects whose tag differs from the live
    epoch's, without ever touching the live epoch's in-flight segments."""
    return (int(epoch) % (1 << 32)).to_bytes(4, "big")


def _release_col_msg(msg):
    release = getattr(msg, "release", None)
    if release is not None:
        try:
            release()
        except Exception:
            pass


class _ColBufferPool:
    """Receive-buffer pool for the pipelined collective data path,
    keyed (group, nbytes). The transport's PUSH_OOB reader acquires a
    buffer per incoming segment; the host backend's take side releases
    it after reducing — steady-state allreduce cycles the same few
    buffers with zero per-step allocations. Bounded per key and in
    total so a burst (or a leak) degrades to plain allocation instead
    of growing forever; purge(group) drops a destroyed group's buffers.
    Process-wide (in-process test clusters share it), like the
    transports themselves."""

    MAX_PER_KEY = 8
    MAX_TOTAL_BYTES = 256 * 1024 * 1024

    def __init__(self):
        self._lock = threading.Lock()
        self._free: dict[tuple, list] = {}
        self._bytes = 0

    def acquire(self, key: tuple, nbytes: int):
        with self._lock:
            bucket = self._free.get(key)
            if bucket:
                self._bytes -= nbytes
                return bucket.pop()
        return bytearray(nbytes)

    def release(self, key: tuple, buf):
        nbytes = len(buf)
        with self._lock:
            bucket = self._free.setdefault(key, [])
            if (len(bucket) < self.MAX_PER_KEY
                    and self._bytes + nbytes <= self.MAX_TOTAL_BYTES):
                bucket.append(buf)
                self._bytes += nbytes

    def purge(self, group: str):
        with self._lock:
            for key in [k for k in self._free if k[0] == group]:
                self._bytes -= sum(len(b) for b in self._free.pop(key))

    def stats(self) -> dict:
        with self._lock:
            return {"keys": len(self._free), "bytes": self._bytes,
                    "buffers": sum(len(v) for v in self._free.values())}


COL_RECV_POOL = _ColBufferPool()

# Hand the transports the pool: PUSH_OOB bodies tagged with a pool hint
# (the collective group name) are received straight into recycled
# buffers instead of fresh allocations (pure-Python transport; the
# native C core allocates in C and release() no-ops there).
from ray_tpu._private import protocol as _protocol  # noqa: E402

_protocol.set_oob_buffer_pool(COL_RECV_POOL)


def _freeze(obj):
    if obj is None:
        return None
    if isinstance(obj, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in obj.items()))
    if isinstance(obj, (list, tuple)):
        return tuple(_freeze(v) for v in obj)
    return obj


_current_worker: CoreWorker | None = None
_current_worker_lock = threading.Lock()


def current_worker() -> CoreWorker | None:
    return _current_worker


def set_current_worker(worker: CoreWorker | None):
    global _current_worker
    with _current_worker_lock:
        _current_worker = worker
