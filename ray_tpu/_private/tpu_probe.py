"""TPU reachability probe, shared by bench/benchmark entry points.

The axon tunnel can hang for hours and a hung tunnel blocks
``jax.devices()`` FOREVER in any process that touches the TPU backend —
so the probe runs in a SUBPROCESS with a timeout, and callers decide the
platform before their own first jax import (see bench.py for the
retry-with-backoff policy layered on top).
"""
from __future__ import annotations

import json
import subprocess
import sys


def tpu_reachable_once(timeout_s: float = 120.0) -> bool:
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices()[0].platform == 'tpu'"],
            timeout=timeout_s, capture_output=True)
        return probe.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


_CHIP_PROBE_SRC = """
import json, jax
chips = [d for d in jax.devices() if d.platform == "tpu"]
info = {}
if chips:
    info["chips"] = len(chips)
    coords = [list(getattr(d, "coords", ()) or ()) for d in chips]
    if any(coords):
        info["coords"] = coords
    si = getattr(chips[0], "slice_index", None)
    if si is not None:
        info["slice_id"] = f"slice-{si}"
print(json.dumps(info))
"""


_chip_probe_cache: list = []   # [] = never probed; [result] = cached


def probe_chips(timeout_s: float = 60.0) -> dict | None:
    """Chip count / coords / slice id via a SUBPROCESS jax.devices() call
    (same hang rationale as above — the raylet must never block its own
    init on the tunnel). None = no chips or probe failed/timed out.
    Memoized per process: detect_resources and detect_tpu_topology both
    call this during raylet init, and a wedged tunnel should cost one
    timeout, not two."""
    if _chip_probe_cache:
        return _chip_probe_cache[0]
    result = _probe_chips_once(timeout_s)
    _chip_probe_cache.append(result)
    return result


def _probe_chips_once(timeout_s: float) -> dict | None:
    try:
        probe = subprocess.run(
            [sys.executable, "-c", _CHIP_PROBE_SRC],
            timeout=timeout_s, capture_output=True, text=True)
        if probe.returncode != 0:
            return None
        info = json.loads(probe.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, OSError, ValueError, IndexError):
        return None
    if not info.get("chips"):
        return None
    if "coords" in info:
        info["coords"] = [tuple(c) for c in info["coords"]]
    return info
