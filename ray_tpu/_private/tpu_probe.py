"""TPU reachability + per-device telemetry probes.

The axon tunnel can hang for hours and a hung tunnel blocks
``jax.devices()`` FOREVER in any process that touches the TPU backend —
so every probe here runs in a SUBPROCESS with a timeout, and callers
decide the platform before their own first jax import (see bench.py for
the retry-with-backoff policy layered on top).

Besides the reachability/chip probes the raylet uses at init, this
module is the data-plane device-telemetry source (PR 3):

- ``probe_devices()``      subprocess-safe per-device snapshot — HBM
                           bytes in use/limit, platform/kind, coords
                           and slice when the runtime exposes them;
                           on CPU the same shape comes back with the
                           host allocator stats jax reports (graceful
                           fallback, never an error).
- ``publish_device_gauges()`` folds a snapshot into the
                           ``ray_tpu_device_hbm_bytes`` catalog gauge.
- ``start_device_gauge_poller()`` background refresh loop the raylet
                           starts when real chips were detected.
- ``local_device_identity()`` IN-process identity for tagging train
                           step events — consults jax only if the
                           process already imported it (a train worker
                           inevitably will), so it adds zero new
                           backend-init hang risk.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading


def tpu_reachable_once(timeout_s: float = 120.0) -> bool:
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices()[0].platform == 'tpu'"],
            timeout=timeout_s, capture_output=True)
        return probe.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


_CHIP_PROBE_SRC = """
import json, jax
chips = [d for d in jax.devices() if d.platform == "tpu"]
info = {}
if chips:
    info["chips"] = len(chips)
    coords = [list(getattr(d, "coords", ()) or ()) for d in chips]
    if any(coords):
        info["coords"] = coords
    si = getattr(chips[0], "slice_index", None)
    if si is not None:
        info["slice_id"] = f"slice-{si}"
print(json.dumps(info))
"""


_chip_probe_cache: list = []   # [] = never probed; [result] = cached


def probe_chips(timeout_s: float = 60.0) -> dict | None:
    """Chip count / coords / slice id via a SUBPROCESS jax.devices() call
    (same hang rationale as above — the raylet must never block its own
    init on the tunnel). None = no chips or probe failed/timed out.
    Memoized per process: detect_resources and detect_tpu_topology both
    call this during raylet init, and a wedged tunnel should cost one
    timeout, not two."""
    if _chip_probe_cache:
        return _chip_probe_cache[0]
    result = _probe_chips_once(timeout_s)
    _chip_probe_cache.append(result)
    return result


def _probe_chips_once(timeout_s: float) -> dict | None:
    try:
        probe = subprocess.run(
            [sys.executable, "-c", _CHIP_PROBE_SRC],
            timeout=timeout_s, capture_output=True, text=True)
        if probe.returncode != 0:
            return None
        info = json.loads(probe.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, OSError, ValueError, IndexError):
        return None
    if not info.get("chips"):
        return None
    if "coords" in info:
        info["coords"] = [tuple(c) for c in info["coords"]]
    return info


# ------------------------------------------------ per-device telemetry

_DEVICE_PROBE_SRC = """
import json, jax
out = []
for d in jax.local_devices():
    rec = {"id": d.id, "platform": d.platform,
           "kind": getattr(d, "device_kind", ""),
           "process_index": d.process_index}
    coords = getattr(d, "coords", None)
    if coords:
        rec["coords"] = list(coords)
    si = getattr(d, "slice_index", None)
    if si is not None:
        rec["slice_index"] = si
    try:
        ms = d.memory_stats()
    except Exception:
        ms = None
    if ms:
        if ms.get("bytes_in_use") is not None:
            rec["hbm_bytes_in_use"] = int(ms["bytes_in_use"])
        if ms.get("bytes_limit") is not None:
            rec["hbm_bytes_limit"] = int(ms["bytes_limit"])
    out.append(rec)
print(json.dumps(out))
"""


def probe_devices(timeout_s: float = 60.0) -> list[dict] | None:
    """Per-device snapshot via a SUBPROCESS jax call (same hang
    rationale as the chip probe): id, platform, kind, coords/slice when
    exposed, HBM bytes in use / limit when the backend reports memory
    stats. CPU fallback is the same record shape minus TPU-only fields;
    None only when the probe itself failed or timed out. NOT memoized —
    memory numbers are the point of polling."""
    try:
        probe = subprocess.run(
            [sys.executable, "-c", _DEVICE_PROBE_SRC],
            timeout=timeout_s, capture_output=True, text=True)
        if probe.returncode != 0:
            return None
        devices = json.loads(probe.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, OSError, ValueError, IndexError):
        return None
    return devices if isinstance(devices, list) else None


def publish_device_gauges(devices: list[dict] | None = None,
                          timeout_s: float = 60.0) -> int:
    """Fold a device snapshot (probed here unless injected by the
    caller) into the ``ray_tpu_device_hbm_bytes`` gauge, one
    (node, device, platform, stat) series per reported stat. Returns
    the number of devices seen; 0 when telemetry is off or the probe
    failed.

    When this function PROBES (devices=None), only ``stat=limit`` is
    published: the subprocess's ``bytes_in_use`` is the fresh probe
    process's own allocator state, not the training workload's — and a
    stale near-zero value under the same tag set would race the owning
    worker's live publishes last-write-wins. Injected records (owner
    processes, tests) carry whatever stats the caller vouches for."""
    from ray_tpu._private import telemetry as _tm

    if not _tm.ENABLED:
        return 0
    probed = devices is None
    if probed:
        devices = probe_devices(timeout_s)
    if not devices:
        return 0
    node = os.uname().nodename
    for d in devices:
        # node tag: local device ids restart at 0 on every host (the
        # probe subprocess has no jax.distributed world) — without the
        # hostname, multi-host gauges collide last-write-wins
        tags = {"node": node, "device": str(d.get("id")),
                "platform": str(d.get("platform", "?"))}
        if not probed and d.get("hbm_bytes_in_use") is not None:
            _tm.gauge_set("ray_tpu_device_hbm_bytes",
                          float(d["hbm_bytes_in_use"]),
                          tags={**tags, "stat": "in_use"})
        if d.get("hbm_bytes_limit") is not None:
            _tm.gauge_set("ray_tpu_device_hbm_bytes",
                          float(d["hbm_bytes_limit"]),
                          tags={**tags, "stat": "limit"})
    return len(devices)


_poller_lock = threading.Lock()
_poller_thread: threading.Thread | None = None


def start_device_gauge_poller(interval_s: float | None = None) -> bool:
    """Background per-device gauge publisher (daemon thread, one per
    process), started by the raylet only when REAL chips were detected.

    Default behavior is ONE probe, at raylet start — i.e. before any
    training worker exists. A subprocess `import jax` takes exclusive
    TPU ownership under libtpu's single-process lock, so a RECURRING
    probe on a busy host either fails every poll (worker owns the
    chips: gauges silently absent exactly when they matter) or, worse,
    wins the race between worker restarts and fails the worker's own
    backend init. Recurring polling is therefore opt-in
    (``RAY_TPU_DEVICE_GAUGE_POLL_S`` > 0), for hosts where probing is
    known-safe; live in-use HBM during training comes from the OWNING
    process instead via ``publish_local_device_gauges()`` (train
    workers call it on every step report). Returns True if the
    publisher thread is (now) running."""
    global _poller_thread
    from ray_tpu._private import telemetry as _tm

    if not _tm.ENABLED:
        return False
    with _poller_lock:
        if _poller_thread is not None and _poller_thread.is_alive():
            return True

        def _loop():
            import time as _time

            from ray_tpu._private.config import get_config

            while True:
                try:
                    publish_device_gauges()
                except Exception:
                    pass   # telemetry must never take the raylet down
                iv = (interval_s if interval_s is not None
                      else float(get_config("device_gauge_poll_s")))
                if iv <= 0:
                    return     # one-shot seed (the safe default)
                _time.sleep(iv)

        _poller_thread = threading.Thread(
            target=_loop, daemon=True, name="device-gauge-poller")
        _poller_thread.start()
    return True


def publish_local_device_gauges() -> int:
    """IN-process gauge publish from a process that already owns the
    jax backend (train workers): ``memory_stats()`` on the live runtime
    costs microseconds and cannot contend with anyone for chip
    ownership — the right source for live HBM while training runs.
    Consults jax only if this process already imported it (same
    no-new-hang-risk rule as ``local_device_identity``)."""
    from ray_tpu._private import telemetry as _tm

    if not _tm.ENABLED:
        return 0
    jax = sys.modules.get("jax")
    if jax is None:
        return 0
    try:
        devs = jax.local_devices()
    except Exception:
        return 0
    records = []
    for d in devs:
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        records.append({"id": d.id, "platform": d.platform,
                        "hbm_bytes_in_use": ms.get("bytes_in_use"),
                        "hbm_bytes_limit": ms.get("bytes_limit")})
    if not records:
        return 0
    return publish_device_gauges(devices=records)


def local_device_identity() -> dict:
    """IN-process device identity for tagging train-step events: host +
    pid always; platform/devices only when this process ALREADY imported
    jax (a train worker does before its first step) — never triggers a
    fresh backend init, so no new tunnel-hang exposure."""
    info: dict = {"host": os.uname().nodename, "pid": os.getpid(),
                  "platform": None, "device_count": 0}
    jax = sys.modules.get("jax")
    if jax is None:
        return info
    try:
        devs = jax.local_devices()
    except Exception:
        return info
    if not devs:
        return info
    info["platform"] = devs[0].platform
    info["device_count"] = len(devs)
    info["device_kind"] = getattr(devs[0], "device_kind", "")
    info["device_ids"] = [d.id for d in devs]
    coords = [list(getattr(d, "coords", ()) or ()) for d in devs]
    if any(coords):
        info["coords"] = coords
    si = getattr(devs[0], "slice_index", None)
    if si is not None:
        info["slice_index"] = si
    return info
