"""TPU reachability probe, shared by bench/benchmark entry points.

The axon tunnel can hang for hours and a hung tunnel blocks
``jax.devices()`` FOREVER in any process that touches the TPU backend —
so the probe runs in a SUBPROCESS with a timeout, and callers decide the
platform before their own first jax import (see bench.py for the
retry-with-backoff policy layered on top).
"""
from __future__ import annotations

import subprocess
import sys


def tpu_reachable_once(timeout_s: float = 120.0) -> bool:
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices()[0].platform == 'tpu'"],
            timeout=timeout_s, capture_output=True)
        return probe.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False
