"""Node memory monitor + worker-killing policy (OOM protection).

Reference: src/ray/common/memory_monitor.h:48,88 (MemoryMonitor polls
/proc meminfo/cgroup usage on an interval and fires a callback above a
usage threshold) and src/ray/raylet/worker_killing_policy.h:30 (pick a
victim worker — newest-task-first, so long-running work survives and
the likely culprit dies) — the raylet kills the victim with a
RETRIABLE error instead of letting the kernel OOM-killer take down the
whole node (or the raylet itself).

The raylet owns one Monitor; the victim's task fails with
OutOfMemoryError naming the culprit and its RSS, and normal task retry
(retries_left) gives the resubmitted task its chance on a quieter node.
"""
from __future__ import annotations

import os
import threading


def node_memory_usage() -> tuple[int, int]:
    """(used_bytes, total_bytes) for this node. Cgroup-aware: in a
    container the cgroup limit is the real ceiling, not the host total
    (memory_monitor.h reads both and takes the tighter bound)."""
    total = used = None
    try:
        with open("/proc/meminfo") as f:
            info = {}
            for line in f:
                parts = line.split()
                info[parts[0].rstrip(":")] = int(parts[1]) * 1024
        total = info["MemTotal"]
        used = total - info.get("MemAvailable",
                                info.get("MemFree", 0))
    except (OSError, KeyError):
        total, used = 8 << 30, 0
    for limit_path, usage_path in (
            ("/sys/fs/cgroup/memory.max",
             "/sys/fs/cgroup/memory.current"),
            ("/sys/fs/cgroup/memory/memory.limit_in_bytes",
             "/sys/fs/cgroup/memory/memory.usage_in_bytes")):
        try:
            with open(limit_path) as f:
                raw = f.read().strip()
            if raw == "max":
                continue
            limit = int(raw)
            if 0 < limit < total:
                with open(usage_path) as f:
                    cg_used = int(f.read().strip())
                return cg_used, limit
        except (OSError, ValueError):
            continue
    return used, total


def process_rss(pid: int) -> int:
    """Resident set size of one process in bytes (0 if gone)."""
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


def pick_victim(workers: list[dict]) -> dict | None:
    """Newest-task-first (worker_killing_policy.h:30): among workers
    currently running a task, kill the one whose task started LAST —
    retrying young work wastes the least progress, and the most recent
    arrival is the likeliest cause of the spike. Ties (no task-start
    info) break toward the largest RSS.

    Each entry: {"pid", "task_started_at" (float|None), ...}; returns the
    chosen entry (caller kills + packages the error).
    """
    candidates = [w for w in workers if w.get("pid")]
    if not candidates:
        return None
    running = [w for w in candidates if w.get("task_started_at")]
    if running:
        return max(running, key=lambda w: w["task_started_at"])
    return max(candidates, key=lambda w: process_rss(w["pid"]))


class MemoryMonitor:
    """Polls node usage; above `threshold` of capacity, calls
    `on_pressure(usage, total)` (the raylet's kill hook) once per
    crossing, re-armed after usage falls below the threshold minus
    `hysteresis` (no kill storms while usage hovers at the line) —
    OR after `cooldown_s` with usage still above the threshold: one
    kill may not relieve the pressure (another worker still growing),
    and the reference keeps killing while over the line
    (memory_monitor.h fires per monitoring interval)."""

    def __init__(self, on_pressure, threshold: float | None = None,
                 interval_s: float | None = None,
                 hysteresis: float = 0.05,
                 cooldown_s: float | None = None,
                 usage_fn=node_memory_usage):
        from ray_tpu._private.config import get_config

        self.threshold = (threshold if threshold is not None
                          else get_config("memory_usage_threshold"))
        self.interval_s = (interval_s if interval_s is not None
                           else get_config("memory_monitor_refresh_ms")
                           / 1000.0)
        self.hysteresis = hysteresis
        self.cooldown_s = (cooldown_s if cooldown_s is not None
                           else get_config(
                               "memory_monitor_kill_cooldown_s"))
        self._on_pressure = on_pressure
        self._usage_fn = usage_fn
        self._armed = True
        self._last_fire = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self):
        if self.interval_s <= 0:      # disabled by config
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="memory-monitor")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def tick(self):
        """One poll step (exposed for tests; the thread calls this)."""
        import time

        used, total = self._usage_fn()
        if total <= 0:
            return
        frac = used / total
        if frac >= self.threshold:
            now = time.monotonic()
            if self._armed or now - self._last_fire >= self.cooldown_s:
                self._armed = False
                self._last_fire = now
                try:
                    self._on_pressure(used, total)
                except Exception:
                    pass
        elif frac < self.threshold - self.hysteresis:
            self._armed = True

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self.tick()
