"""Central config table with env-var overrides.

TPU-native analog of the reference's RAY_CONFIG macro table
(/root/reference/src/ray/common/ray_config_def.h:32 — 179 entries,
each overridable via a `RAY_<name>` env var and propagable cluster-wide).
Here each entry is declared once in _CONFIG_DEFS and overridable via
`RAY_TPU_<NAME>`; `system_config` overrides passed to `init()` win over env.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict

_CONFIG_DEFS: Dict[str, Any] = {
    # --- scheduling ---
    "worker_lease_timeout_ms": 30_000,
    "worker_pool_min_size": 0,
    "worker_register_timeout_s": 60.0,  # worker process spawn+import budget
    "worker_pool_idle_timeout_s": 120.0,
    "max_tasks_in_flight_per_worker": 2,  # lease pipelining depth
    "scheduler_spread_threshold": 0.5,  # hybrid policy pack→spread knob
    "scheduler_top_k_fraction": 0.2,
    "lease_soft_cap": 0,               # 0 = auto: 2x cluster CPUs
    "actor_resolution_poll_max_s": 1.0,  # backoff cap for pending actors
    # --- worker pool ---
    "prestart_workers": 4,             # warm-pool watermark per node
    "idle_worker_cap": 8,              # max idle processes kept per node
    "max_startup_concurrency": 0,      # 0 = auto: one per core
    # --- TPU probing ---
    "chip_probe_timeout_s": 60.0,      # subprocess jax.devices() budget
    # --- object store ---
    "object_store_memory_default": 256 * 1024 * 1024,
    "object_store_full_delay_ms": 10,
    "object_store_full_max_retries": 500,
    "object_spilling_threshold": 0.8,
    "min_spilling_size_bytes": 1024 * 1024,
    "max_io_workers": 2,
    "inline_object_max_size_bytes": 100 * 1024,  # small results ride the RPC reply
    "object_transfer_chunk_bytes": 4 * 1024 * 1024,
    "pull_max_inflight_bytes": 256 * 1024 * 1024,  # pull admission control
    # --- memory anatomy (_private/memory_anatomy.py) ---
    # Leak-sweep grace window: objects younger than this are referenced
    # by definition (an in-flight collective segment between put and
    # consume must not classify as a leak).
    "memory_sweep_grace_s": 5.0,
    # Periodic background sweep cadence per worker process (0 disables
    # the timer; sweeps still run on demand from summarize_memory /
    # the flight recorder / the memory-snapshot RPC).
    "memory_sweep_interval_s": 30.0,
    # Bounded provenance-op ring per process (the flight recorder's
    # memory.jsonl window).
    "memory_ring_size": 2048,
    # Bounded best-effort re-send of free fan-outs on the one-way
    # owner→GCS→raylet delete pipeline: when the GCS finds no live
    # raylet connection for a holder node, retry the push once after
    # re-resolving the connection (the counted drop otherwise strands
    # the object until the leak sweep names it). 0 disables.
    "store_free_resend": 1,
    # --- lineage / reconstruction ---
    "max_lineage_bytes": 64 * 1024 * 1024,  # retained task specs for rebuild
    # --- fault tolerance ---
    "task_max_retries_default": 3,
    "actor_max_restarts_default": 0,
    "health_check_period_ms": 1_000,
    "health_check_failure_threshold": 5,
    "gcs_rpc_timeout_s": 30.0,
    # --- unified control-plane retry policy (_private/retry.py) ---
    "rpc_retry_max_attempts": 5,        # per-call attempt cap
    "rpc_retry_base_backoff_s": 0.05,   # full-jitter backoff base
    "rpc_retry_max_backoff_s": 2.0,     # backoff cap
    "rpc_retry_deadline_s": 90.0,       # total budget across attempts
    # --- memory monitor ---
    "memory_monitor_refresh_ms": 250,
    "memory_usage_threshold": 0.95,
    "memory_monitor_kill_cooldown_s": 5.0,  # re-kill while still over
    # --- runtime envs ---
    "runtime_env_dir": "/tmp/ray_tpu/runtime_envs",
    "runtime_env_cache_max": 8,        # unreferenced envs kept (LRU)
    # --- logs ---
    "log_monitor_interval_ms": 250,    # worker-log tail cadence
    # --- serve ---
    "serve_stream_chunk_timeout_s": 300.0,  # first chunk may be a compile
    # serve-as-a-tenant (apps registered with a job): CPU bundle each
    # replica's capacity placement group reserves when the deployment's
    # ray_actor_options carry no num_cpus of their own
    "serve_replica_capacity_cpu": 1.0,
    # 0 restores the legacy direct-stop scale-down for tenant apps
    # (bit-identical kill switch: no preemption-warning round trip, no
    # draining broadcast — replicas stop the pre-tenant way)
    "serve_preempt_scale_down": 1,
    # --- collective / mesh ---
    "collective_default_backend": "xla",
    "collective_op_timeout_s": 300.0,  # dead-member detector of last resort
    # Gang fault tolerance (ray_tpu.train + util/collective): the group's
    # rendezvous actor watches the GCS actor-death feed and POISONS the
    # group when a member dies — surviving ranks' pending/future
    # collective ops raise CollectiveGroupError (naming the dead rank)
    # well under the op timeout, and members that directly observe a peer
    # connection drop poison the group themselves.
    # RAY_TPU_COLLECTIVE_DEATH_POISONING=0 falls back to timeout-only
    # detection.
    "collective_death_poisoning": True,
    # Driver-side gang death monitor (train.BackendExecutor): subscribes
    # to actor-death events for the training workers so a rank death
    # surfaces as TrainWorkerGroupError(dead_ranks=...) within seconds.
    # Kill switch: RAY_TPU_TRAIN_DEATH_MONITOR=0.
    "train_death_monitor": True,
    # Bucketed data-parallel gradient sync (train/ddp.py): partition the
    # grad pytree into size-targeted buckets and launch each bucket's
    # allreduce asynchronously so comm overlaps the rest of the backward
    # walk + pack/unpack. Kill switch RAY_TPU_TRAIN_BUCKET_DDP=0 =
    # legacy single synchronous allreduce over the whole flattened tree
    # (bit-identical at world 2 — see README "Overlapped gradient
    # sync" for the determinism contract).
    "train_bucket_ddp": True,
    "train_grad_bucket_bytes": 4 * 1024 * 1024,   # target bucket size
    # DDP sync shape (train/ddp.py): "allreduce" (legacy default —
    # every rank gets the full synced tree) or "reducescatter"
    # (ZeRO-style — each rank gets only its shard of every bucket;
    # pair with train.ddp.ZeroOptimizer for sharded optimizer state
    # and async param allgathers). The default stays bit-identical to
    # the pre-sharding behavior.
    "train_ddp_mode": "allreduce",
    # Sharded checkpointing (train/sharded_checkpoint.py). checkpoint_dir
    # is the generation root for standalone (non-trainer) use — trainers
    # plumb their storage_path instead. checkpoint_async moves the shard
    # disk write to a background thread (the two-phase commit still runs
    # at the caller's next harvest point); 0 = fully synchronous saves.
    # checkpoint_fsync=0 is a TEST-ONLY kill switch skipping the
    # fsync-file + fsync-dir calls in _private/atomic_write.py.
    "checkpoint_dir": "",
    "checkpoint_async": True,
    "checkpoint_fsync": True,
    # Pipelined host-collective data path (util/collective/host_backend):
    # one-way zero-copy segment sends, double-buffered so the reduce of
    # segment k overlaps the transfer of segment k+1. Pipeline kill
    # switch: RAY_TPU_COLLECTIVE_PIPELINE=0 restores the legacy
    # synchronous request/reply ring exactly.
    "collective_pipeline": True,
    "collective_segment_bytes": 4 * 1024 * 1024,  # ring segment size
    # Block-quantized wire formats (util/collective/wire.py): "off"
    # (default, bit-exact), "bf16" (2x smaller wire) or "int8" (per-
    # block float32 scales, ~4x smaller). Applies to float32 sum
    # allreduce/reducescatter segments on the pipelined path only;
    # everything else keeps the exact framing.
    # RAY_TPU_COLLECTIVE_WIRE_DTYPE mirrors RAY_TPU_COLLECTIVE_PIPELINE
    # as the per-group env knob.
    "collective_wire_dtype": "off",
    "collective_quant_block": 1024,   # int8 scale-block size (elements)
    # Same-node segment transport: ranks sharing a node exchange ring
    # segments as shared-memory store references (one copy in, zero-copy
    # pinned view out; forwarded hops pass the same object id) instead
    # of socket bytes. RAY_TPU_COLLECTIVE_SHM=0 forces sockets.
    "collective_shm": True,
    # Intra-host-first hierarchy: "auto" reduces within each host and
    # rings one leader per host when the membership spans >1 host with
    # co-located ranks (the DCN/ICI split); "1" forces it (tests), "0"
    # disables.
    "collective_hierarchy": "auto",
    # --- collective data-plane telemetry (util/collective/telemetry.py) ---
    "collective_timing_flush_s": 0.25,      # rank-timing flush cadence
    "collective_straggler_multiple": 3.0,   # lag > multiple * median lag
    "collective_straggler_min_lag_s": 0.05,  # floor: ignore µs jitter in
                                             # tight groups (median ~ 0)
    # --- multi-slice MPMD pipeline training (train/pipeline/) ---
    # Default wire format for inter-stage activation/grad hops: "off"
    # (exact), "bf16" (the classic half-width activation wire; ~2x
    # smaller inter-slice traffic, error <= 2^-8 * |x| per element) or
    # "int8" (per-block scales). PipelineConfig.wire_dtype overrides
    # per trainer; gradients always travel exact unless
    # pipeline_quantize_grads is also set.
    "pipeline_wire_dtype": "off",
    "pipeline_quantize_grads": False,
    # GPipe in-flight window: how many un-acked microbatch activations
    # a stage may have posted downstream before it parks for an ack
    # credit (bounds the receiver's mailbox/activation memory under
    # one-way pushes). 0 = unbounded. 1F1B ignores it — its warmup
    # depth (<= P - stage) is the inherent bound.
    "pipeline_inflight_window": 0,
    # --- step anatomy (parallel/step_anatomy.py) ---
    # Rolling-baseline step-time regression detector: compare p50 of the
    # last `window` steps against p50 of the window before it; fire a
    # STEP_REGRESSION event + counter when recent > multiple * baseline.
    # window=0 disables the detector (anatomy recording stays on).
    "step_regression_multiple": 2.0,
    "step_regression_window": 20,
    # --- device telemetry (_private/tpu_probe.py) ---
    "device_gauge_poll_s": 0.0,        # 0 = one probe at raylet start
                                       # (before workers own the chips);
                                       # recurring subprocess probes
                                       # contend with training workers
                                       # for TPU ownership — opt-in only.
                                       # Live in-use HBM comes from the
                                       # owning train workers in-process.
    "mesh_ici_axis_order": "dp,pp,ep,sp,tp",  # slowest→fastest varying axes
    # --- control plane at scale (cluster soak, _private/sim_cluster.py) ---
    # Death-feed coalescing: node deaths arriving within the window are
    # swept in ONE locked pass and (at >= gcs_death_batch_min of them)
    # fanned out as ONE `batch_dead` message + NODE_BATCH_DEAD event
    # instead of per-death broadcasts. 0 disables coalescing (every
    # death sweeps and broadcasts individually, the pre-PR-12 path).
    "gcs_death_coalesce_window_s": 0.05,
    "gcs_death_batch_min": 3,
    # Bounded admission on registration bursts: concurrent register_node
    # bodies beyond this queue on the gate (clients retry under the
    # unified policy if their wait exceeds the RPC timeout).
    "gcs_register_max_concurrent": 16,
    # Reconnect herd damping: every ReconnectingRpcClient sleeps
    # uniform(0, this) before dialing a lost endpoint, so a GCS restart
    # at 100 nodes doesn't eat one synchronized reconnect+replay storm.
    # 0 restores immediate reconnects.
    "gcs_reconnect_jitter_s": 0.2,
    # --- multi-tenant control plane (jobs/quotas/preemption, gcs.py) ---
    # Grace window between the PREEMPTION warning a victim placement
    # group receives and the GCS reclaiming its bundles: the Train
    # plane uses it to cut a checkpoint so the victim loses at most the
    # post-checkpoint steps, not the run.
    "gcs_preempt_grace_s": 5.0,
    # PlacementGroup.ready()/wait() ride the `pg_state` pubsub channel;
    # this is the cadence of the direct-RPC FALLBACK poll kept
    # underneath it (a missed transition can't hang a waiter past one
    # fallback period; PR 12's snapshot-resync covers feed gaps).
    "pg_wait_poll_fallback_s": 2.0,
    # --- misc ---
    "rpc_max_message_bytes": 512 * 1024 * 1024,
    "pubsub_poll_timeout_s": 30.0,
    "pubsub_max_mailbox": 1000,           # long-poll mailbox bound (drop-oldest)
    "pubsub_subscriber_timeout_s": 60.0,  # GC long-pollers gone this long
    "client_poll_slice_s": 60.0,          # ray:// get/wait re-poll granularity
    "actor_creation_rpc_timeout_s": 330.0,  # driver->raylet create_actor
                                          # RPC; raise when worker spawn
                                          # is slow (e.g. a wedged TPU
                                          # tunnel makes every python
                                          # startup pay a slow axon
                                          # plugin registration)
    "client_session_ttl_s": 60.0,         # ray:// reconnect grace: session
                                          # state survives a dropped socket
                                          # this long
    "client_chunk_bytes": 4 * 1024 * 1024,  # ray:// get/put chunk size —
                                          # bounds per-frame size on the
                                          # shared client socket
    "event_log_max_bytes": 16 * 1024 * 1024,
    "metrics_report_interval_ms": 2_000,
    "log_to_driver": True,
}


class _Config:
    def __init__(self):
        self._values = dict(_CONFIG_DEFS)
        self._system_overrides: set = set()
        for name, default in _CONFIG_DEFS.items():
            env = os.environ.get("RAY_TPU_" + name.upper())
            if env is not None:
                self._values[name] = _parse(env, default)

    def __getattr__(self, name: str):
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(name) from None

    def apply_system_config(self, overrides: Dict[str, Any] | None):
        if not overrides:
            return
        for k, v in overrides.items():
            if k not in self._values:
                raise ValueError(f"Unknown system config key: {k}")
            self._values[k] = v
            self._system_overrides.add(k)

    def system_override_env(self) -> Dict[str, str]:
        """init(system_config=...) overrides as RAY_TPU_<NAME> env vars.
        The raylet injects these into spawned worker processes so keys
        consumed worker-side (runtime_env_dir, serve stream timeout, ...)
        honor the driver's overrides — without this, system_config would
        silently apply only in the driver process."""
        out = {}
        for k in self._system_overrides:
            v = self._values[k]
            if isinstance(v, bool):
                v = "1" if v else "0"
            elif isinstance(v, (dict, list)):
                v = json.dumps(v)
            out["RAY_TPU_" + k.upper()] = str(v)
        return out

    def reset_system_config(self):
        """Drop init(system_config=...) overrides (called at shutdown so
        one driver's overrides don't leak into the next init in the same
        process — test isolation depends on this)."""
        for k in self._system_overrides:
            env = os.environ.get("RAY_TPU_" + k.upper())
            self._values[k] = (_parse(env, _CONFIG_DEFS[k])
                               if env is not None else _CONFIG_DEFS[k])
        self._system_overrides.clear()

    def snapshot(self) -> Dict[str, Any]:
        return dict(self._values)


def _parse(env: str, default: Any):
    if isinstance(default, bool):
        return env.lower() in ("1", "true", "yes")
    if isinstance(default, int):
        return int(env)
    if isinstance(default, float):
        return float(env)
    if isinstance(default, (dict, list)):
        return json.loads(env)
    return env


GlobalConfig = _Config()


def get_config(name: str):
    """Read one config value. Precedence (matching the module contract and
    the reference's RayConfig): init(system_config=...) > `RAY_TPU_<NAME>`
    env (read live, so tests/operators can set it after import) > default."""
    if name not in GlobalConfig._system_overrides:
        env = os.environ.get("RAY_TPU_" + name.upper())
        if env is not None:
            return _parse(env, _CONFIG_DEFS[name])
    return getattr(GlobalConfig, name)
