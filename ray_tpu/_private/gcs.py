"""GCS — the global control service.

TPU-native analog of the reference's gcs_server
(/root/reference/src/ray/gcs/gcs_server/gcs_server.cc:242-626): one process
holding the cluster-global state machines —

- node table + health (gcs_node_manager.h, gcs_health_check_manager.h):
  nodes register, heartbeat over their persistent RPC connection; connection
  loss marks the node dead and triggers actor/PG failover,
- actor table (gcs_actor_manager.h:270): registration, name→actor resolution,
  death notification, restart bookkeeping (ReconstructActor:495),
- internal KV (gcs_kv_manager.h): function table, cluster metadata,
- object directory (residual): locations live with OWNING WORKERS
  (worker_runtime.py owner-based directory, matching the reference's
  ownership_based_object_directory.h) — the GCS keeps only the free-path
  fan-out (owners hand it holder lists; it maps node ids to raylet
  connections) and legacy tables for observability stats,
- placement groups (gcs_placement_group_manager.h): bundle reservation with
  PACK/SPREAD/STRICT_PACK/STRICT_SPREAD over the node table,
- job table (gcs_job_manager.h, extended): named jobs carrying per-job
  resource QUOTAS and a PRIORITY CLASS — enforced at placement-group
  admission (all-or-nothing over the whole gang) and, via the `jobs`
  pubsub channel, at raylet lease grant; pending bundles are scheduled
  fair-share (dominant-resource, weighted by quota) off a
  priority-ordered queue, and a higher-priority gang that cannot place
  PREEMPTS the lowest-priority job's newest gang: a warning with a
  grace window (`gcs_preempt_grace_s`) lets the victim cut a
  checkpoint, then its bundles are reclaimed and it re-queues to
  resume when capacity returns (the Ray paper's multi-tenant
  GCS/distributed-scheduler arbitration, arXiv:1712.05889 §4),
- pubsub (pubsub_handler.h): actor state, node membership, placement
  group state (`pg_state`, with snapshot-resync) and job quota
  channels pushed to subscribed connections.

State is held in memory (the reference's default InMemoryStoreClient) and
made durable by a pluggable write-through store (gcs_store.py: sqlite or
append-only log — the reference's redis_store_client.h fault-tolerant
mode): every actor/PG/KV/job mutation lands in the store before the RPC
returns, and a restarted GCS reloads the tables then reconciles against
the raylets that re-register (_restore_from_store /
_reconcile_after_restart). The periodic snapshot file remains only as a
legacy fallback for deployments without a store.
"""
from __future__ import annotations

import os
import pickle
import threading
import time
import uuid

from ray_tpu._private import events as _events
from ray_tpu._private.protocol import RpcServer

PG_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD",
                 "SPREAD_ACROSS_SLICES")


class NodeInfo:
    def __init__(self, node_id: str, addr, resources: dict, meta: dict):
        self.node_id = node_id
        self.addr = tuple(addr)          # raylet RPC address
        self.resources = dict(resources)  # total resources
        self.meta = dict(meta)            # store name, spill dir, hostname...
        self.alive = True
        self.start_time = time.time()
        # id of the raylet connection that registered this incarnation:
        # a DELAYED disconnect of a superseded connection (half-open
        # socket erroring long after the node re-registered on a fresh
        # one) must not kill the live registration
        self.conn_id: str | None = None
        # live availability gossiped by the raylet (~600ms cadence); the PG
        # scheduler packs against this so bundles don't land on top of
        # non-PG load (reference: RaySyncer resource view)
        self.resources_reported: dict | None = None
        self.reported_at: float = 0.0
        self.pending_demand: list = []   # queued request shapes (autoscaler)
        self.busy: int = 0               # active leases + actors

    def snapshot(self) -> dict:
        return {
            "NodeID": self.node_id,
            "Alive": self.alive,
            "NodeManagerAddress": self.addr[0],
            "NodeManagerPort": self.addr[1],
            "Resources": dict(self.resources),
            "StartTime": self.start_time,
            **{k: v for k, v in self.meta.items()},
        }


class ActorInfo:
    def __init__(self, actor_id: bytes, spec: dict):
        self.actor_id = actor_id
        self.spec = spec                  # class blob, options, owner
        self.state = "PENDING_CREATION"   # ALIVE / RESTARTING / DEAD
        self.addr = None                  # worker rpc addr when alive
        self.node_id = None
        self.num_restarts = 0
        self.death_cause = None
        self.name = spec.get("name")
        self.namespace = spec.get("namespace", "default")

    def snapshot(self) -> dict:
        return {
            "ActorID": self.actor_id.hex(),
            "State": self.state,
            "Name": self.name or "",
            "Namespace": self.namespace,
            "NodeID": self.node_id,
            "NumRestarts": self.num_restarts,
            "ClassName": self.spec.get("class_name", ""),
            "DeathCause": self.death_cause,
        }


class JobInfo:
    """One named tenant in the scheduling plane: a resource quota (max
    concurrent usage per resource; empty = unlimited) and a priority
    class (higher preempts lower). Placement groups and leases carry
    the job NAME as a label; usage is derived from the PG table plus
    the per-job lease usage raylets gossip — the job table itself holds
    only policy + counters."""

    def __init__(self, name: str, quota: dict | None = None,
                 priority: int = 0):
        self.name = name
        self.quota = {k: float(v) for k, v in (quota or {}).items()}
        self.priority = int(priority)
        self.created_at = time.time()
        self.preemptions = 0          # gangs of THIS job preempted
        self.quota_rejections = 0     # admissions blocked on quota

    def snapshot(self) -> dict:
        return {
            "Job": self.name,
            "Priority": self.priority,
            "Quota": dict(self.quota),
            "CreatedAt": self.created_at,
            "Preemptions": self.preemptions,
            "QuotaRejections": self.quota_rejections,
        }


class PlacementGroupInfo:
    def __init__(self, pg_id: bytes, bundles: list[dict], strategy: str,
                 name: str = "", job: str = "", stages: list | None = None):
        self.pg_id = pg_id
        self.bundles = bundles            # list of resource dicts
        self.strategy = strategy
        self.name = name
        self.job = job or ""              # owning job label ("" = none)
        # per-bundle stage labels (SPREAD_ACROSS_SLICES): bundles sharing
        # a label form one stage sub-gang that must land contiguous
        # inside ONE slice, with distinct stages on distinct slices.
        # None = every bundle is its own stage (plain one-per-slice
        # spread). Parallel to `bundles` when given.
        self.stages = list(stages) if stages is not None else None
        self.state = "PENDING"            # CREATED / REMOVED / RESCHEDULING
        self.bundle_nodes: list[str | None] = [None] * len(bundles)
        self.commit_ts = 0.0              # when it became CREATED
        self.last_sched_attempt = 0.0     # rate-limits PENDING rescans
        self.created_seq = 0              # FIFO tiebreak in the queue
        self.quota_blocked = False        # rejection counted once per
        #                                   transition into the state
        self.preempt_deadline: float | None = None   # warned; fires then
        self.preemptor: bytes | None = None
        # post-fire re-queue holdoff: a just-preempted gang must not be
        # re-placed in the same scheduling pass that freed its bundles
        # (with no waiting preemptor it would bounce CREATED->CREATED
        # before its driver's teardown even observes the preemption)
        self.holdoff_until = 0.0
        # when (if ever) a preemption FIRED on this pg: the pg_state
        # resync snapshot carries it so a preemption monitor that
        # missed the PREEMPTED push can distinguish "my gang was
        # preempted" from "my gang is RESCHEDULING after a node death"
        # (the latter must charge the failure budget, not requeue free)
        self.preempted_at: float | None = None

    def snapshot(self) -> dict:
        return {
            "PlacementGroupID": self.pg_id.hex(),
            "Name": self.name,
            "Job": self.job,
            "State": self.state,
            "Strategy": self.strategy,
            "Bundles": [dict(b) for b in self.bundles],
            "BundleNodes": list(self.bundle_nodes),
            "Stages": list(self.stages) if self.stages is not None else None,
            "PreemptDeadline": self.preempt_deadline,
        }


class GcsServer:
    """RPC handler + state. One instance per cluster head."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 snapshot_path: str | None = None, store=None,
                 recovery_grace_s: float = 8.0):
        """store: a GcsStoreClient (or "sqlite:<path>"/"log:<path>" spec)
        making the actor/PG/KV/job tables durable with zero snapshot
        window — every mutation is written through before the RPC
        returns (reference: redis_store_client.h fault-tolerant mode).
        snapshot_path remains the legacy periodic-snapshot fallback."""
        self._lock = threading.RLock()
        self.nodes: dict[str, NodeInfo] = {}
        self.actors: dict[bytes, ActorInfo] = {}
        self.named_actors: dict[tuple[str, str], bytes] = {}
        self.kv: dict[str, dict[bytes, bytes]] = {}
        self.object_locations: dict[bytes, set[str]] = {}
        self.object_sizes: dict[bytes, int] = {}
        self.lost_objects: set[bytes] = set()  # created, then all copies died
        self.placement_groups: dict[bytes, PlacementGroupInfo] = {}
        self.jobs: dict[str, JobInfo] = {}   # removed via rpc_remove_job
        # Fair-share scheduling queue: ONLY the PENDING/RESCHEDULING pg
        # ids. Capacity events used to rescan the whole PG table
        # (O(hosts² · bundles) under this lock, per gossip tick); now
        # they walk this queue and return immediately when it is empty.
        self._pending_pgs: set[bytes] = set()
        self._pg_seq = 0                     # admission order tiebreak
        self._sched_pass_at = 0.0            # pass-level rate limit
        # Capacity reclaimed by recent preemption fires that the owning
        # raylets have not re-gossiped yet: [(fired_ts, bundles,
        # bundle_nodes, reflected_node_ids)]. _node_available_for_pg
        # adds these back so the fire's own queue re-drive doesn't warn
        # a fresh victim for capacity that already exists (fire-boundary
        # over-preemption); a node's first post-fire report consumes the
        # entry for that node (recorded in reflected_node_ids).
        self._preempt_freed: list[tuple] = []
        # node_id -> {job: {resource: amount}} gossiped by raylets
        # (lease-grant usage; popped when the node dies)
        self._lease_usage: dict[str, dict] = {}
        self._quota_over: set[str] = set()   # jobs currently over quota
        self._quota_refreshed = 0.0
        self.job_counter = 0
        self.cluster_id = uuid.uuid4().hex
        self._subscribers: dict[str, list] = {}   # channel -> [Connection]
        # long-poll delivery mode (reference: src/ray/pubsub/publisher.h) —
        # for subscribers that can't hold an inbound push channel
        from ray_tpu._private.pubsub import Publisher

        self._long_poll = Publisher()
        # long-poll handlers by delegation (RpcServer._lookup getattrs the
        # instance, so bound methods work as rpc_ handlers)
        self.rpc_psub_subscribe = self._long_poll.rpc_psub_subscribe
        self.rpc_psub_unsubscribe = self._long_poll.rpc_psub_unsubscribe
        self.rpc_psub_poll = self._long_poll.rpc_psub_poll
        self.rpc_psub_resync = self._long_poll.rpc_psub_resync
        # snapshot-resync sources: a subscriber that overflowed its
        # mailbox past the gap counter reconverges from these instead of
        # permanently missing the dropped head of the stream
        self._long_poll.set_snapshot_provider(
            "actors", self._actors_resync_snapshot)
        self._long_poll.set_snapshot_provider(
            "nodes", self._nodes_resync_snapshot)
        self._long_poll.set_snapshot_provider(
            "pg_state", self._pg_state_resync_snapshot)
        # Death-feed coalescing (cluster-scale soak, PR 12): simultaneous
        # node deaths (a rack loss, a seeded 10% mass kill) within the
        # coalesce window are swept in ONE locked pass and fanned out as
        # ONE batch message instead of per-death broadcasts — at 100
        # subscribers x k deaths that is n pushes instead of n*k.
        self._death_lock = threading.Lock()
        # node_id -> (reason, observed NodeInfo incarnation or None)
        self._pending_deaths: dict[str, tuple] = {}
        self._death_flusher_active = False
        self._fanout_stats = {"death_batches": 0, "deaths_coalesced": 0,
                              "max_death_batch": 0,
                              "register_throttled": 0,
                              "last_fanout_s": 0.0}
        # Bounded admission for registration bursts (a reconnect storm
        # after a GCS restart): at most this many register_node bodies
        # run concurrently; the rest queue on the gate, keeping the
        # node-table lock and the "alive" publish fanout from being
        # stampeded by 100 simultaneous re-registrations.
        from ray_tpu._private.config import get_config

        self._register_gate = threading.BoundedSemaphore(
            max(1, int(get_config("gcs_register_max_concurrent"))))
        self._snapshot_path = snapshot_path
        if isinstance(store, str):
            from ray_tpu._private.gcs_store import make_store

            store = make_store(store)
        self._store = store
        self._recovery_grace_s = recovery_grace_s
        self._restored = False
        # actor_started announcements seen by THIS process — after a
        # restore, an ALIVE actor whose raylet came back but never
        # re-announced it is dead (its worker died during the outage)
        self._reannounced: set[bytes] = set()
        # node registrations seen by THIS process — after a restore, a
        # restored-alive node that never re-registered within the grace
        # window died during the outage; reconcile marks it dead
        # THROUGH the death pipeline so survivors' death feeds learn
        # about outage-window deaths instead of watching the node
        # silently vanish from the table (soak round 12 finding)
        self._reregistered: set[str] = set()
        if store is not None:
            self._restore_from_store()
        self._server = RpcServer(self, host, port)
        if not self._restored and snapshot_path and \
                os.path.exists(snapshot_path):
            self._load_snapshot()
        if store is not None and not self._restored:
            self._persist_meta()   # cluster_id survives the first restart

    def start(self):
        self._server.start()
        if self._restored:
            # raylets reconnect + re-register within their gossip tick;
            # after the grace window, reconcile restored state against
            # who actually came back (reference: node_manager.cc:1179
            # HandleNotifyGCSRestart + gcs_actor_manager restart-on-
            # -node-death)
            threading.Thread(target=self._reconcile_after_restart,
                             daemon=True, name="gcs-recovery").start()
        if self._snapshot_path:
            # periodic durability (the reference's Redis-backed tables
            # analog): metadata survives a GCS restart
            t = threading.Thread(target=self._snapshot_loop, daemon=True,
                                 name="gcs-snapshot")
            t.start()
        return self

    def _snapshot_loop(self):
        while not self._server._stopped:
            time.sleep(5.0)
            try:
                self.rpc_save_snapshot()
            except Exception:
                pass

    @property
    def addr(self):
        return self._server.addr

    def stop(self):
        self._server.stop()
        if self._store is not None:
            self._store.close()

    # ---- connection liveness → node failure detection ----------------------

    def on_connect(self, conn):
        pass

    def on_disconnect(self, conn):
        node_id = conn.meta.get("node_id")
        if node_id:
            self._mark_node_dead(node_id, "raylet connection lost",
                                 conn_id=getattr(conn, "id", None))

    def _mark_node_dead(self, node_id: str, reason: str,
                        conn_id: str | None = None):
        """Single-death entry point (connection loss, drain). With a
        coalesce window configured, deaths arriving within the window
        are batched through ``_mark_nodes_dead`` — a seeded mass kill
        tears down many connections in the same instant, and sweeping/
        broadcasting them one at a time is the O(n·k) path the soak
        measures.

        The pending entry pins the NodeInfo INCARNATION it observed:
        a node that re-registers inside the coalesce window installs a
        fresh NodeInfo, and the deferred sweep must not mark the new
        registration dead (the node would believe it is registered and
        never retry — a permanently wrong cluster view). ``conn_id``
        (connection-loss deaths) closes the remaining hole: a DELAYED
        disconnect of a connection the node has already replaced
        observes the FRESH incarnation here, so the death only counts
        if the dying connection still owns the registration."""
        from ray_tpu._private.config import get_config

        incarnation = self.nodes.get(node_id)
        if conn_id is not None and incarnation is not None \
                and incarnation.conn_id != conn_id:
            return   # superseded connection: the node re-registered
        window = float(get_config("gcs_death_coalesce_window_s"))
        if window <= 0:
            self._mark_nodes_dead({node_id: (reason, incarnation)})
            return
        with self._death_lock:
            # plain assignment, not setdefault: a die→re-register→die
            # sequence inside one window must pin the FRESHEST
            # incarnation or the sweep's identity check would skip the
            # second death and leave the node alive forever
            self._pending_deaths[node_id] = (reason, incarnation)
            if self._death_flusher_active:
                return   # an open window will sweep this death too
            self._death_flusher_active = True
        threading.Thread(target=self._death_flush_after, args=(window,),
                         daemon=True, name="gcs-death-flush").start()

    def _death_flush_after(self, window: float):
        time.sleep(window)
        with self._death_lock:
            deaths, self._pending_deaths = self._pending_deaths, {}
            self._death_flusher_active = False
        if deaths:
            self._mark_nodes_dead(deaths)

    def _mark_nodes_dead(self, deaths: dict):
        """Sweep + fan out a set of node deaths. ``deaths`` maps
        node_id -> (reason, observed NodeInfo-or-None): an entry only
        applies if the table still holds the SAME NodeInfo object —
        a re-registration (always a fresh NodeInfo) between observation
        and this sweep supersedes the death. ONE locked pass covers
        the whole batch (the owned-value sweep walks object_locations
        once, not once per death), and the broadcast happens OFF-lock on
        a snapshot of the transitions — at 100 nodes × many refs the
        old under-lock per-death walk is exactly what RTL101 exists to
        keep out of hot control paths. A batch of >=
        ``gcs_death_batch_min`` deaths fans out as ONE coalesced
        ``batch_dead`` message + ``NODE_BATCH_DEAD`` event instead of
        per-death broadcasts."""
        from ray_tpu._private.config import get_config

        to_restart: list[bytes] = []
        fanout: list[tuple[str, dict]] = []   # deferred (channel, message)
        dead: dict[str, str] = {}
        with self._lock:
            for node_id, (reason, incarnation) in deaths.items():
                node = self.nodes.get(node_id)
                if node is None or not node.alive:
                    continue
                if node is not incarnation:
                    continue   # re-registered since the death was seen
                node.alive = False
                self._persist_node(node)
                self._reregistered.discard(node_id)
                dead[node_id] = reason
            if not dead:
                return
            dead_ids = set(dead)
            # Objects whose only copies were there are gone — record them
            # as lost. Owners consume this signal in CoreWorker._fetch_bytes
            # / rpc_get_owned_value: if they hold lineage for the object
            # they re-execute the creating task (_maybe_reconstruct,
            # reference object_recovery_manager.h:30), else ObjectLostError.
            for oid, locs in self.object_locations.items():
                if locs & dead_ids:
                    locs -= dead_ids
                    if not locs and oid in self.object_sizes:
                        self.lost_objects.add(oid)
            for actor in self.actors.values():
                if actor.node_id not in dead_ids:
                    continue
                if actor.state in ("ALIVE", "PENDING_CREATION"):
                    decision = self._on_actor_failure(
                        actor, f"node {actor.node_id} died: "
                               f"{dead[actor.node_id]}", fanout=fanout)
                    if decision.get("restart"):
                        to_restart.append(actor.actor_id)
                elif actor.state == "RESTARTING":
                    # Its restart was being driven by a raylet that just
                    # died — re-drive on a survivor without charging
                    # another restart against the budget.
                    to_restart.append(actor.actor_id)
            for pg in self.placement_groups.values():
                if pg.state in ("CREATED", "PENDING") and \
                        any(n in dead_ids for n in pg.bundle_nodes):
                    pg.state = "RESCHEDULING"
                    # node death supersedes an in-flight preemption (the
                    # fire would find state != CREATED and abort anyway)
                    pg.preempt_deadline = None
                    pg.preemptor = None
                    self._pending_pgs.add(pg.pg_id)
                    self._persist_pg(pg)
                    fanout.append(("pg_state", {
                        "event": "state", "pg_id": pg.pg_id,
                        "state": "RESCHEDULING", "job": pg.job}))
            for node_id in dead_ids:
                # per-job lease usage gossiped by a dead raylet is gone
                # with its leases (RTL106: keyed per node, removed here)
                self._lease_usage.pop(node_id, None)
        # ---- fanout, OFF the GCS lock, on the snapshot above ----
        t0 = time.monotonic()
        batch_min = max(2, int(get_config("gcs_death_batch_min")))
        node_ids = sorted(dead)
        if len(dead) >= batch_min:
            self._publish("nodes", {"event": "batch_dead",
                                    "node_ids": node_ids,
                                    "reasons": dict(dead)})
            _events.record("NODE_BATCH_DEAD", node_ids=node_ids,
                           count=len(node_ids),
                           reasons=sorted(set(dead.values())))
            # per-node lifecycle events STILL fire (ring appends are
            # ~µs): consumers pairing ALIVE/DEAD node_state events
            # (`ray-tpu events --kind node_state`) must not see
            # batched nodes as alive-forever — only the per-death
            # BROADCAST is coalesced
            for node_id in node_ids:
                _events.record("node_state", node_id=node_id,
                               state="DEAD", reason=dead[node_id],
                               batched=True)
            with self._death_lock:
                st = self._fanout_stats
                st["death_batches"] += 1
                st["deaths_coalesced"] += len(dead)
                st["max_death_batch"] = max(st["max_death_batch"],
                                            len(dead))
        else:
            for node_id in node_ids:
                self._publish("nodes", {"event": "dead",
                                        "node_id": node_id,
                                        "reason": dead[node_id]})
                _events.record("node_state", node_id=node_id,
                               state="DEAD", reason=dead[node_id])
        # deferred actor transitions: batched per channel through
        # publish_many (one Publisher lock hold + wakeup per channel,
        # not per transition) + per-message conn pushes
        by_channel: dict[str, list] = {}
        for channel, message in fanout:
            by_channel.setdefault(channel, []).append(message)
        for channel, messages in by_channel.items():
            self._long_poll.publish_many(channel, messages)
            for conn_msg in messages:
                self._push_subscribers(channel, conn_msg)
        from ray_tpu._private import telemetry as _tm

        fanout_s = time.monotonic() - t0
        with self._death_lock:
            self._fanout_stats["last_fanout_s"] = fanout_s
        if _tm.ENABLED:
            _tm.observe("ray_tpu_gcs_death_fanout_seconds", fanout_s)
        # The dead nodes' raylets can't re-create their actors — pick a
        # surviving raylet to do it (reference: GcsActorScheduler re-leases
        # from another node, gcs_actor_scheduler.h).
        for actor_id in to_restart:
            self._push_recreate(actor_id)

    def _push_recreate(self, actor_id: bytes):
        with self._lock:
            alive_ids = {nid for nid, n in self.nodes.items() if n.alive}
        for conn in self._server.connections():
            if conn.meta.get("node_id") in alive_ids and conn.alive:
                conn.push("recreate_actor", actor_id=actor_id)
                return

    # ---- nodes -------------------------------------------------------------

    def rpc_register_node(self, conn, node_id: str, addr, resources: dict,
                          meta: dict):
        # Bounded admission: a reconnect storm (GCS restart at 100
        # nodes) otherwise runs 100 registration bodies + "alive"
        # publish fanouts concurrently. Excess registrations QUEUE on
        # the gate — register_node is retry-safe, so a client whose
        # wait exceeds its RPC timeout simply retries under its policy.
        from ray_tpu._private.config import get_config

        throttled = not self._register_gate.acquire(blocking=False)
        if throttled:
            with self._death_lock:
                self._fanout_stats["register_throttled"] += 1
            from ray_tpu._private import telemetry as _tm

            if _tm.ENABLED:
                _tm.counter_inc("ray_tpu_gcs_register_throttled_total")
            if not self._register_gate.acquire(
                    timeout=float(get_config("gcs_rpc_timeout_s"))):
                raise TimeoutError(
                    "GCS registration admission timed out under a "
                    "registration storm; retry")
        try:
            with self._lock:
                node = NodeInfo(node_id, addr, resources, meta)
                node.conn_id = getattr(conn, "id", None)
                self.nodes[node_id] = node
                conn.meta["node_id"] = node_id
                self._reregistered.add(node_id)
                self._persist_node(node)
                snapshot = node.snapshot()
            self._publish("nodes", {"event": "alive", "node_id": node_id,
                                    "snapshot": snapshot})
            _events.record("node_state", node_id=node_id, state="ALIVE",
                           hostname=meta.get("hostname"))
            return {"cluster_id": self.cluster_id}
        finally:
            self._register_gate.release()

    def rpc_report_resources(self, conn, node_id: str, available: dict,
                             pending_demand: list | None = None,
                             busy: int = 0,
                             job_busy: dict | None = None):
        with self._lock:
            node = self.nodes.get(node_id)
            if node is not None:
                node.resources_reported = dict(available)
                node.reported_at = time.time()
                node.pending_demand = list(pending_demand or [])
                node.busy = int(busy)
                if job_busy is not None:
                    # per-job lease usage on this node (quota enforcement
                    # input); empty dict clears the entry
                    if job_busy:
                        self._lease_usage[node_id] = {
                            j: dict(r) for j, r in job_busy.items()}
                    else:
                        self._lease_usage.pop(node_id, None)
            # fresh capacity may unblock pending placement groups. This
            # used to rescan the WHOLE PG table (O(hosts² · bundles)
            # under the GCS lock, per ~600ms gossip tick per raylet);
            # now it walks only the priority-ordered pending queue and
            # returns immediately when it is empty.
            self._maybe_schedule_pending()
            # rate-limited even when lease usage changed: with
            # job-labeled task churn most gossip pushes change SOME
            # node's job_busy, and a forced O(jobs · PGs) recompute per
            # push is the per-tick hot-spot class this PR removes from
            # the PG path — the raylet throttle is documented as
            # eventually consistent by one beat anyway
            self._refresh_quota_throttle_locked()
        return True

    def rpc_get_cluster_load(self, conn):
        """The autoscaler's input (reference: LoadMetrics built from GCS
        resource reports): per-node availability + queued demand shapes +
        unplaced placement-group bundles."""
        with self._lock:
            nodes = []
            for n in self.nodes.values():
                nodes.append({
                    "NodeID": n.node_id,
                    "Alive": n.alive,
                    "Resources": dict(n.resources),
                    "Available": dict(n.resources_reported
                                      if n.resources_reported is not None
                                      else n.resources),
                    "PendingDemand": list(getattr(n, "pending_demand", [])),
                    "Busy": int(getattr(n, "busy", 0)),
                    "ReportedAt": n.reported_at,
                })
            pending_bundles = []
            pending_pgs = []
            for pg in self.placement_groups.values():
                if pg.state in ("PENDING", "RESCHEDULING"):
                    pending_bundles.extend(dict(b) for b in pg.bundles)
                    # strategy-aware form: the demand binpacker needs to
                    # know STRICT_PACK must co-locate and STRICT_SPREAD
                    # must anti-affine (resource_demand_scheduler.py:171)
                    pending_pgs.append({
                        "pg_id": pg.pg_id.hex(),
                        "strategy": pg.strategy,
                        "bundles": [dict(b) for b in pg.bundles],
                    })
            return {"nodes": nodes, "pending_pg_bundles": pending_bundles,
                    "pending_pgs": pending_pgs}

    def rpc_drain_node(self, conn, node_id: str):
        self._mark_node_dead(node_id, "drained")
        return True

    def rpc_get_nodes(self, conn):
        with self._lock:
            return [n.snapshot() for n in self.nodes.values()]

    def rpc_get_node_addr(self, conn, node_id: str):
        """Single-node address lookup — the hot consumers (raylet
        spillback/PG target resolution, remote lease return) used to
        pull the FULL node table to resolve one id, an O(n)-payload
        round trip per call that the 100-node soak turns into the
        dominant control-plane traffic. Returns (host, port) or None
        when the node is unknown/dead."""
        with self._lock:
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                return None
            return tuple(node.addr)

    def rpc_cluster_resources(self, conn):
        with self._lock:
            total: dict[str, float] = {}
            for n in self.nodes.values():
                if not n.alive:
                    continue
                for k, v in n.resources.items():
                    total[k] = total.get(k, 0) + v
            return total

    def rpc_next_job_id(self, conn):
        with self._lock:
            self.job_counter += 1
            self._persist_meta()
            return self.job_counter

    # ---- named jobs: quotas, priority, fair share ---------------------------
    # The multi-tenant arbitration layer (reference:
    # gcs_job_manager.h extended per the Ray paper's §4 scheduler).
    # Enforcement points: placement-group admission here (all-or-
    # nothing over the gang), lease grant at the raylets (they ride the
    # `jobs` channel's over-quota set). Fair share: pending bundles are
    # served highest priority first, then lowest dominant resource
    # share (usage / quota, falling back to usage / cluster total).

    @staticmethod
    def _validate_quota(quota: dict | None) -> dict:
        from ray_tpu.exceptions import JobQuotaError

        out = {}
        for k, v in (quota or {}).items():
            if not isinstance(k, str):
                raise JobQuotaError(f"quota resource name {k!r} not a str")
            try:
                amt = float(v)
            except (TypeError, ValueError):
                raise JobQuotaError(
                    f"quota amount {v!r} for {k!r} is not a number") \
                    from None
            if amt < 0:
                raise JobQuotaError(f"quota {k!r} amount {amt} < 0")
            out[k] = amt
        return out

    def rpc_register_job(self, conn, name: str, quota: dict | None = None,
                         priority: int | None = None):
        """Create-or-update (idempotent: clients retry across GCS
        restarts; re-registering updates quota/priority in place — a
        quota RAISED at runtime immediately re-drives the pending queue
        so a quota-blocked gang unblocks without waiting for a
        capacity event). ``None`` for quota/priority means KEEP the
        existing value (default priority 0 on create) — a quota-only
        re-register must not silently demote the job to priority 0 and
        hand its gangs to the preemptor (review finding)."""
        from ray_tpu.exceptions import JobQuotaError

        if not name or not isinstance(name, str):
            raise JobQuotaError(f"job name must be a non-empty str, "
                                f"got {name!r}")
        quota = self._validate_quota(quota) if quota is not None else None
        with self._lock:
            job = self.jobs.get(name)
            created = job is None
            if created:
                job = JobInfo(name, quota,
                              0 if priority is None else priority)
                self.jobs[name] = job
            else:
                if quota is not None:
                    job.quota = quota
                if priority is not None:
                    job.priority = int(priority)
            self._persist_job(job)
            self._refresh_quota_throttle_locked(force=True)
            self._maybe_schedule_pending(force=True)
            snap = self._job_snapshot_locked(job)
        if created:
            _events.record("JOB_REGISTERED", job=name,
                           priority=0 if priority is None
                           else int(priority), quota=quota or {})
        return snap

    def rpc_update_job(self, conn, name: str, quota: dict | None = None,
                       priority: int | None = None):
        """Runtime policy change for a registered job; raising a quota
        unblocks queued gangs on the spot (tested edge)."""
        from ray_tpu.exceptions import JobQuotaError

        quota = self._validate_quota(quota) if quota is not None else None
        with self._lock:
            job = self.jobs.get(name)
            if job is None:
                raise JobQuotaError(f"unknown job {name!r}")
            if quota is not None:
                job.quota = quota
            if priority is not None:
                job.priority = int(priority)
            self._persist_job(job)
            self._refresh_quota_throttle_locked(force=True)
            self._maybe_schedule_pending(force=True)
            return self._job_snapshot_locked(job)

    def rpc_remove_job(self, conn, name: str):
        """Retire a job's policy entry (its PGs keep the label; with no
        JobInfo they fall back to priority 0 / no quota)."""
        with self._lock:
            existed = self.jobs.pop(name, None) is not None
            if existed:
                if self._store is not None:
                    self._store.delete("jobs", name)
                # always clear the throttle state — a storeless GCS must
                # not keep throttling a retired job's leases
                self._refresh_quota_throttle_locked(force=True)
        return existed

    def rpc_get_job_throttle(self, conn):
        """The current over-quota job set — raylets SEED their lease
        throttle view from this at (re-)registration: the `jobs`
        channel only publishes on CHANGE, so a node joining (or
        healing across a GCS restart) while the set is stable would
        otherwise never learn it and grant past-quota leases from
        exactly the capacity everyone else is throttling."""
        with self._lock:
            return sorted(self._quota_over)

    def rpc_get_job(self, conn, name: str):
        with self._lock:
            job = self.jobs.get(name)
            return self._job_snapshot_locked(job) if job else None

    def rpc_list_jobs(self, conn):
        """Per-job policy + live usage rollup — `summarize_jobs()` /
        `ray-tpu jobs` source. Jobs seen only as PG labels (never
        registered) appear with default policy so usage is never
        hidden."""
        with self._lock:
            labels = {pg.job for pg in self.placement_groups.values()
                      if pg.job and pg.state != "REMOVED"}
            rows = [self._job_snapshot_locked(j)
                    for j in self.jobs.values()]
            rows.extend(self._job_snapshot_locked(JobInfo(name))
                        for name in sorted(labels - set(self.jobs)))
            return rows

    def _job_snapshot_locked(self, job: "JobInfo") -> dict:
        snap = job.snapshot()
        usage = self._job_usage(job.name)
        pgs = {"created": 0, "pending": 0}
        for pg in self.placement_groups.values():
            if pg.job != job.name:
                continue
            if pg.state == "CREATED":
                pgs["created"] += 1
            elif pg.state in ("PENDING", "RESCHEDULING"):
                pgs["pending"] += 1
        snap.update({
            "Usage": usage,
            "DominantShare": self._dominant_share(job.name),
            "PlacementGroups": pgs,
            "OverQuota": any(usage.get(k, 0.0) > cap + 1e-9
                             for k, cap in job.quota.items()),
        })
        return snap

    def _job_usage(self, name: str) -> dict:
        """Cluster-wide usage attributed to a job: bundles of its
        CREATED placement groups plus the per-job lease usage raylets
        gossip. Caller holds self._lock."""
        usage: dict[str, float] = {}
        for pg in self.placement_groups.values():
            if pg.job != name or pg.state != "CREATED":
                continue
            for b in pg.bundles:
                for k, v in b.items():
                    usage[k] = usage.get(k, 0.0) + v
        for per_job in self._lease_usage.values():
            for k, v in (per_job.get(name) or {}).items():
                usage[k] = usage.get(k, 0.0) + v
        return usage

    def _pg_priority(self, pg: "PlacementGroupInfo") -> int:
        job = self.jobs.get(pg.job) if pg.job else None
        return job.priority if job is not None else 0

    def _dominant_share(self, name: str) -> float:
        """Dominant-resource share: max over resources of
        usage / weight, weight = the job's quota for that resource when
        set, else the cluster total (DRF over quota-normalized
        capacity). Caller holds self._lock."""
        if not name:
            return 0.0
        job = self.jobs.get(name)
        usage = self._job_usage(name)
        if not usage:
            return 0.0
        totals: dict[str, float] = {}
        for n in self.nodes.values():
            if n.alive:
                for k, v in n.resources.items():
                    totals[k] = totals.get(k, 0.0) + v
        share = 0.0
        for k, v in usage.items():
            weight = 0.0
            if job is not None and job.quota.get(k):
                weight = job.quota[k]
            elif totals.get(k):
                weight = totals[k]
            if weight > 0:
                share = max(share, v / weight)
        return share

    def _quota_blocked_pg(self, pg: "PlacementGroupInfo") -> bool:
        """Would admitting this WHOLE gang push its job over quota?
        All-or-nothing: the Nth bundle exceeding the quota blocks the
        entire gang (a partial gang is useless to a collective
        workload). Caller holds self._lock."""
        job = self.jobs.get(pg.job) if pg.job else None
        if job is None or not job.quota:
            return False
        usage = self._job_usage(pg.job)
        demand: dict[str, float] = {}
        for b in pg.bundles:
            for k, v in b.items():
                demand[k] = demand.get(k, 0.0) + v
        return any(usage.get(k, 0.0) + demand.get(k, 0.0) > cap + 1e-9
                   for k, cap in job.quota.items())

    def _refresh_quota_throttle_locked(self, force: bool = False):
        """Recompute the over-quota job set and publish it on the
        `jobs` channel when it changes — raylets throttle lease grants
        for listed jobs. Rate-limited off the gossip path (per-call
        cost is O(jobs · PGs)); `force` bypasses for policy changes."""
        now = time.monotonic()
        if not force and now - self._quota_refreshed < 0.25:
            return
        self._quota_refreshed = now
        over = set()
        for name, job in self.jobs.items():
            if not job.quota:
                continue
            usage = self._job_usage(name)
            if any(usage.get(k, 0.0) > cap + 1e-9
                   for k, cap in job.quota.items()):
                over.add(name)
        if over != self._quota_over:
            self._quota_over = over
            self._publish("jobs", {"event": "quota",
                                   "over": sorted(over)})

    def _persist_job(self, job: "JobInfo"):
        if self._store is None:
            return
        self._store.put("jobs", job.name, pickle.dumps({
            "name": job.name, "quota": job.quota,
            "priority": job.priority, "created_at": job.created_at,
            "preemptions": job.preemptions,
            "quota_rejections": job.quota_rejections}))

    # ---- KV (function table, metadata) -------------------------------------

    def rpc_kv_put(self, conn, ns: str, key: bytes, value: bytes,
                   overwrite: bool = True):
        with self._lock:
            table = self.kv.setdefault(ns, {})
            if not overwrite and key in table:
                return False
            table[key] = value
            self._persist_kv(ns, key, value)
            return True

    def rpc_kv_get(self, conn, ns: str, key: bytes):
        with self._lock:
            return self.kv.get(ns, {}).get(key)

    def rpc_kv_del(self, conn, ns: str, key: bytes):
        with self._lock:
            existed = self.kv.get(ns, {}).pop(key, None) is not None
            if existed:
                self._persist_kv(ns, key, None)
            return existed

    def rpc_kv_exists(self, conn, ns: str, key: bytes):
        with self._lock:
            return key in self.kv.get(ns, {})

    def rpc_kv_keys(self, conn, ns: str, prefix: bytes = b""):
        with self._lock:
            return [k for k in self.kv.get(ns, {}) if k.startswith(prefix)]

    # ---- object directory --------------------------------------------------

    def rpc_add_object_location(self, conn, object_id: bytes, node_id: str,
                                size: int = 0):
        with self._lock:
            self.object_locations.setdefault(object_id, set()).add(node_id)
            self.lost_objects.discard(object_id)  # recreated copies revive it
            if size:
                self.object_sizes[object_id] = size
        return True

    def rpc_remove_object_location(self, conn, object_id: bytes, node_id: str):
        with self._lock:
            locs = self.object_locations.get(object_id)
            if locs:
                locs.discard(node_id)
        return True

    def rpc_get_object_locations(self, conn, object_id: bytes):
        with self._lock:
            node_ids = [n for n in self.object_locations.get(object_id, ())
                        if self.nodes.get(n) and self.nodes[n].alive]
            return {
                "nodes": [self.nodes[n].snapshot() for n in node_ids],
                "size": self.object_sizes.get(object_id, 0),
                "lost": object_id in self.lost_objects,
            }

    def rpc_free_objects(self, conn, object_ids: list[bytes],
                         locations: dict | None = None):
        """Broadcast deletion to every node holding a copy. `locations`
        (oid → [node_id]) comes from the OWNER's directory — the GCS's
        residual table only supplements it (owner-based directory: the GCS
        no longer tracks per-object locations itself)."""
        with self._lock:
            targets: dict[str, list[bytes]] = {}
            for oid in object_ids:
                holders = set(self.object_locations.pop(oid, ()))
                if locations:
                    holders |= set(locations.get(oid, ()))
                for node_id in holders:
                    targets.setdefault(node_id, []).append(oid)
                self.object_sizes.pop(oid, None)
            conns = {c.meta.get("node_id"): c
                     for c in self._server.connections()}
        retry: list[tuple[str, list[bytes]]] = []
        for node_id, oids in targets.items():
            c = conns.get(node_id)
            if c is None:
                retry.append((node_id, oids))
                continue
            try:
                c.push("free_objects", object_ids=oids)
            except Exception:
                retry.append((node_id, oids))
        if retry:
            self._retry_free_fanout(retry)
        return True

    def _retry_free_fanout(self, retry: list):
        """The fan-out hop of the free pipeline is one-way: a missing or
        broken raylet connection silently strands the objects on their
        holder node. Count every such drop (the
        `ray_tpu_store_frees_dropped_total{stage=gcs_fanout}` smoking
        gun), and — behind config `store_free_resend` — re-resolve the
        connection and re-push ONCE, best-effort (the leak sweep remains
        the backstop for deletes this still loses)."""
        from ray_tpu._private import telemetry as _tm
        from ray_tpu._private.config import get_config

        resend = 0
        try:
            resend = int(get_config("store_free_resend"))
        except Exception:
            pass
        if resend > 0:
            with self._lock:
                conns = {c.meta.get("node_id"): c
                         for c in self._server.connections()}
            still: list = []
            for node_id, oids in retry:
                c = conns.get(node_id)
                if c is None:
                    still.append((node_id, oids))
                    continue
                try:
                    c.push("free_objects", object_ids=oids)
                    _tm.counter_inc("ray_tpu_store_free_resends_total",
                                    float(len(oids)))
                except Exception:
                    still.append((node_id, oids))
            retry = still
        dropped = sum(len(oids) for _, oids in retry)
        if dropped:
            _tm.counter_inc("ray_tpu_store_frees_dropped_total",
                            float(dropped), tags={"stage": "gcs_fanout"})

    # ---- actors ------------------------------------------------------------

    def rpc_register_actor(self, conn, actor_id: bytes, spec: dict):
        with self._lock:
            if actor_id in self.actors:
                # replay of our own registration (client retried across a
                # GCS restart that had already applied it) — idempotent
                return {"existing": None}
            name = spec.get("name")
            ns = spec.get("namespace", "default")
            if name:
                existing_id = self.named_actors.get((ns, name))
                if existing_id is not None:
                    existing = self.actors.get(existing_id)
                    if existing and existing.state != "DEAD":
                        if spec.get("get_if_exists"):
                            return {"existing": existing.snapshot()}
                        raise ValueError(
                            f"actor name {name!r} already taken in "
                            f"namespace {ns!r}")
            info = ActorInfo(actor_id, spec)
            self.actors[actor_id] = info
            if name:
                self.named_actors[(ns, name)] = actor_id
            self._persist_actor(info)
        _events.record("actor_state", actor_id=actor_id.hex(),
                       state="REGISTERED",
                       class_name=spec.get("class_name", ""))
        return {"existing": None}

    def rpc_actor_started(self, conn, actor_id: bytes, addr, node_id: str):
        with self._lock:
            actor = self.actors.get(actor_id)
            if actor is None:
                return False
            actor.state = "ALIVE"
            actor.addr = tuple(addr)
            actor.node_id = node_id
            self._reannounced.add(actor_id)
            self._persist_actor(actor)
        self._publish("actors", {"event": "alive",
                                 "actor_id": actor_id,
                                 "addr": tuple(addr)})
        _events.record("actor_state", actor_id=actor_id.hex(),
                       state="ALIVE", node_id=node_id)
        return True

    def rpc_actor_failed(self, conn, actor_id: bytes, reason: str):
        with self._lock:
            actor = self.actors.get(actor_id)
            if actor is None:
                return None
            return self._on_actor_failure(actor, reason)

    def rpc_actor_exited(self, conn, actor_id: bytes):
        """Graceful termination (__ray_terminate__ / kill(no_restart))."""
        with self._lock:
            actor = self.actors.get(actor_id)
            if actor is None:
                return False
            actor.state = "DEAD"
            actor.death_cause = "exited"
            self._drop_name(actor)
            self._persist_actor(actor)
        self._publish("actors", {"event": "dead", "actor_id": actor_id,
                                 "reason": "exited"})
        _events.record("actor_state", actor_id=actor_id.hex(),
                       state="DEAD", reason="exited")
        return True

    def _drop_name(self, actor: ActorInfo):
        if actor.name and self.named_actors.get(
                (actor.namespace, actor.name)) == actor.actor_id:
            del self.named_actors[(actor.namespace, actor.name)]
        # terminal transitions also retire the re-announce bookkeeping:
        # keyed by actor id with no other removal path, this set grew by
        # one entry per actor for the GCS lifetime (the RTL106 class)
        self._reannounced.discard(actor.actor_id)

    def _on_actor_failure(self, actor: ActorInfo, reason: str,
                          fanout: list | None = None):
        """Returns restart decision; caller-side raylet re-creates. Mirrors
        GcsActorManager::ReconstructActor (gcs_actor_manager.h:495).

        ``fanout`` (batch node-death path) collects the pubsub messages
        for the caller to publish AFTER releasing the GCS lock — a mass
        kill transitions many actors, and pushing each to 100
        subscribers while holding the table lock stalls every control
        RPC behind socket writes."""
        emit = (fanout.append if fanout is not None
                else lambda cm: self._publish(*cm))
        max_restarts = actor.spec.get("max_restarts", 0)
        if actor.state == "DEAD":
            return {"restart": False}
        if max_restarts == -1 or actor.num_restarts < max_restarts:
            actor.num_restarts += 1
            actor.state = "RESTARTING"
            actor.addr = None
            emit(("actors", {"event": "restarting",
                             "actor_id": actor.actor_id,
                             "reason": reason}))
            _events.record("actor_state", actor_id=actor.actor_id.hex(),
                           state="RESTARTING", reason=reason,
                           num_restarts=actor.num_restarts)
            self._persist_actor(actor)
            return {"restart": True, "num_restarts": actor.num_restarts}
        actor.state = "DEAD"
        actor.death_cause = reason
        self._drop_name(actor)
        emit(("actors", {"event": "dead",
                         "actor_id": actor.actor_id,
                         "reason": reason}))
        _events.record("actor_state", actor_id=actor.actor_id.hex(),
                       state="DEAD", reason=reason)
        self._persist_actor(actor)
        return {"restart": False}

    def rpc_get_actor(self, conn, actor_id: bytes = None, name: str = None,
                      namespace: str = "default"):
        with self._lock:
            if actor_id is None:
                actor_id = self.named_actors.get((namespace, name))
                if actor_id is None:
                    return None
            actor = self.actors.get(actor_id)
            if actor is None:
                return None
            return {"actor_id": actor.actor_id, "state": actor.state,
                    "addr": actor.addr, "spec_meta": {
                        k: actor.spec.get(k)
                        for k in ("class_name", "max_task_retries",
                                  "max_restarts", "name", "namespace")},
                    "num_restarts": actor.num_restarts,
                    "death_cause": actor.death_cause}

    def rpc_list_actors(self, conn):
        with self._lock:
            return [a.snapshot() for a in self.actors.values()]

    def rpc_list_named_actors(self, conn, all_namespaces: bool = False,
                              namespace: str = "default"):
        with self._lock:
            out = []
            for (ns, name), aid in self.named_actors.items():
                actor = self.actors.get(aid)
                if actor is None or actor.state == "DEAD":
                    continue
                if all_namespaces or ns == namespace:
                    out.append({"name": name, "namespace": ns})
            return out

    # ---- placement groups ---------------------------------------------------

    def rpc_create_placement_group(self, conn, pg_id: bytes,
                                   bundles: list[dict], strategy: str,
                                   name: str = "", job: str = "",
                                   stages: list | None = None):
        if strategy not in PG_STRATEGIES:
            raise ValueError(f"unknown strategy {strategy}")
        if stages is not None and len(stages) != len(bundles):
            raise ValueError(
                f"stages must label every bundle: got {len(stages)} "
                f"labels for {len(bundles)} bundles")
        with self._lock:
            if pg_id in self.placement_groups:
                # replay of our own creation (client retried across a
                # GCS restart that had already applied it) — idempotent
                return self.placement_groups[pg_id].snapshot()
            pg = PlacementGroupInfo(pg_id, bundles, strategy, name, job,
                                    stages=stages)
            self._pg_seq += 1
            pg.created_seq = self._pg_seq
            self.placement_groups[pg_id] = pg
            self._pending_pgs.add(pg_id)
            # forced: admission must attempt THIS gang now (not wait
            # out the pass rate limit) — still through the fair-share
            # order, so a new low-priority gang can't jump older
            # higher-priority demand
            self._maybe_schedule_pending(force=True)
            self._persist_pg(pg)
            return pg.snapshot()

    def _maybe_schedule_pending(self, force: bool = False):
        """Serve the pending queue: highest job priority first, then
        lowest dominant resource share (fair share), then admission
        order. Empty queue = immediate return (the capacity-event hot
        path). Quota-blocked gangs are skipped whole (all-or-nothing);
        a schedulable gang that still cannot place may trigger
        preemption of lower-priority capacity. Caller holds self._lock;
        ``force`` bypasses the per-PG attempt rate limit (job policy
        changes, preemption completions)."""
        if not self._pending_pgs:
            return
        now = time.time()
        # pass-level rate limit: the sort + dominant-share math below
        # is O(pending·jobs·PGs) under the GCS lock, and the hot
        # callers (per-raylet gossip, queued-creation polls) can hit
        # this hundreds of times a second — one pass per beat serves
        # every PG whose own limit expired, the rest were pure waste
        if not force and now - self._sched_pass_at < 0.25:
            return
        self._sched_pass_at = now
        from ray_tpu._private import telemetry as _tm

        shares: dict[str, float] = {}

        def _share(name: str) -> float:
            if name not in shares:
                shares[name] = self._dominant_share(name)
            return shares[name]

        def _order(pg_id):
            pg = self.placement_groups[pg_id]
            return (-self._pg_priority(pg), _share(pg.job),
                    pg.created_seq)

        # Priority blocking: once a FEASIBLE higher-priority gang fails
        # to place in this pass, strictly-lower-priority gangs are not
        # attempted — freed/fresh capacity is held for the blocked gang
        # instead of being backfilled out from under it (which forced a
        # second preemption round: the victim's requeued gang would
        # grab its own freed bundles before the preemptor's gossip view
        # caught up). A gang that can't fit even an EMPTY cluster never
        # raises the barrier, so an infeasible shape can't starve the
        # tenants below it.
        barrier_pri: int | None = None
        for pg_id in sorted(self._pending_pgs, key=_order):
            pg = self.placement_groups.get(pg_id)
            if pg is None or pg.state not in ("PENDING", "RESCHEDULING"):
                self._pending_pgs.discard(pg_id)
                continue
            pri = self._pg_priority(pg)
            if barrier_pri is not None and pri < barrier_pri:
                continue
            if now < pg.holdoff_until:
                continue   # freshly preempted: even force waits this out
            if not force and now - pg.last_sched_attempt <= 0.25:
                continue
            pg.last_sched_attempt = now
            if self._quota_blocked_pg(pg):
                if not pg.quota_blocked:
                    pg.quota_blocked = True
                    job = self.jobs.get(pg.job)
                    if job is not None:
                        job.quota_rejections += 1
                        self._persist_job(job)
                    if _tm.ENABLED:
                        _tm.counter_inc("ray_tpu_quota_rejections_total",
                                        tags={"job": pg.job})
                continue
            pg.quota_blocked = False
            self._try_schedule_pg(pg)
            if pg.state in ("PENDING", "RESCHEDULING"):
                self._maybe_preempt_for(pg)
                if self._feasible_on_totals(pg):
                    barrier_pri = pri if barrier_pri is None \
                        else max(barrier_pri, pri)
        if _tm.ENABLED:
            for name in self.jobs:
                _tm.gauge_set("ray_tpu_job_dominant_share_ratio",
                              _share(name), tags={"job": name})

    def _try_schedule_pg(self, pg: PlacementGroupInfo):
        """Bundle→node assignment over the live node table. The 2-phase
        prepare/commit of gcs_placement_group_scheduler.h degenerates to a
        single atomic pass because GCS owns the resource view (v1: resources
        are reserved here, raylets enforce)."""
        alive = [n for n in self.nodes.values() if n.alive]
        if not alive:
            return
        avail = {n.node_id: self._node_available_for_pg(n) for n in alive}

        def fits(node_id, bundle):
            a = avail[node_id]
            return all(a.get(k, 0) >= v for k, v in bundle.items())

        def take(node_id, bundle):
            for k, v in bundle.items():
                avail[node_id][k] = avail[node_id].get(k, 0) - v

        assignment: list[str | None] = [None] * len(pg.bundles)
        order = sorted(avail, key=lambda n: -sum(avail[n].values()))
        # ICI-topology-aware gang packing (the TPU-native extension of
        # gcs_placement_group_scheduler.h, SURVEY §2.4/§7 phase 3): TPU
        # bundles under PACK/STRICT_PACK land on a contiguous block of
        # hosts inside ONE slice, so the gang's collectives ride ICI
        # instead of DCN. Falls through to the generic policy when no
        # slice can host the gang.
        if pg.strategy == "SPREAD_ACROSS_SLICES":
            # Multi-slice MPMD gang: each stage's bundle sub-gang lands
            # contiguous inside ONE slice, distinct stages on distinct
            # slices (activations hop the inter-slice plane, compute
            # rides ICI). Strictly all-or-nothing: a gang that cannot
            # place EVERY stage this way stays PENDING whole — there is
            # no generic fallback, because a stage split across slices
            # would silently put the pipeline's inner collectives on
            # the wrong plane.
            placed = self._place_across_slices(pg, avail, take)
            if placed is not None:
                assignment = placed
        elif pg.strategy in ("PACK", "STRICT_PACK"):
            ici_placed = False
            if all(b.get("TPU", 0) > 0 for b in pg.bundles):
                ici = self._place_on_contiguous_slice(pg, avail, take)
                if ici is not None:
                    assignment = ici
                    ici_placed = True
            if any(a is None for a in assignment):
                for i, bundle in enumerate(pg.bundles):
                    for node_id in order:
                        if fits(node_id, bundle):
                            assignment[i] = node_id
                            take(node_id, bundle)
                            break
            # For TPU gangs STRICT_PACK means "one contiguous ICI domain"
            # (a multi-host slice block), not one host — don't collapse an
            # ICI placement onto a single node.
            if pg.strategy == "STRICT_PACK" and not ici_placed and len(
                    {a for a in assignment if a}) > 1:
                assignment = [None] * len(pg.bundles)
                # retry all on one node — against FRESH availability: the
                # discarded multi-node pass mutated `avail` via take(), and
                # judging nodes by those leftovers can wrongly reject a
                # node that fits the whole gang
                fresh = {n.node_id: self._node_available_for_pg(n)
                         for n in alive}
                for node_id in order:
                    a = dict(fresh[node_id])
                    ok = True
                    for bundle in pg.bundles:
                        if all(a.get(k, 0) >= v for k, v in bundle.items()):
                            for k, v in bundle.items():
                                a[k] = a.get(k, 0) - v
                        else:
                            ok = False
                            break
                    if ok:
                        assignment = [node_id] * len(pg.bundles)
                        break
        else:  # SPREAD / STRICT_SPREAD round-robin distinct nodes
            used: set[str] = set()
            for i, bundle in enumerate(pg.bundles):
                candidates = [n for n in order
                              if fits(n, bundle) and (n not in used or
                                 pg.strategy == "SPREAD")]
                prefer = [n for n in candidates if n not in used]
                pick = (prefer or candidates)[:1]
                if pick:
                    assignment[i] = pick[0]
                    take(pick[0], bundle)
                    used.add(pick[0])
        if all(a is not None for a in assignment):
            pg.bundle_nodes = assignment
            pg.state = "CREATED"
            pg.commit_ts = time.time()
            self._pending_pgs.discard(pg.pg_id)
            pg.quota_blocked = False
            self._persist_pg(pg)
            # bundles ride along so raylets can reserve without calling back
            # into GCS (the push handler runs on their RPC reader thread)
            self._publish("placement_groups",
                          {"event": "created", "pg_id": pg.pg_id,
                           "bundle_nodes": assignment,
                           "bundles": [dict(b) for b in pg.bundles]})
            self._publish("pg_state", {"event": "state",
                                       "pg_id": pg.pg_id,
                                       "state": "CREATED", "job": pg.job})

    def _slice_inventory(self, avail) -> dict[str, list]:
        """slice_id -> sorted [(worker_id, node_id)] over the schedulable
        nodes that report TPU topology (raylet `tpu_topology` meta, from
        tpu_probe slice identity / the TPU runtime env)."""
        slices: dict[str, list] = {}
        for node_id in avail:
            node = self.nodes.get(node_id)
            tpu = (node.meta or {}).get("tpu") if node else None
            if not tpu:
                continue
            slices.setdefault(str(tpu.get("slice_id", "slice-0")), []).append(
                (int(tpu.get("worker_id", 0)), node_id))
        for hosts in slices.values():
            hosts.sort()
        return slices

    @staticmethod
    def _fit_contiguous_window(bundles, hosts, avail):
        """Trial-fit `bundles` onto a contiguous run of hosts (by TPU
        worker index) within one slice's host list. Scans all windows of
        every length ≥ 1, SMALLEST first (tight packing leaves the big
        runs whole for bigger gangs). Hosts must be consecutive worker
        indices to form a window — a gap (dead/absent host) breaks
        contiguity, because contiguous worker indices are what share ICI
        neighbours on TPU pods. Returns the per-bundle node assignment,
        or None. Pure trial: `avail` is never mutated."""
        n = len(hosts)
        for width in range(1, n + 1):
            for start in range(0, n - width + 1):
                window = hosts[start:start + width]
                if window[-1][0] - window[0][0] != width - 1:
                    continue   # gap (a dead host) breaks contiguity
                trial_avail = {nid: dict(avail[nid]) for _, nid in window}
                assignment = []
                ok = True
                for bundle in bundles:
                    for _, nid in window:
                        a = trial_avail[nid]
                        if all(a.get(k, 0) >= v for k, v in bundle.items()):
                            assignment.append(nid)
                            for k, v in bundle.items():
                                a[k] = a.get(k, 0) - v
                            break
                    else:
                        ok = False
                        break
                if ok:
                    return assignment
        return None

    def _place_on_contiguous_slice(self, pg, avail, take):
        """Try to place every bundle on a contiguous run of hosts (by TPU
        worker index) within a single slice. Returns the assignment list or
        None. Contiguous worker indices share ICI neighbours on TPU pods,
        so the gang's mesh axes map onto torus links instead of DCN."""
        best = None
        for slice_id, hosts in sorted(self._slice_inventory(avail).items()):
            best = self._fit_contiguous_window(pg.bundles, hosts, avail)
            if best is not None:
                break
        if best is None:
            return None
        for i, bundle in enumerate(pg.bundles):
            take(best[i], bundle)
        return best

    def _spread_slices_trial(self, pg, avail):
        """SPREAD_ACROSS_SLICES trial placement against ``avail`` (never
        mutated): group bundles by their stage label and fit each
        stage's sub-gang contiguous inside one slice, with DISTINCT
        stages on DISTINCT slices. Returns the per-bundle assignment or
        None — strictly all-or-nothing: fewer usable slices than
        stages, or any one stage that cannot fit a slice contiguously,
        fails the whole gang.

        Slice choice is best-fit when slices outnumber stages: each
        stage prefers the slice with the FEWEST schedulable hosts that
        still fits it (intra-slice-first packing — small pipelines
        consume the small slices and leave the big contiguous runs
        whole for gangs that actually need them). Stages place largest
        sub-gang first so a big stage is not starved by a small one
        grabbing the only slice that could hold it; ties break on
        declared stage order."""
        labels = pg.stages if pg.stages is not None \
            else list(range(len(pg.bundles)))
        stage_idxs: dict = {}
        for i, lab in enumerate(labels):
            stage_idxs.setdefault(lab, []).append(i)
        slices = self._slice_inventory(avail)
        if len(slices) < len(stage_idxs):
            return None
        assignment: list = [None] * len(pg.bundles)
        trial_avail = {nid: dict(avail[nid]) for nid in avail}
        used_slices: set[str] = set()
        order = sorted(stage_idxs.items(),
                       key=lambda kv: (-len(kv[1]), labels.index(kv[0])))
        for lab, idxs in order:
            bundles = [pg.bundles[i] for i in idxs]
            best = None   # ((free_hosts, slice_id), placement)
            for sid, hosts in slices.items():
                if sid in used_slices:
                    continue
                placement = self._fit_contiguous_window(bundles, hosts,
                                                        trial_avail)
                if placement is None:
                    continue
                key = (len(hosts), sid)
                if best is None or key < best[0]:
                    best = (key, placement)
            if best is None:
                return None
            used_slices.add(best[0][1])
            for i, nid in zip(idxs, best[1]):
                assignment[i] = nid
                for k, v in pg.bundles[i].items():
                    trial_avail[nid][k] = trial_avail[nid].get(k, 0) - v
        return assignment

    def _place_across_slices(self, pg, avail, take):
        """Commit wrapper over ``_spread_slices_trial``: on success the
        assignment's takes are applied to ``avail``."""
        assignment = self._spread_slices_trial(pg, avail)
        if assignment is None:
            return None
        for i, bundle in enumerate(pg.bundles):
            take(assignment[i], bundle)
        return assignment

    def _node_available_for_pg(self, node: NodeInfo) -> dict:
        """Capacity the PG scheduler may hand out on this node. Prefer the
        raylet's gossiped live availability (which already excludes both
        non-PG load and bundles it has reserved); bundles committed AFTER
        the last report aren't reflected there yet, so subtract those. Fall
        back to totals-minus-all-bundles when no report arrived (fresh
        node) — that path is blind to non-PG load, which is why raylets
        gossip in the first place."""
        fresh = (node.resources_reported is not None
                 and time.time() - node.reported_at < 5.0)
        if fresh:
            avail = dict(node.resources_reported)
            # Grace period: a report taken shortly AFTER a commit may still
            # predate the raylet processing the bundle reservation (the
            # "created" push is async) — treat such commits as unreflected
            # and subtract them, at worst briefly double-counting.
            cutoff = node.reported_at - 1.5
            # Mirror image for preemption fires: bundles a fire reclaimed
            # AFTER the last report are still counted as held there — add
            # them back until a post-fire report lands. Without this the
            # fire's own queue re-drive sees the freed capacity as
            # occupied and warns one MORE victim per fire (fire-boundary
            # over-preemption). Direction matters: the commit margin
            # above errs by double-SUBTRACTING (conservative), but adding
            # freed bundles a report already shows OVER-COMMITS — the
            # scheduler would admit a gang onto capacity that does not
            # exist. So each entry is consumed per node by the first
            # report taken after the fire (no grace margin: a report
            # racing the reclaim push at worst briefly under-states,
            # the conservative direction), not by a wall-clock window.
            for fired_ts, bundles, nids, reflected in self._preempt_freed:
                if node.node_id in reflected:
                    continue    # a post-fire report already showed it
                if node.reported_at > fired_ts:
                    reflected.add(node.node_id)
                    continue
                for bundle, nid in zip(bundles, nids):
                    if nid == node.node_id:
                        for k, v in bundle.items():
                            avail[k] = avail.get(k, 0) + v
        else:
            # totals-minus-CREATED-bundles already reflects a fired gang
            # (it is no longer CREATED): no freed adjustment needed
            avail = dict(node.resources)
            cutoff = 0.0
        for pg in self.placement_groups.values():
            if pg.state not in ("CREATED",):
                continue
            if pg.commit_ts <= cutoff:
                continue    # already reflected in the raylet's report
            for bundle, nid in zip(pg.bundles, pg.bundle_nodes):
                if nid == node.node_id:
                    for k, v in bundle.items():
                        avail[k] = avail.get(k, 0) - v
        return avail

    # ---- priority preemption ------------------------------------------------
    # Graceful degradation, not failure: when a higher-priority gang
    # cannot place, victims come from the LOWEST-priority job,
    # newest-gang-first; each gets a PREEMPTION warning with a grace
    # window (`gcs_preempt_grace_s`) — the Train plane's notice handler
    # cuts a checkpoint inside it — then its bundles are reclaimed and
    # it re-queues PENDING, resuming when capacity returns.

    def _maybe_preempt_for(self, pg: "PlacementGroupInfo"):
        """Pick and warn victims for an unplaceable pending gang.
        Caller holds self._lock."""
        from ray_tpu._private.config import get_config

        my_pri = self._pg_priority(pg)
        # Reclaims already in flight count as INCOMING capacity: the
        # pending queue re-attempts this gang every rate-limit beat for
        # the whole grace window, and without this each pass would warn
        # one MORE victim than the preemptor needs (cascading
        # over-preemption — three gangs checkpoint-interrupted where
        # one sufficed; review finding).
        inflight = [v for v in self.placement_groups.values()
                    if v.state == "CREATED"
                    and v.preempt_deadline is not None]
        if inflight and self._placeable_with_freed(pg, inflight):
            return   # enough already cooking — wait for the fires
        cands = [v for v in self.placement_groups.values()
                 if v.state == "CREATED" and v.preempt_deadline is None
                 and self._pg_priority(v) < my_pri]
        if not cands:
            return
        # lowest-priority job first; within it, newest gang first —
        # the oldest (longest-amortized) work survives longest
        cands.sort(key=lambda v: (self._pg_priority(v), -v.commit_ts,
                                  -v.created_seq))
        chosen: list = list(inflight)
        for v in cands:
            chosen.append(v)
            if self._placeable_with_freed(pg, chosen):
                break
        if not self._placeable_with_freed(pg, chosen):
            return   # even every lower-pri gang freed wouldn't fit: don't
            #          preempt for nothing (infeasible shape)
        grace = float(get_config("gcs_preempt_grace_s"))
        for v in chosen:
            if v.preempt_deadline is None:
                self._warn_preemption(v, pg, grace)

    def _feasible_on_totals(self, pg) -> bool:
        """Could this gang fit an EMPTY cluster (first-fit over node
        TOTALS)? The priority barrier only holds for feasible gangs."""
        totals = {n.node_id: dict(n.resources)
                  for n in self.nodes.values() if n.alive}
        if pg.strategy == "SPREAD_ACROSS_SLICES":
            # the strategy is STRUCTURAL (distinct slices per stage,
            # contiguous windows), not just resource sums: a gang with
            # more stages than the cluster has slices must never raise
            # the priority barrier — it would starve every lower-
            # priority tenant forever for a gang that can never place
            return self._spread_slices_trial(pg, totals) is not None
        for bundle in pg.bundles:
            for nid in totals:
                a = totals[nid]
                if all(a.get(k, 0.0) >= v for k, v in bundle.items()):
                    for k, v in bundle.items():
                        a[k] = a.get(k, 0.0) - v
                    break
            else:
                return False
        return True

    def _placeable_with_freed(self, pg, victims: list) -> bool:
        """First-fit feasibility check of ``pg`` against current
        availability plus the victims' bundles added back (approximate:
        strategy constraints are re-judged for real by
        _try_schedule_pg once the bundles are actually released)."""
        alive = [n for n in self.nodes.values() if n.alive]
        avail = {n.node_id: self._node_available_for_pg(n) for n in alive}
        for v in victims:
            for bundle, nid in zip(v.bundles, v.bundle_nodes):
                if nid in avail:
                    for k, amt in bundle.items():
                        avail[nid][k] = avail[nid].get(k, 0.0) + amt
        if pg.strategy == "SPREAD_ACROSS_SLICES":
            # judge the REAL structural constraint: freeing resources on
            # too few slices cannot help a gang that needs more slices —
            # without this, a slice-infeasible high-priority gang would
            # warn and tear down checkpointed victims for nothing
            return self._spread_slices_trial(pg, avail) is not None
        order = sorted(avail, key=lambda n: -sum(avail[n].values()))
        for bundle in pg.bundles:
            for nid in order:
                a = avail[nid]
                if all(a.get(k, 0.0) >= v for k, v in bundle.items()):
                    for k, v in bundle.items():
                        a[k] = a.get(k, 0.0) - v
                    break
            else:
                return False
        return True

    def _warn_preemption(self, victim, preemptor, grace: float):
        """Stamp the deadline, broadcast the warning, arm the fire
        timer. Caller holds self._lock."""
        victim.preempt_deadline = time.time() + grace
        victim.preemptor = preemptor.pg_id if preemptor else None
        self._publish("pg_state", {
            "event": "preempt_warning", "pg_id": victim.pg_id,
            "job": victim.job, "grace_s": grace,
            "preemptor": victim.preemptor.hex()
            if victim.preemptor else None})
        _events.record("PREEMPTION_WARNED", pg_id=victim.pg_id.hex(),
                       job=victim.job, grace_s=grace,
                       preemptor=victim.preemptor.hex()
                       if victim.preemptor else None)
        threading.Thread(target=self._fire_after,
                         args=(victim.pg_id, grace), daemon=True,
                         name="gcs-preempt-fire").start()

    def _fire_after(self, pg_id: bytes, grace: float):
        time.sleep(grace)
        if not self._server._stopped:
            self._fire_preemption(pg_id)

    def _fire_preemption(self, pg_id: bytes) -> bool:
        """Grace elapsed: reclaim the victim's bundles (raylets release
        reservations via the standard `removed` push), re-queue it
        PENDING, and re-drive the queue so the preemptor places. The
        victim's worker processes are the DRIVER'S to tear down (the
        Train plane raises TrainPreemptedError and goes through the
        gang-teardown path); until it does, the freed logical capacity
        may briefly be oversubscribed — the documented teardown
        bound."""
        from ray_tpu._private import telemetry as _tm

        with self._lock:
            pg = self.placement_groups.get(pg_id)
            if pg is None or pg.state != "CREATED" \
                    or pg.preempt_deadline is None:
                return False   # removed/re-placed/node-death superseded
            preemptor = pg.preemptor
            if preemptor is not None:
                # Reprieve: the demand that warned this victim may have
                # evaporated during the grace window — the preemptor
                # placed on capacity freed elsewhere, was removed, or
                # current availability now fits it without this gang.
                # Firing anyway would reclaim a victim nobody needs
                # (same supersede principle as the node-death path).
                # Admin/chaos/self-preempt warnings carry no preemptor
                # and always fire.
                pre = self.placement_groups.get(preemptor)
                if (pre is None
                        or pre.state not in ("PENDING", "RESCHEDULING")
                        or self._placeable_with_freed(pre, [])):
                    pg.preempt_deadline = None
                    pg.preemptor = None
                    self._persist_pg(pg)
                    self._publish("pg_state", {
                        "event": "preempt_canceled", "pg_id": pg_id,
                        "job": pg.job,
                        "preemptor": preemptor.hex()})
                    _events.record("PREEMPTION_CANCELED",
                                   pg_id=pg_id.hex(), job=pg.job,
                                   preemptor=preemptor.hex())
                    self._maybe_schedule_pending(force=True)
                    return False
            # The owning raylets won't re-gossip the reclaimed bundles
            # for up to a gossip beat: remember them so availability
            # reads add them back (and the re-drive below doesn't
            # over-preempt). Entries past the 5s report-freshness
            # horizon are inert — prune here to bound the list.
            now = time.time()
            self._preempt_freed = [f for f in self._preempt_freed
                                   if now - f[0] < 5.0]
            self._preempt_freed.append(
                (now, list(pg.bundles), list(pg.bundle_nodes), set()))
            pg.preempt_deadline = None
            pg.preemptor = None
            pg.state = "PENDING"
            pg.bundle_nodes = [None] * len(pg.bundles)
            pg.commit_ts = 0.0
            pg.holdoff_until = time.time() + 0.5
            pg.preempted_at = time.time()
            self._pending_pgs.add(pg_id)
            self._persist_pg(pg)
            job = self.jobs.get(pg.job)
            if job is not None:
                job.preemptions += 1
                self._persist_job(job)
            self._publish("placement_groups", {"event": "removed",
                                               "pg_id": pg_id})
            self._publish("pg_state", {"event": "state", "pg_id": pg_id,
                                       "state": "PREEMPTED",
                                       "job": pg.job})
            _events.record("PREEMPTION_FIRED", pg_id=pg_id.hex(),
                           job=pg.job,
                           preemptor=preemptor.hex() if preemptor
                           else None)
            if _tm.ENABLED:
                _tm.counter_inc("ray_tpu_preemptions_total",
                                tags={"job": pg.job})
            self._maybe_schedule_pending(force=True)
            self._refresh_quota_throttle_locked(force=True)
        return True

    def rpc_preempt_job(self, conn, name: str, grace_s: float = None,
                        pg_name: str = None):
        """Force-preempt the named job's newest CREATED gang (the fault
        DSL's `preempt_job` primitive and the admin escape hatch): same
        warning → grace → reclaim lifecycle as an organic priority
        preemption. ``pg_name`` narrows the victim to the job's gang of
        that name — the handle the Serve controller and slot-scoped
        chaos schedules use to warn ONE replica's capacity instead of
        whichever gang happens to be newest. Returns the victim pg id
        hex, or None when the job holds no preemptible gang (for
        pg_name: none of that name)."""
        from ray_tpu._private.config import get_config

        grace = (float(grace_s) if grace_s is not None
                 else float(get_config("gcs_preempt_grace_s")))
        with self._lock:
            cands = [pg for pg in self.placement_groups.values()
                     if pg.job == name and pg.state == "CREATED"
                     and pg.preempt_deadline is None
                     and (pg_name is None or pg.name == pg_name)]
            if not cands:
                return None
            victim = max(cands, key=lambda p: (p.commit_ts,
                                               p.created_seq))
            self._warn_preemption(victim, None, grace)
            return victim.pg_id.hex()

    def rpc_get_placement_group(self, conn, pg_id: bytes = None,
                                name: str = None):
        with self._lock:
            if pg_id is None:
                for pg in self.placement_groups.values():
                    if pg.name == name and pg.state != "REMOVED":
                        return pg.snapshot()
                return None
            pg = self.placement_groups.get(pg_id)
            # Late scheduling: nodes may have joined since creation —
            # re-drive the QUEUE (rate-limited per PG) so a poll can
            # unblock its gang without letting a hard-polled low-pri
            # PG jump the fair-share order.
            if pg is not None and pg.state in ("PENDING", "RESCHEDULING"):
                self._maybe_schedule_pending()
            return pg.snapshot() if pg else None

    def rpc_remove_placement_group(self, conn, pg_id: bytes):
        with self._lock:
            pg = self.placement_groups.get(pg_id)
            if pg is None:
                return False
            pg.state = "REMOVED"
            pg.preempt_deadline = None
            pg.preemptor = None
            self._pending_pgs.discard(pg_id)
            self._persist_pg(pg)
            # removal IS a capacity event: the freed bundles may place
            # queued demand (the gossip tick would also get there, but
            # a tenant releasing capacity shouldn't make the next one
            # wait out a gossip round)
            self._maybe_schedule_pending(force=True)
            self._refresh_quota_throttle_locked(force=True)
        self._publish("placement_groups", {"event": "removed",
                                           "pg_id": pg_id})
        self._publish("pg_state", {"event": "state", "pg_id": pg_id,
                                   "state": "REMOVED",
                                   "job": pg.job})
        return True

    def rpc_list_placement_groups(self, conn):
        with self._lock:
            return [pg.snapshot() for pg in self.placement_groups.values()]

    def rpc_list_objects(self, conn):
        """Object directory dump (state API `list objects` / `ray memory`
        source; reference: memory_utils.py over raylet stats)."""
        with self._lock:
            return [{
                "ObjectID": oid.hex(),
                "Size": self.object_sizes.get(oid, 0),
                "Locations": sorted(locs),
                "Lost": oid in self.lost_objects,
            } for oid, locs in self.object_locations.items()]

    # ---- pubsub -------------------------------------------------------------

    def rpc_subscribe(self, conn, channels: list[str]):
        with self._lock:
            for ch in channels:
                subs = self._subscribers.setdefault(ch, [])
                if conn not in subs:
                    subs.append(conn)
        return True

    def _publish(self, channel: str, message: dict):
        self._long_poll.publish(channel, message)
        self._push_subscribers(channel, message)

    def _push_subscribers(self, channel: str, message: dict):
        """Conn-push half of a publish (the long-poll half is the
        Publisher's); batch paths call the two separately so a storm
        pays one Publisher lock hold via publish_many."""
        subs = list(self._subscribers.get(channel, ()))
        for conn in subs:
            if conn.alive:
                conn.push("pubsub", channel=channel, message=message)
            else:
                with self._lock:
                    try:
                        self._subscribers[channel].remove(conn)
                    except ValueError:
                        pass

    def rpc_publish(self, conn, channel: str, message: dict):
        self._publish(channel, message)
        return True

    # ---- snapshot-resync providers (pubsub gap recovery) --------------------

    def _actors_resync_snapshot(self) -> list[dict]:
        """Actor-table state for a death-watch subscriber reconverging
        after a mailbox overflow/GC: the watcher re-reports anything
        DEAD/RESTARTING through its callback (duplicate-tolerant by the
        at-least-once contract), so a missed feed message can never
        become a permanently missed death. Only DEAD/RESTARTING rows
        ship — consumers ignore ALIVE rows, and the actor table retains
        dead actors for the cluster lifetime, so an unfiltered snapshot
        would grow (and be re-reported) with cluster AGE rather than
        with the gap being recovered."""
        with self._lock:
            return [{"actor_id": a.actor_id, "state": a.state,
                     "reason": a.death_cause}
                    for a in self.actors.values()
                    if a.state in ("DEAD", "RESTARTING")]

    def _nodes_resync_snapshot(self) -> list[dict]:
        with self._lock:
            return [{"node_id": n.node_id, "alive": n.alive}
                    for n in self.nodes.values()]

    def _pg_state_resync_snapshot(self) -> list[dict]:
        """PG-table state for a `pg_state` subscriber reconverging after
        a feed gap: a waiter that missed its CREATED transition (or a
        preemption monitor that missed the warning) re-reads it from
        here instead of hanging on the feed. REMOVED rows are excluded —
        the table retains them and consumers only wait on live ids."""
        with self._lock:
            return [{"pg_id": pg.pg_id, "state": pg.state, "job": pg.job,
                     "preempt_deadline": pg.preempt_deadline,
                     "preempted_at": pg.preempted_at}
                    for pg in self.placement_groups.values()
                    if pg.state != "REMOVED"]

    # ---- durable store (write-through fault tolerance) ----------------------
    # Reference: src/ray/gcs/store_client/redis_store_client.h — in
    # fault-tolerant mode every actor/PG/KV/job mutation lands in the
    # external store before the RPC returns; a restarted GCS reloads the
    # tables and raylets re-register (HandleNotifyGCSRestart,
    # node_manager.cc:1179).

    def _persist_actor(self, actor: "ActorInfo"):
        if self._store is None:
            return
        self._store.put("actors", actor.actor_id.hex(), pickle.dumps({
            "actor_id": actor.actor_id, "spec": actor.spec,
            "state": actor.state, "addr": actor.addr,
            "node_id": actor.node_id, "num_restarts": actor.num_restarts,
            "death_cause": actor.death_cause}))

    def _persist_pg(self, pg: "PlacementGroupInfo"):
        if self._store is None:
            return
        if pg.state == "REMOVED":
            self._store.delete("pgs", pg.pg_id.hex())
            return
        self._store.put("pgs", pg.pg_id.hex(), pickle.dumps({
            "pg_id": pg.pg_id, "bundles": pg.bundles,
            "strategy": pg.strategy, "name": pg.name, "state": pg.state,
            "bundle_nodes": pg.bundle_nodes, "job": pg.job,
            "created_seq": pg.created_seq, "stages": pg.stages,
            "preempted_at": pg.preempted_at}))

    def _persist_node(self, node: "NodeInfo"):
        """Node-table durability (reference: gcs_node_manager over the
        Redis store). Without it a GCS restart FORGETS nodes that died
        during the outage — they vanish from the table instead of being
        marked dead, so no death broadcast ever reaches survivors and
        their cluster views never reconverge (found by the 100-raylet
        soak's restart-mid-storm phase)."""
        if self._store is None:
            return
        self._store.put("nodes", node.node_id, pickle.dumps({
            "node_id": node.node_id, "addr": node.addr,
            "resources": node.resources, "meta": node.meta,
            "alive": node.alive}))

    def _persist_meta(self):
        if self._store is None:
            return
        self._store.put("meta", "meta", pickle.dumps({
            "job_counter": self.job_counter,
            "cluster_id": self.cluster_id}))

    def _persist_kv(self, ns: str, key: bytes, value: bytes | None):
        if self._store is None:
            return
        skey = f"{ns}\x00{key.hex()}"
        if value is None:
            self._store.delete("kv", skey)
        else:
            self._store.put("kv", skey, value)

    def _restore_from_store(self):
        meta = self._store.get("meta", "meta")
        actors = self._store.get_all("actors")
        pgs = self._store.get_all("pgs")
        kv = self._store.get_all("kv")
        nodes = self._store.get_all("nodes")
        job_rows = self._store.get_all("jobs")
        if meta is None and not actors and not pgs and not kv \
                and not nodes and not job_rows:
            return   # fresh store: nothing to restore
        if meta is not None:
            m = pickle.loads(meta)
            self.job_counter = m["job_counter"]
            self.cluster_id = m["cluster_id"]
        for blob in nodes.values():
            d = pickle.loads(blob)
            info = NodeInfo(d["node_id"], d["addr"], d["resources"],
                            d["meta"])
            info.alive = d["alive"]
            self.nodes[d["node_id"]] = info
            # restored-alive is provisional: raylets re-register within
            # the grace window; _reconcile_after_restart marks the rest
            # dead through the normal death pipeline (broadcast + actor
            # failover), so outage-window node deaths are NOT silent
        for blob in actors.values():
            d = pickle.loads(blob)
            info = ActorInfo(d["actor_id"], d["spec"])
            info.state = d["state"]
            info.addr = tuple(d["addr"]) if d["addr"] else None
            info.node_id = d["node_id"]
            info.num_restarts = d["num_restarts"]
            info.death_cause = d["death_cause"]
            self.actors[d["actor_id"]] = info
            if info.name and info.state != "DEAD":
                self.named_actors[(info.namespace, info.name)] = \
                    info.actor_id
        for blob in pgs.values():
            d = pickle.loads(blob)
            pg = PlacementGroupInfo(d["pg_id"], d["bundles"],
                                    d["strategy"], d["name"],
                                    d.get("job", ""),
                                    stages=d.get("stages"))
            pg.state = d["state"]
            pg.bundle_nodes = d["bundle_nodes"]
            pg.created_seq = d.get("created_seq", 0)
            pg.preempted_at = d.get("preempted_at")
            self._pg_seq = max(self._pg_seq, pg.created_seq)
            self.placement_groups[d["pg_id"]] = pg
            if pg.state in ("PENDING", "RESCHEDULING"):
                self._pending_pgs.add(pg.pg_id)
        for blob in job_rows.values():
            d = pickle.loads(blob)
            job = JobInfo(d["name"], d["quota"], d["priority"])
            job.created_at = d["created_at"]
            job.preemptions = d["preemptions"]
            job.quota_rejections = d["quota_rejections"]
            self.jobs[d["name"]] = job
        for skey, value in kv.items():
            ns, _, keyhex = skey.partition("\x00")
            self.kv.setdefault(ns, {})[bytes.fromhex(keyhex)] = value
        self._restored = True

    def _reconcile_after_restart(self):
        time.sleep(self._recovery_grace_s)
        if self._server._stopped:
            return
        # Nodes restored alive that never re-registered died during the
        # outage: route them through the BATCH death pipeline (one
        # sweep, coalesced broadcast) so survivors' death feeds hear
        # about them — this is what makes the post-restart cluster view
        # reconverge instead of silently forgetting the dead.
        with self._lock:
            # pin each death to the restored NodeInfo incarnation: a
            # node re-registering between this snapshot and the sweep
            # installs a FRESH NodeInfo, which the sweep's identity
            # check treats as superseding the death
            lost_nodes = {nid: ("lost across GCS restart", n)
                          for nid, n in self.nodes.items()
                          if n.alive and nid not in self._reregistered}
        if lost_nodes:
            self._mark_nodes_dead(lost_nodes)
        to_recreate: list[bytes] = []
        with self._lock:
            alive = {nid for nid, n in self.nodes.items() if n.alive}
            for actor in self.actors.values():
                if actor.state == "DEAD":
                    continue
                if actor.state == "ALIVE" and actor.node_id in alive \
                        and actor.actor_id in self._reannounced:
                    continue   # its raylet came back AND re-announced it
                # host never returned, or the worker died during the
                # outage (node back but no re-announce), or creation was
                # in flight: normal failure path → restart budget decides
                # (_on_actor_failure persists on both branches)
                if actor.state in ("ALIVE", "PENDING_CREATION"):
                    decision = self._on_actor_failure(
                        actor, "lost across GCS restart")
                    if decision.get("restart"):
                        to_recreate.append(actor.actor_id)
                elif actor.state == "RESTARTING":
                    to_recreate.append(actor.actor_id)
            for pg in self.placement_groups.values():
                if pg.state == "CREATED" and \
                        not all(n in alive for n in pg.bundle_nodes):
                    pg.state = "RESCHEDULING"
                    self._pending_pgs.add(pg.pg_id)
                    self._persist_pg(pg)
                # PENDING/RESCHEDULING PGs reschedule on the next
                # report_resources gossip tick (via the pending queue)
        for actor_id in to_recreate:
            self._push_recreate(actor_id)

    # ---- snapshot (GCS fault tolerance analog) ------------------------------

    def rpc_save_snapshot(self, conn=None):
        if not self._snapshot_path:
            return False
        with self._lock:
            blob = pickle.dumps({
                "kv": self.kv,
                "named_actors": dict(self.named_actors),
                "job_counter": self.job_counter,
                "cluster_id": self.cluster_id,
            })
        from ray_tpu._private.atomic_write import atomic_write

        atomic_write(self._snapshot_path, blob, tag="gcs",
                     name="snapshot")
        return True

    def _load_snapshot(self):
        with open(self._snapshot_path, "rb") as f:
            data = pickle.loads(f.read())
        self.kv = data["kv"]
        self.named_actors = data["named_actors"]
        self.job_counter = data["job_counter"]
        self.cluster_id = data["cluster_id"]

    def rpc_events_snapshot(self, conn):
        """The GCS process's structured event ring (node membership, actor
        lifecycle) for `list_cluster_events()`."""
        return _events.snapshot()

    def rpc_metrics_snapshot(self, conn):
        """The GCS process's metric registry (pubsub backlog, gcs-store
        ops, its own RPC-client latencies) for `metrics_summary()`."""
        from ray_tpu.util.metrics import registry_snapshot

        return registry_snapshot()

    def rpc_blackbox_snapshot(self, conn):
        """The GCS process's flight-recorder window (its event ring is
        where node/actor lifecycle lands) for a cluster black-box dump."""
        from ray_tpu._private import flight_recorder

        snap = flight_recorder.local_snapshot()
        return [snap] if snap else []

    def rpc_debug_state(self, conn):
        with self._lock:
            out = {
                "nodes": len(self.nodes),
                "alive_nodes": sum(n.alive for n in self.nodes.values()),
                "actors": len(self.actors),
                "alive_actors": sum(a.state == "ALIVE"
                                    for a in self.actors.values()),
                "objects_tracked": len(self.object_locations),
                "placement_groups": len(self.placement_groups),
                "pending_pgs": len(self._pending_pgs),
                "jobs": len(self.jobs),
                "preemptions_fired": sum(j.preemptions
                                         for j in self.jobs.values()),
                "quota_rejections": sum(j.quota_rejections
                                        for j in self.jobs.values()),
                "jobs_over_quota": sorted(self._quota_over),
            }
        # control-plane scale counters (soak harness / `ray-tpu control`)
        with self._death_lock:
            out.update(self._fanout_stats)
        out["pubsub_resyncs_served"] = self._long_poll.resyncs_served
        out["pubsub_subscribers"] = self._long_poll.subscriber_count()
        return out


def main():  # pragma: no cover - exercised as a subprocess
    """Entry point: `python -m ray_tpu._private.gcs <port> [snapshot]
    [--store sqlite:<path>|log:<path>] [--grace <s>]`."""
    import sys

    from ray_tpu._private import fault_injection

    fault_injection.set_role("gcs")

    argv = [a for a in sys.argv[1:]]
    store = grace = None
    if "--store" in argv:
        i = argv.index("--store")
        store = argv[i + 1]
        del argv[i:i + 2]
    if "--grace" in argv:
        i = argv.index("--grace")
        grace = float(argv[i + 1])
        del argv[i:i + 2]
    port = int(argv[0]) if argv else 0
    snap = argv[1] if len(argv) > 1 else None
    kwargs = {}
    if grace is not None:
        kwargs["recovery_grace_s"] = grace
    server = GcsServer(port=port, snapshot_path=snap, store=store,
                       **kwargs).start()
    # Report the bound port on stdout for the parent supervisor.
    print(f"GCS_READY {server.addr[0]}:{server.addr[1]}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":  # pragma: no cover
    main()
