"""Pluggable GCS storage backends — durable control-plane tables.

Reference: src/ray/gcs/store_client/ — the store-client interface
(store_client.h) with InMemoryStoreClient (default) and
RedisStoreClient (fault-tolerant mode). Same split here, shaped for a
head node without external services: the durable backends are a local
sqlite file (WAL mode — every put committed before the RPC returns, no
snapshot window) and an append-only record log with replay + compaction.
Which tables are durable and when they're written is the GcsServer's
business; this module only stores bytes.

Interface (Redis-hash-shaped, like the reference's
Put/Get/GetAll/Delete over (table, key)):

    put(table, key, value)   -> None      key: str, value: bytes
    get(table, key)          -> bytes | None
    delete(table, key)       -> None
    get_all(table)           -> dict[str, bytes]
    close()
"""
from __future__ import annotations

import os
import struct
import threading


class GcsStoreClient:
    def put(self, table: str, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get(self, table: str, key: str) -> bytes | None:
        raise NotImplementedError

    def delete(self, table: str, key: str) -> None:
        raise NotImplementedError

    def get_all(self, table: str) -> dict[str, bytes]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryStoreClient(GcsStoreClient):
    """Default: no durability (reference: in_memory_store_client.h)."""

    def __init__(self):
        self._tables: dict[str, dict[str, bytes]] = {}
        self._lock = threading.Lock()

    def put(self, table, key, value):
        with self._lock:
            self._tables.setdefault(table, {})[key] = bytes(value)

    def get(self, table, key):
        with self._lock:
            return self._tables.get(table, {}).get(key)

    def delete(self, table, key):
        with self._lock:
            self._tables.get(table, {}).pop(key, None)

    def get_all(self, table):
        with self._lock:
            return dict(self._tables.get(table, {}))


class SqliteStoreClient(GcsStoreClient):
    """Durable store over one sqlite file. WAL journal + NORMAL
    synchronous: a put is on disk when it returns (the WAL is fsynced
    on checkpoint; NORMAL survives process SIGKILL, which is the
    failure mode GCS fault tolerance defends — machine-crash torn-write
    protection would use synchronous=FULL at ~2x the write latency)."""

    def __init__(self, path: str):
        import sqlite3

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # one writer connection guarded by a lock: the GCS mutates state
        # under its own global lock anyway, so store writes are already
        # serialized — check_same_thread=False lets any handler thread in
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS gcs (tbl TEXT, key TEXT, "
            "value BLOB, PRIMARY KEY (tbl, key))")
        self._db.commit()
        self._lock = threading.Lock()

    def put(self, table, key, value):
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO gcs (tbl, key, value) "
                "VALUES (?, ?, ?)", (table, key, bytes(value)))
            self._db.commit()

    def get(self, table, key):
        with self._lock:
            row = self._db.execute(
                "SELECT value FROM gcs WHERE tbl = ? AND key = ?",
                (table, key)).fetchone()
        return None if row is None else row[0]

    def delete(self, table, key):
        with self._lock:
            self._db.execute(
                "DELETE FROM gcs WHERE tbl = ? AND key = ?", (table, key))
            self._db.commit()

    def get_all(self, table):
        with self._lock:
            rows = self._db.execute(
                "SELECT key, value FROM gcs WHERE tbl = ?",
                (table,)).fetchall()
        return {k: v for k, v in rows}

    def close(self):
        with self._lock:
            try:
                self._db.commit()
                self._db.close()
            except Exception:
                pass


# record ops for the file log
_OP_PUT = 1
_OP_DEL = 2
_HEADER = struct.Struct("<BIII")   # op, table_len, key_len, value_len


class FileLogStoreClient(GcsStoreClient):
    """Append-only record log with replay and size-triggered compaction.

    Every mutation appends one framed record and fsyncs — zero loss
    window at one fsync (~50-500µs on local disk) per control-plane
    mutation, which control-plane rates (actor/PG/job transitions, not
    per-task) absorb easily. A torn final record (crash mid-append) is
    detected by frame-length underrun and dropped. When the log exceeds
    compact_bytes the in-memory view is rewritten as a fresh base log
    (temp file + atomic rename)."""

    def __init__(self, path: str, compact_bytes: int = 8 * 1024 * 1024):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self.compact_bytes = compact_bytes
        self._tables: dict[str, dict[str, bytes]] = {}
        self._lock = threading.Lock()
        if os.path.exists(path):
            valid_end = self._replay()
            if valid_end < os.path.getsize(path):
                # torn trailing record (crash mid-append): TRUNCATE it
                # away — appending after the tear would mis-frame every
                # later record on the next replay
                with open(path, "r+b") as f:
                    f.truncate(valid_end)
        self._f = open(path, "ab")

    # -- interface -----------------------------------------------------------
    def put(self, table, key, value):
        value = bytes(value)
        with self._lock:
            self._tables.setdefault(table, {})[key] = value
            self._append(_OP_PUT, table, key, value)

    def get(self, table, key):
        with self._lock:
            return self._tables.get(table, {}).get(key)

    def delete(self, table, key):
        with self._lock:
            self._tables.get(table, {}).pop(key, None)
            self._append(_OP_DEL, table, key, b"")

    def get_all(self, table):
        with self._lock:
            return dict(self._tables.get(table, {}))

    def close(self):
        with self._lock:
            try:
                self._f.close()
            except Exception:
                pass

    # -- internals -----------------------------------------------------------
    def _append(self, op: int, table: str, key: str, value: bytes):
        t, k = table.encode(), key.encode()
        self._f.write(_HEADER.pack(op, len(t), len(k), len(value)))
        self._f.write(t)
        self._f.write(k)
        self._f.write(value)
        self._f.flush()
        os.fsync(self._f.fileno())
        if self._f.tell() > self.compact_bytes:
            self._compact()

    def _replay(self) -> int:
        """Rebuild the in-memory view; returns the offset of the last
        complete record (the caller truncates anything after it)."""
        with open(self.path, "rb") as f:
            data = f.read()
        off = 0
        while off + _HEADER.size <= len(data):
            op, tl, kl, vl = _HEADER.unpack_from(data, off)
            end = off + _HEADER.size + tl + kl + vl
            if end > len(data):
                break   # torn final record: drop it
            p = off + _HEADER.size
            table = data[p:p + tl].decode()
            key = data[p + tl:p + tl + kl].decode()
            value = data[p + tl + kl:end]
            if op == _OP_PUT:
                self._tables.setdefault(table, {})[key] = value
            elif op == _OP_DEL:
                self._tables.get(table, {}).pop(key, None)
            off = end
        return off

    def _compact(self):
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            for table, entries in self._tables.items():
                t = table.encode()
                for key, value in entries.items():
                    k = key.encode()
                    f.write(_HEADER.pack(_OP_PUT, len(t), len(k),
                                         len(value)))
                    f.write(t)
                    f.write(k)
                    f.write(value)
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")


class InstrumentedStoreClient(GcsStoreClient):
    """Counts durable-store operations into the internal metric plane
    (`ray_tpu_gcs_store_ops_total{backend,op}`) around any backend —
    write-through durability is on the GCS mutation path, so op rates
    and their growth are the first thing to check when control-plane
    RPCs slow down."""

    def __init__(self, inner: GcsStoreClient, backend: str):
        self._inner = inner
        self._backend = backend

    def _count(self, op: str):
        from ray_tpu._private import telemetry as _tm

        _tm.counter_inc("ray_tpu_gcs_store_ops_total",
                        tags={"backend": self._backend, "op": op})

    def put(self, table, key, value):
        self._count("put")
        return self._inner.put(table, key, value)

    def get(self, table, key):
        self._count("get")
        return self._inner.get(table, key)

    def delete(self, table, key):
        self._count("delete")
        return self._inner.delete(table, key)

    def get_all(self, table):
        return self._inner.get_all(table)

    def close(self):
        return self._inner.close()


def make_store(spec: str | None) -> GcsStoreClient:
    """Factory from a config string: None/"memory" | "sqlite:<path>" |
    "log:<path>" (reference analog: RAY_REDIS_ADDRESS selecting the
    redis store client). Every backend is wrapped with op counters."""
    if not spec or spec == "memory":
        return InstrumentedStoreClient(InMemoryStoreClient(), "memory")
    if spec.startswith("sqlite:"):
        return InstrumentedStoreClient(
            SqliteStoreClient(spec[len("sqlite:"):]), "sqlite")
    if spec.startswith("log:"):
        return InstrumentedStoreClient(
            FileLogStoreClient(spec[len("log:"):]), "log")
    raise ValueError(f"unknown GCS store spec {spec!r}")
