"""Unified control-plane retry policy: backoff, deadlines, idempotency.

Before this module every retry decision was local folklore — a fixed
``retry: int = 3`` connect loop in protocol.py, a hand-rolled
exponential sleep in pubsub.py, one blind reconnect-and-retry in
ReconnectingRpcClient, and bare ``except ConnectionLost: pass`` at
assorted call sites. The reference concentrates this in one place
(gRPC channel retry args + per-call-site policy in gcs_rpc_client.h);
this module is our analog:

- ``RetryPolicy``: exponential backoff with FULL jitter (AWS-style:
  ``sleep = uniform(0, min(cap, base * 2**attempt))`` — decorrelated
  herds beat synchronized ones), a per-call deadline that bounds total
  time across attempts AND shrinks each attempt's RPC timeout to the
  remaining budget, and a max-attempt count.
- A process-wide ``RetryBudget``: a token bucket that bounds cluster
  retry amplification. When a dependency is hard-down, unbounded
  per-call retries turn N callers into N*attempts hammering it; once
  the bucket drains, calls fail fast until it refills.
- The idempotency registry: per-RPC-method flags saying whether a call
  that MAY have been applied server-side can be safely re-sent.
  Retry-safe here means "replay is harmless", which is weaker than
  strictly idempotent — e.g. ``next_job_id`` replayed mints a fresh
  (still unique) id. Non-retry-safe methods fail fast instead of
  blind-retrying (``actor_failed`` double-charges the restart budget).

Consumers: protocol.ReconnectingRpcClient (GCS table ops),
worker_runtime.request_lease (raylet lease path) and _pull_rpc
(object-pull chunks), pubsub.Subscriber (poll-loop backoff), and
autoscaler.tpu_provider.GceTpuApi (HTTP 429/503).
"""
from __future__ import annotations

import random
import threading
import time


# --------------------------------------------------------------- idempotency
#
# Control-plane methods where re-sending a request that may already have
# been applied is harmless. Everything NOT listed fails fast on
# ConnectionLost/timeout — add a method here only after checking its
# replay semantics (the comment says why each entry is safe).

RETRY_SAFE_RPCS = frozenset({
    # GCS tables: keyed overwrites / pure reads
    "register_node", "subscribe", "get_nodes", "cluster_resources",
    "get_cluster_load", "debug_state", "list_objects", "save_snapshot",
    "kv_put", "kv_get", "kv_del", "kv_exists", "kv_keys",
    "add_object_location", "remove_object_location",
    "get_object_locations", "free_objects",
    # actor table: registration dedups by actor_id, started/exited
    # re-announce state the GCS overwrites by id
    "register_actor", "actor_started", "actor_exited", "get_actor",
    "list_actors", "list_named_actors",
    # placement groups: create replays overwrite by pg_id; reads are pure
    "create_placement_group", "get_placement_group",
    "remove_placement_group", "list_placement_groups",
    # replay mints a FRESH id — wastes one, ids stay unique
    "next_job_id",
    # pubsub: at-least-once by contract (subscribers dedup by seq floor);
    # a duplicated publish is a duplicate delivery consumers tolerate.
    # psub_resync replayed just re-registers + re-snapshots (the floor
    # moves forward, newer state only re-delivers)
    "publish", "psub_subscribe", "psub_unsubscribe", "psub_poll",
    "psub_resync",
    # single-node address lookup: pure read (gcs.rpc_get_node_addr)
    "get_node_addr",
    # raylet: a lease grant whose reply was lost leaks a lease the
    # lessee-GC reaps (worker death / remote-lessee sweep); return is
    # idempotent by lease_id
    "request_worker_lease", "return_worker", "register_worker",
    # object plane: pure reads
    "fetch_object", "fetch_object_chunk", "get_owned_value",
    "locate_object", "store_stats", "node_info", "ping", "task_state",
    "report_resources", "drain_node",
    # streaming data plane: a block fetch is a pure read of an immutable
    # sealed object (data/_internal/streaming/executor.py)
    "data_block_fetch",
    # telemetry plane: pure reads (per-process metric/event/span rings)
    "metrics_snapshot", "events_snapshot", "profile_events",
    "trace_spans", "step_records", "blackbox_snapshot",
    # ray:// client protocol: the proxy DEDUPS every mutating op by the
    # session-scoped req_id the client attaches (util/client/server.py),
    # so replay across a proxy restart is safe — these were built to
    # ride ReconnectingRpcClient's heal-and-retry (session resume via
    # on_reconnect replaying client_hello)
    "client_hello", "client_put", "client_put_chunk", "client_get",
    "client_get_chunk", "client_wait", "client_submit_task",
    "client_submit_actor_task", "client_create_actor",
    "client_register_function", "client_gcs_call", "client_cancel",
    "client_kill", "client_release", "client_available_resources",
    "client_timeline",   # pure read (api.timeline())
})

# Methods whose replay is actively harmful — documented fail-fast. (Not
# the complement of RETRY_SAFE_RPCS: unknown methods also fail fast; this
# set exists so is_retry_safe(m, default=True) callers still refuse them.)
NON_RETRY_SAFE_RPCS = frozenset({
    # consumes the actor restart budget: applied-then-lost + retry
    # double-charges it (protocol.ReconnectingRpcClient.call_once doc)
    "actor_failed",
    # task execution: at-most-once per attempt; retries are the task
    # layer's job (retries_left) which knows about side effects
    "push_task",
    # actor creation is driven by _drive_actor_creation with its own
    # spillback walk + actor_failed terminal path
    "create_actor",
})


def is_retry_safe(method: str, default: bool = False) -> bool:
    if method in NON_RETRY_SAFE_RPCS:
        return False
    if method in RETRY_SAFE_RPCS:
        return True
    return default


# -------------------------------------------------------------------- budget


class RetryBudget:
    """Token bucket bounding process-wide retry amplification. take()
    consumes one token per actual retry (first attempts are free);
    tokens refill continuously at ``refill_per_s`` up to ``capacity``."""

    def __init__(self, capacity: float = 100.0, refill_per_s: float = 10.0):
        self.capacity = float(capacity)
        self.refill_per_s = float(refill_per_s)
        self._tokens = float(capacity)
        self._stamp = time.monotonic()
        self._lock = threading.Lock()
        self.exhausted_count = 0   # observability: fail-fasts due to budget

    def take(self) -> bool:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.capacity,
                self._tokens + (now - self._stamp) * self.refill_per_s)
            self._stamp = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            self.exhausted_count += 1
        # outside the lock: exhaustion is rare and the answer to "why did
        # this call fail fast during the outage" — surface it as both a
        # counter and a structured cluster event
        from ray_tpu._private import events as _events
        from ray_tpu._private import telemetry as _tm

        _tm.counter_inc("ray_tpu_retry_budget_exhausted_total")
        _events.record("retry_budget_exhausted",
                       capacity=self.capacity,
                       refill_per_s=self.refill_per_s,
                       exhausted_count=self.exhausted_count)
        return False


_default_budget = RetryBudget()


def default_budget() -> RetryBudget:
    return _default_budget


def full_jitter(cap_s: float) -> float:
    """One full-jitter draw: ``uniform(0, cap_s)``. The herd-damping
    primitive shared by the backoff policy and the reconnect path
    (ReconnectingRpcClient sleeps this before re-dialing a restarted
    endpoint, so 100 clients that lost the same connection in the same
    instant don't re-arrive in the same instant either)."""
    return random.uniform(0.0, cap_s) if cap_s > 0 else 0.0


# -------------------------------------------------------------------- policy


class RetryPolicy:
    """max_attempts × exponential-backoff-with-full-jitter, bounded by a
    wall-clock deadline that also shrinks each attempt's RPC timeout.

    ``attempt_timeout_s`` is the per-attempt RPC timeout; each attempt
    actually gets ``min(attempt_timeout_s, deadline remainder)`` so the
    last attempt cannot blow through the deadline.
    """

    def __init__(self, max_attempts: int = 5,
                 base_backoff_s: float = 0.05,
                 max_backoff_s: float = 2.0,
                 deadline_s: float | None = 60.0,
                 attempt_timeout_s: float | None = None,
                 budget: RetryBudget | None = None):
        self.max_attempts = max(1, int(max_attempts))
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self.deadline_s = deadline_s
        self.attempt_timeout_s = attempt_timeout_s
        self.budget = budget if budget is not None else _default_budget

    @classmethod
    def from_config(cls, attempt_timeout_s: float | None = None,
                    deadline_s: float | None = None) -> "RetryPolicy":
        from ray_tpu._private.config import get_config

        return cls(
            max_attempts=int(get_config("rpc_retry_max_attempts")),
            base_backoff_s=float(get_config("rpc_retry_base_backoff_s")),
            max_backoff_s=float(get_config("rpc_retry_max_backoff_s")),
            deadline_s=(deadline_s if deadline_s is not None
                        else float(get_config("rpc_retry_deadline_s"))),
            attempt_timeout_s=attempt_timeout_s)

    def backoff(self, attempt: int) -> float:
        """Sleep before retry number ``attempt`` (1-indexed): full
        jitter over an exponentially growing cap. The exponent is
        clamped: unlimited-retry callers (gang restarts with
        max_failures=-1) pass an unbounded attempt counter, and
        ``2 ** 1079`` no longer converts to float (OverflowError) —
        past ~60 doublings every base overshoots max_backoff_s anyway."""
        cap = min(self.max_backoff_s,
                  self.base_backoff_s * (2 ** min(60, max(0, attempt - 1))))
        return full_jitter(cap)

    def run(self, fn, *, method: str | None = None,
            retry_on: tuple = (), describe: str = ""):
        """Run ``fn(attempt_timeout_s)`` under this policy.

        ``fn`` receives the per-attempt timeout (None = no cap) and must
        raise to signal failure. Exceptions whose type is in
        ``retry_on`` are retried (subject to method retry-safety, the
        attempt count, the deadline, and the global budget); everything
        else propagates immediately.
        """
        deadline = (time.monotonic() + self.deadline_s
                    if self.deadline_s is not None else None)
        retry_allowed = method is None or is_retry_safe(method)
        attempt = 0
        while True:
            attempt += 1
            timeout = self.attempt_timeout_s
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    remaining = 0.001   # one last, effectively-instant try
                timeout = (min(timeout, remaining)
                           if timeout is not None else remaining)
            try:
                return fn(timeout)
            except retry_on as e:
                if not retry_allowed:
                    raise
                if attempt >= self.max_attempts:
                    raise
                if deadline is not None and \
                        time.monotonic() >= deadline:
                    raise
                if not self.budget.take():
                    raise   # budget drained: stop amplifying the outage
                from ray_tpu._private import telemetry as _tm

                _tm.counter_inc("ray_tpu_retry_attempts_total", tags={
                    "method": method or describe or "?"})
                pause = self.backoff(attempt)
                if deadline is not None:
                    pause = min(pause,
                                max(0.0, deadline - time.monotonic()))
                if pause > 0:
                    time.sleep(pause)
                _ = e   # (kept for symmetry with debuggers' locals view)
