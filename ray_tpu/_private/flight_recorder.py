"""Cluster flight recorder — the always-on per-process black box.

Reference tier: `ray timeline` + the debug-state dumps operators grab
AFTER something died — except those must be requested while the patient
is still alive. Here every process already keeps bounded rings of its
recent telemetry (chrome-timeline spans, tracing spans, structured
events, step-anatomy records, metric registries); this module is the
window cut + the dump fan-out that turns them into a post-mortem
artifact at the moment of failure:

- ``local_snapshot(window_s)`` — one process's recent telemetry, cut to
  the last ``RAY_TPU_FLIGHT_RECORDER_WINDOW_S`` seconds (spans/events
  older than the window are noise by the time a human reads the dump);
- ``dump(reason)`` — fans out over the GCS and every raylet's workers
  (``blackbox_snapshot`` RPC), writes one timestamped directory with a
  per-process ``<node>_<pid>.jsonl`` plus one merged
  ``timeline.json`` chrome trace (pids remapped to be unique across
  hosts — chrome keys processes by pid alone, and pid 4242 on two nodes
  is two different processes);
- ``trigger_dump(reason)`` — the automatic hook, debounced so a failure
  storm produces one black box, not a disk-filling flurry. Wired into
  the gang-failure path (train/trainer.py ``GANG_FAILED``), the
  driver's gang death monitor (train/backend_executor.py), and
  collective group poisoning (util/collective/collective.py).

Kill switch: ``RAY_TPU_INTERNAL_TELEMETRY=0`` disables snapshots,
dumps, and triggers entirely (the rings it reads are off too).
"""
from __future__ import annotations

import json
import os
import threading
import time

from ray_tpu._private import telemetry as _tm

_WINDOW_KNOB = "RAY_TPU_FLIGHT_RECORDER_WINDOW_S"
_DIR_KNOB = "RAY_TPU_FLIGHT_RECORDER_DIR"
_DEFAULT_WINDOW_S = 120.0
_DEBOUNCE_S = 15.0          # min spacing between AUTO dumps per process

_PID = os.getpid()
_NODE = os.uname().nodename

_lock = threading.Lock()
_last_auto_dump_ts = 0.0
_last_dump_path: str | None = None
_dump_seq = 0     # uniquifies same-second dumps from one process


def enabled() -> bool:
    return _tm.ENABLED


def window_s() -> float:
    try:
        return float(os.environ.get(_WINDOW_KNOB, _DEFAULT_WINDOW_S))
    except ValueError:
        return _DEFAULT_WINDOW_S


def base_dir() -> str:
    configured = os.environ.get(_DIR_KNOB)
    if configured:
        return configured
    import tempfile

    return os.path.join(tempfile.gettempdir(), "ray_tpu", "blackbox")


def last_dump_path() -> str | None:
    """The most recent dump this process wrote (None if none) — the
    conftest failure header and operators start post-mortems here."""
    return _last_dump_path


def find_latest_dump(base: str | None = None) -> str | None:
    """Newest dump directory ON DISK under the base dir. The in-memory
    ``last_dump_path`` is per-process — a fresh CLI process asking
    "where did the last auto-dump land?" must scan instead."""
    base = base or base_dir()
    try:
        dumps = [d for d in os.listdir(base)
                 if d.startswith("blackbox_")]
    except OSError:
        return None
    if not dumps:
        return None
    paths = [os.path.join(base, d) for d in dumps]
    return max(paths, key=lambda p: (os.path.getmtime(p), p))


def local_snapshot(window: float | None = None) -> dict:
    """This process's black box: recent spans/events/steps + a metrics
    snapshot, cut to the window. Cheap (ring copies); safe to call from
    failure paths."""
    if not enabled():
        return {}
    if window is None:
        window = window_s()
    now = time.time()
    cutoff = now - window
    out = {"node": _NODE, "pid": _PID, "ts": now, "window_s": window}
    try:
        from ray_tpu._private import events as _events

        out["events"] = [e for e in _events.snapshot()
                         if e.get("ts", now) >= cutoff]
    except Exception:
        out["events"] = []
    try:
        from ray_tpu._private import profiling as _prof

        cutoff_us = cutoff * 1e6
        out["timeline"] = [e for e in _prof.snapshot()
                           if e.get("ts", 0) + e.get("dur", 0)
                           >= cutoff_us]
        out["timeline_dropped"] = _prof.stats()["dropped"]
    except Exception:
        out["timeline"] = []
    try:
        from ray_tpu.util import tracing

        cutoff_ns = cutoff * 1e9
        out["spans"] = [s for s in tracing.local_spans()
                        if s.get("endTimeUnixNano", 0) >= cutoff_ns]
        out["spans_dropped"] = tracing.stats()["dropped"]
    except Exception:
        out["spans"] = []
    try:
        from ray_tpu.parallel import step_anatomy as _sa

        out["steps"] = _sa.local_records()
    except Exception:
        out["steps"] = {}
    try:
        from ray_tpu._private.events import _role
        from ray_tpu.util.metrics import registry_snapshot

        out["role"] = _role()
        out["metrics"] = registry_snapshot()
    except Exception:
        out["metrics"] = []
    try:
        from ray_tpu._private import memory_anatomy as _ma

        # ring cut to the dump window: a leak post-mortem reads the
        # put/delete history around the incident, not process lifetime
        out["memory"] = _ma.local_snapshot(top_k=10, window_s=window)
    except Exception:
        out["memory"] = {}
    return out


def _collect(address: str | None) -> list[dict]:
    """This process + the GCS + every raylet's workers. Degrades to
    driver-local when there is no cluster to ask (the black box of the
    one process you have beats no black box)."""
    snaps = [local_snapshot()]
    try:
        from ray_tpu.experimental.state.api import _each_raylet, _gcs

        with _gcs(address) as call:
            try:
                snaps.extend(call("blackbox_snapshot"))
            except Exception:
                pass   # older GCS build: its ring just isn't visible
            snaps.extend(_each_raylet(call, "blackbox_snapshot"))
    except Exception:
        pass
    # dedup by (node, pid): the driver answers locally AND through the
    # fan-out in in-process clusters
    seen: set[tuple] = set()
    out = []
    for s in snaps:
        if not s:
            continue
        key = (s.get("node"), s.get("pid"))
        if key in seen:
            continue
        seen.add(key)
        out.append(s)
    return out


def merged_timeline(snaps: list[dict]) -> list[dict]:
    """One chrome-trace event list over every process's recent spans.
    Pids are remapped to unique ints — chrome://tracing keys processes
    by pid, and pids collide across hosts — with ``process_name``
    metadata rows carrying the real (node, pid) identity. Sorted by
    ``ts`` (arrival order does not matter)."""
    pid_map: dict[tuple, int] = {}
    out: list[dict] = []
    for s in snaps:
        key = (s.get("node"), s.get("pid"))
        if key not in pid_map:
            pid_map[key] = len(pid_map) + 1
            out.append({"ph": "M", "name": "process_name",
                        "pid": pid_map[key], "ts": 0,
                        "args": {"name": f"{key[0]}/pid{key[1]}"}})
        fake = pid_map[key]
        if s.get("timeline_dropped"):
            # a ring that evicted spans must say so IN the merged file
            # a post-mortem reader actually loads, not only in the
            # per-process jsonl header chrome never shows
            out.append({"ph": "M", "name": "ray_tpu_timeline_dropped",
                        "pid": fake, "ts": 0,
                        "args": {"dropped": s["timeline_dropped"]}})
        for e in s.get("timeline", ()):
            e = dict(e)
            e["pid"] = fake
            out.append(e)
    out.sort(key=lambda e: (e.get("ts", 0), e.get("ph") != "M"))
    return out


def dump(reason: str, *, address: str | None = None,
         out_dir: str | None = None) -> str | None:
    """Write one black-box dump directory and return its path:
    ``<base>/blackbox_<utc-stamp>_<reason>/`` with one ``.jsonl`` per
    process (line 1: a header with identity/window/drop counts; then one
    line per event/span/step record tagged with its source table) and a
    merged ``timeline.json`` loadable at chrome://tracing."""
    global _last_dump_path, _dump_seq
    if not enabled():
        return None
    snaps = _collect(address)
    stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
    safe_reason = "".join(c if c.isalnum() or c in "-_" else "_"
                          for c in reason)[:48] or "manual"
    with _lock:
        _dump_seq += 1
        seq = _dump_seq
    # per-process seq in the name: the stamp is 1s-resolution, and two
    # dumps in the same second (retrying gang + manual) must not merge
    # into one directory overwriting each other's files
    path = os.path.join(
        out_dir or base_dir(),
        f"blackbox_{stamp}_{os.getpid()}_{seq}_{safe_reason}")
    os.makedirs(path, exist_ok=True)
    for s in snaps:
        fname = f"{s.get('node', 'node')}_{s.get('pid', 0)}.jsonl"
        with open(os.path.join(path, fname), "w") as f:
            header = {k: s.get(k) for k in
                      ("node", "pid", "role", "ts", "window_s",
                       "timeline_dropped", "spans_dropped")}
            f.write(json.dumps({"table": "header", **header,
                                "reason": reason}) + "\n")
            for table in ("events", "spans", "timeline"):
                for row in s.get(table, ()):
                    f.write(json.dumps({"table": table, **row},
                                       default=str) + "\n")
            steps = s.get("steps") or {}
            for row in steps.get("steps", ()):
                f.write(json.dumps({"table": "step", **row}) + "\n")
            for row in steps.get("activities", ()):
                f.write(json.dumps({"table": "activity", **row}) + "\n")
            f.write(json.dumps({"table": "metrics",
                                "metrics": s.get("metrics", [])},
                               default=str) + "\n")
    with open(os.path.join(path, "timeline.json"), "w") as f:
        json.dump(merged_timeline(snaps), f)
    with open(os.path.join(path, "memory.jsonl"), "w") as f:
        # one line per process: ledger summary row + its recent
        # put/delete ring rows (the leak post-mortem's provenance feed)
        for s in snaps:
            mem = s.get("memory") or {}
            if not mem:
                continue
            summary = {k: v for k, v in mem.items() if k != "ring"}
            f.write(json.dumps({"table": "memory_summary",
                                "node": s.get("node"),
                                "pid": s.get("pid"), **summary},
                               default=str) + "\n")
            for row in mem.get("ring", ()):
                f.write(json.dumps({"table": "memory_ring",
                                    "node": s.get("node"),
                                    "pid": s.get("pid"), **row},
                                   default=str) + "\n")
    with _lock:
        _last_dump_path = path
    from ray_tpu._private import events as _events

    _events.record("FLIGHT_RECORDER_DUMP", reason=reason, path=path,
                   processes=len(snaps))
    _tm.counter_inc("ray_tpu_flight_recorder_dumps_total",
                    tags={"trigger": safe_reason})
    return path


def trigger_dump(reason: str, *, address: str | None = None,
                 background: bool = False,
                 force: bool = False) -> str | None:
    """The automatic failure hook: debounced ``dump`` that never raises
    into the failure path it rides on. ``background=True`` runs the dump
    on a daemon thread (for callbacks that must not block, e.g. the
    pubsub death feed). ``force=True`` skips the debounce — for flagship
    triggers (GANG_FAILED) whose dump must capture state recorded
    moments after a sibling trigger already fired."""
    global _last_auto_dump_ts
    if not enabled():
        return None
    with _lock:
        now = time.monotonic()
        if not force and now - _last_auto_dump_ts < _DEBOUNCE_S:
            return None
        _last_auto_dump_ts = now
    if background:
        threading.Thread(target=lambda: trigger_dump_now(reason, address),
                         daemon=True, name="flight-recorder-dump").start()
        return None
    return trigger_dump_now(reason, address)


def trigger_dump_now(reason: str, address: str | None = None):
    try:
        return dump(reason, address=address)
    except Exception:
        return None   # the black box must never worsen the crash
