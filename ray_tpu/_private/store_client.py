"""Python client for the native shared-memory object store.

Analog of the reference's PlasmaClient
(/root/reference/src/ray/object_manager/plasma/client.h) — but because the
store is a mapped library rather than a daemon (see src/store/store.cc),
put/get are direct shared-memory calls with no socket round trip.

Adds the policy layers plasma keeps in C++:
- spill-to-disk when the segment is full (reference:
  raylet/local_object_manager.h:110 SpillObjects) and transparent restore;
- pinned-buffer lifetime tied to the returned memoryview.
"""
from __future__ import annotations

import ctypes
import os
import threading
import time

from ray_tpu._private import memory_anatomy as _ma
from ray_tpu._private import telemetry as _tm
from ray_tpu._private.native_build import ensure_lib

_ERRORS = {
    0: "OK",
    -1: "NOT_FOUND",
    -2: "EXISTS",
    -3: "FULL",
    -4: "TABLE_FULL",
    -5: "NOT_SEALED",
    -6: "IN_USE",
    -7: "SYS",
    -8: "BAD_SEGMENT",
    -9: "CLOSED",
}


class StoreError(Exception):
    def __init__(self, code: int, op: str):
        self.code = code
        super().__init__(f"store {op} failed: {_ERRORS.get(code, code)}")


def _load():
    lib = ctypes.CDLL(ensure_lib("raystore"))
    lib.store_create.restype = ctypes.c_void_p
    lib.store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64]
    lib.store_connect.restype = ctypes.c_void_p
    lib.store_connect.argtypes = [ctypes.c_char_p]
    for fn in ("store_disconnect", "store_destroy"):
        getattr(lib, fn).restype = None
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib.store_create_object.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_void_p),
    ]
    lib.store_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64),
    ]
    for fn in ("store_seal", "store_abort", "store_release", "store_contains",
               "store_delete"):
        getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.store_stats.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64 * 4)]
    lib.store_list.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64]
    lib.store_data_server_start.restype = ctypes.c_void_p
    lib.store_data_server_start.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
    lib.store_data_server_stop.restype = ctypes.c_int
    lib.store_data_server_stop.argtypes = [ctypes.c_void_p]
    return lib


_lib = None
_lib_lock = threading.Lock()


def _get_lib():
    global _lib
    if _lib is None:
        with _lib_lock:
            if _lib is None:
                _lib = _load()
    return _lib


class PinnedBuffer:
    """A zero-copy view of a sealed object; releases its pin when closed or
    garbage-collected."""

    def __init__(self, client: "StoreClient", object_id: bytes,
                 ptr: int, size: int):
        self._client = client
        self._id = object_id
        self._view = (ctypes.c_char * size).from_address(ptr)
        self._released = False

    def memoryview(self) -> memoryview:
        return memoryview(self._view)

    def to_bytes(self) -> bytes:
        return bytes(self._view)

    def release(self):
        if not self._released:
            self._released = True
            self._client._release(self._id)

    def __len__(self):
        return len(self._view)

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass




def _guarded(fn):
    """Count the thread into the segment for the duration of the C calls
    (close() waits for the count to drain before unmapping)."""
    def wrapper(self, *args, **kwargs):
        self._enter()
        try:
            return fn(self, *args, **kwargs)
        finally:
            self._exit()
    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper

class StoreClient:
    """Connects to (or creates) one node's shm segment. Thread-safe: the
    native layer serializes via the in-segment robust mutex."""

    def __init__(self, name: str, create: bool = False,
                 size: int = 256 * 1024 * 1024, n_slots: int = 32768,
                 spill_dir: str | None = None):
        if create:
            if n_slots & (n_slots - 1) or n_slots == 0:
                raise ValueError("n_slots must be a power of two")
            # Header + entry table + at least one allocatable block must fit.
            min_size = 4096 + n_slots * 48 + 64 * 1024
            if size < min_size:
                raise ValueError(
                    f"segment size {size} too small for {n_slots} slots "
                    f"(need >= {min_size})"
                )
        self._libref = _get_lib()
        self.name = name
        self._owner = create
        if create:
            self._h = self._libref.store_create(name.encode(), size, n_slots)
        else:
            self._h = self._libref.store_connect(name.encode())
        if not self._h:
            raise StoreError(-8, "create" if create else "connect")
        self.spill_dir = spill_dir
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
        # In-flight guard: close() must not unmap the segment while other
        # threads are inside a C call on it, or while PinnedBuffers still
        # point into it — either is a use-after-munmap segfault (observed
        # in cluster teardown: a dispatch thread serving get_owned_value
        # raced worker.shutdown's store close). _active counts C calls,
        # _pins counts outstanding PinnedBuffers.
        self._guard = threading.Condition()
        self._active = 0
        self._pins = 0
        self._closing = False

    def _enter(self):
        with self._guard:
            if self._closing or not self._h:
                raise StoreError(-9, "closed")
            self._active += 1
            return self._h

    def _exit(self):
        with self._guard:
            self._active -= 1
            if self._active == 0:
                self._guard.notify_all()

    def start_data_server(self, port: int = 0) -> int:
        """Start the native (C++) chunk server over this segment; returns
        the bound TCP port. Serving threads read straight from the mmap —
        no Python/GIL on the data path (src/store/data_server.cc). Stopped
        automatically (before the segment is torn down) in close()."""
        out_port = ctypes.c_int(0)
        handle = self._libref.store_data_server_start(
            self._h, port, ctypes.byref(out_port))
        if not handle:
            raise StoreError(-8, "data_server_start")
        self._data_server_handle = handle
        return out_port.value

    def stop_data_server(self) -> bool:
        handle = getattr(self, "_data_server_handle", None)
        if not handle:
            return True
        rc = self._libref.store_data_server_stop(handle)
        self._data_server_handle = None
        return rc == 0

    # -- core ops -----------------------------------------------------------

    @staticmethod
    def _check_id(object_id: bytes):
        if len(object_id) != 16:
            raise ValueError(f"object id must be 16 bytes, got {len(object_id)}")

    @_guarded
    def put(self, object_id: bytes, data) -> bool:
        """Store `data` (bytes-like). Returns False if the object already
        exists (puts are idempotent — including objects that only exist
        spilled on disk). Spills to disk if the segment can't fit it even
        after eviction."""
        self._check_id(object_id)
        created, _size = self._put_views(
            object_id, [memoryview(data).cast("B")])
        return created

    def put_parts(self, object_id: bytes, parts: list) -> int:
        """put() from a frame-parts list (serialize_parts): each part is
        copied straight into the segment (or streamed to the spill
        file) without assembling them first — saves one full copy of
        every out-of-band buffer. Returns the total byte size."""
        _created, total = self._put_views(
            object_id, [memoryview(p).cast("B") for p in parts])
        return total

    def _put_views(self, object_id: bytes, views: list) -> tuple[bool, int]:
        """Single EXISTS/FULL/spill decision path shared by put() and
        put_parts(). Returns (created, total_size); created=False means
        the object already existed (sealed, mid-create, or spilled) —
        puts are idempotent."""
        total = sum(len(v) for v in views)
        if self._spilled_path_if_exists(object_id) is not None:
            return False, total
        if total <= self._capacity():
            try:
                buf = self.create(object_id, total)
            except StoreError as e:
                # FULL / TABLE_FULL (e.g. everything pinned): fall back
                # to the spill file
                if e.code not in (-3, -4) or self.spill_dir is None:
                    raise
                buf = None
            else:
                if buf is None:
                    # EXISTS (sealed or another producer mid-create):
                    # immutable objects make the duplicate a no-op
                    return False, total
                try:
                    dst = memoryview(buf).cast("B")
                    off = 0
                    for v in views:
                        dst[off:off + len(v)] = v
                        off += len(v)
                    self.seal(object_id)
                    if _tm.ENABLED:
                        _tm.counter_inc(
                            "ray_tpu_object_store_put_bytes_total", total)
                        _ma.LEDGER.note_put(object_id, total)
                    return True, total
                except BaseException:
                    self.abort(object_id)
                    raise
        if self.spill_dir is None:
            raise StoreError(-3, "put")
        self._spill_write(object_id, views)
        if _tm.ENABLED:
            _tm.counter_inc("ray_tpu_object_store_put_bytes_total", total)
            _ma.LEDGER.note_put(object_id, total)
        return True, total

    def put_ephemeral(self, object_id: bytes, parts: list) -> int:
        """put_parts for TRANSIENT objects (the collective data plane's
        same-node segments): skips the spill-existence probe and the
        spill fallback — these ids are freshly minted per message, are
        consumed within one op, and must never hit disk. Raises
        StoreError when the segment can't fit (callers fall back to the
        socket path). An id that already EXISTS can only be a stranded
        leftover from a crashed prior incarnation (live processes mint
        unique ids) — serving its stale bytes to the new consumer would
        be silent corruption, so the stale object is deleted and the
        create retried; if it still exists (e.g. pinned by a zombie),
        raise so the caller takes the socket path."""
        views = [memoryview(p).cast("B") for p in parts]
        total = sum(len(v) for v in views)
        buf = self.create(object_id, total)
        if buf is None:
            self.delete_ephemeral(object_id)
            buf = self.create(object_id, total)
            if buf is None:
                # still present (e.g. pinned by a zombie consumer)
                raise StoreError(-2, "put_ephemeral")
        try:
            dst = memoryview(buf).cast("B")
            off = 0
            for v in views:
                dst[off:off + len(v)] = v
                off += len(v)
            self.seal(object_id)
        except BaseException:
            self.abort(object_id)
            raise
        if _tm.ENABLED:
            _tm.counter_inc("ray_tpu_object_store_put_bytes_total", total)
            _ma.LEDGER.note_put(object_id, total, ephemeral=True)
        return total

    @_guarded
    def delete_ephemeral(self, object_id: bytes):
        """delete() for objects known never to spill: skips the spill-
        path stat (a per-call filesystem probe the segment hot path
        can't afford). Best-effort, with one accounting exception: a
        delete refused because another process's pin is still live
        (ERR_IN_USE — e.g. a forwarding hop mid-unpin) is retried once
        after a beat behind config ``store_free_resend``, and counted
        as a dropped free if it still refuses — an uncounted refusal
        here is a permanently stranded segment."""
        self._check_id(object_id)
        rc = self._libref.store_delete(self._h, object_id)
        if rc == -6:                              # ERR_IN_USE
            resend = 0
            try:
                from ray_tpu._private.config import get_config

                resend = int(get_config("store_free_resend"))
            except Exception:
                pass
            if resend > 0:
                time.sleep(0.002)     # off the op critical path: the
                #                       last consumer deletes after its
                #                       op already completed
                rc = self._libref.store_delete(self._h, object_id)
            if rc == -6 and _tm.ENABLED:
                _ma.LEDGER.note_free_dropped("ephemeral_pinned")
        if _tm.ENABLED:
            _ma.LEDGER.note_delete(object_id)

    @_guarded
    def create(self, object_id: bytes, size: int):
        """Reserve a writable buffer; caller fills it then calls seal().
        Returns a ctypes array or None if the object exists."""
        self._check_id(object_id)
        ptr = ctypes.c_void_p()
        rc = self._libref.store_create_object(self._h, object_id, size,
                                              ctypes.byref(ptr))
        if rc == -2:
            return None
        if rc != 0:
            raise StoreError(rc, "create")
        return (ctypes.c_ubyte * size).from_address(ptr.value)

    @_guarded
    def seal(self, object_id: bytes):
        rc = self._libref.store_seal(self._h, object_id)
        if rc != 0:
            raise StoreError(rc, "seal")

    @_guarded
    def abort(self, object_id: bytes):
        """Discard an unsealed create() reservation (e.g. a network pull
        that died mid-write into the segment)."""
        self._libref.store_abort(self._h, object_id)

    @_guarded
    def get(self, object_id: bytes) -> PinnedBuffer | None:
        """Pin + return a sealed object, restoring from spill if needed.

        Known limitation (vs the reference's plasma daemon, which cleans up
        when a client socket drops): a pin held by a SIGKILLed process is
        never reclaimed, so that object stays unevictable. Worker crashes
        are followed by a store segment sweep at the raylet level.
        """
        self._check_id(object_id)
        ptr = ctypes.c_void_p()
        size = ctypes.c_uint64()
        rc = self._libref.store_get(self._h, object_id, ctypes.byref(ptr),
                                    ctypes.byref(size))
        if rc == -1:
            if self._spilled_path_if_exists(object_id) is None:
                _tm.counter_inc("ray_tpu_object_store_get_total",
                                tags={"result": "miss"})
                return None
            fallback = self._spill_restore(object_id)
            if fallback is not None:
                # Couldn't fit back in shm — serve the spilled bytes directly.
                _tm.counter_inc("ray_tpu_object_store_get_total",
                                tags={"result": "hit"})
                return fallback
            rc = self._libref.store_get(self._h, object_id, ctypes.byref(ptr),
                                        ctypes.byref(size))
            if rc == -1:
                # Restored copy already evicted by a concurrent put; the
                # spill file is still the source of truth.
                with open(self._spill_path(object_id), "rb") as f:
                    _tm.counter_inc("ray_tpu_object_store_get_total",
                                    tags={"result": "hit"})
                    return _BytesBuffer(f.read())
            if rc != 0:
                raise StoreError(rc, "get")
        elif rc != 0:
            raise StoreError(rc, "get")
        with self._guard:
            self._pins += 1   # close() waits for pins: the buffer's view
        if _tm.ENABLED:
            _tm.counter_inc("ray_tpu_object_store_get_total",
                            tags={"result": "hit"})
            _ma.LEDGER.note_pin(object_id)
        return PinnedBuffer(self, object_id, ptr.value, size.value)

    @_guarded
    def contains(self, object_id: bytes) -> bool:
        self._check_id(object_id)
        rc = self._libref.store_contains(self._h, object_id)
        if rc == 1:
            return True
        if rc == 0:
            return self._spilled_path_if_exists(object_id) is not None
        raise StoreError(rc, "contains")

    @_guarded
    def delete(self, object_id: bytes):
        self._check_id(object_id)
        self._libref.store_delete(self._h, object_id)  # best-effort
        p = self._spilled_path_if_exists(object_id)
        if p:
            try:
                os.unlink(p)
            except OSError:
                pass
        if _tm.ENABLED:
            _ma.LEDGER.note_delete(object_id)

    def _capacity(self) -> int:
        """Usable heap bytes for ONE object (cached on success only —
        a transient stats() failure must not disable the oversized
        short-circuit forever). 128 bytes of allocator headroom mirror
        heap_alloc's per-allocation overhead, so near-heap-size objects
        short-circuit too instead of evicting everything and failing."""
        cap = getattr(self, "_capacity_cache", None)
        if cap is None:
            try:
                cap = max(0, int(self.stats()["heap_size"]) - 128)
                self._capacity_cache = cap
            except Exception:
                return 1 << 62   # unknown right now: don't short-circuit
        return cap

    @_guarded
    def stats(self) -> dict:
        out = (ctypes.c_uint64 * 4)()
        rc = self._libref.store_stats(self._h, ctypes.byref(out))
        if rc != 0:
            raise StoreError(rc, "stats")
        return {
            "num_objects": out[0],
            "bytes_used": out[1],
            "heap_size": out[2],
            "evictions": out[3],
        }

    def list_objects(self, max_objects: int = 65536) -> list[tuple[bytes, int]]:
        """(object_id, size) of every sealed object in the segment, plus
        spilled ones. Feeds `ray-tpu memory` now that locations live with
        owners instead of a central GCS table."""
        ids = ctypes.create_string_buffer(16 * max_objects)
        sizes = (ctypes.c_uint64 * max_objects)()
        n = self._libref.store_list(
            self._h, ids,
            ctypes.cast(sizes, ctypes.POINTER(ctypes.c_uint64)),
            max_objects)
        if n < 0:
            raise StoreError(n, "list")
        out = [(ids.raw[i * 16:(i + 1) * 16], int(sizes[i]))
               for i in range(n)]
        if self.spill_dir and os.path.isdir(self.spill_dir):
            seen = {oid for oid, _ in out}
            for fname in os.listdir(self.spill_dir):
                try:
                    oid = bytes.fromhex(fname)
                except ValueError:
                    continue
                if len(oid) == 16 and oid not in seen:
                    try:
                        out.append((oid, os.path.getsize(
                            os.path.join(self.spill_dir, fname))))
                    except OSError:
                        pass   # freed between listdir and stat — skip
        return out

    def _release(self, object_id: bytes):
        if _tm.ENABLED:
            _ma.LEDGER.note_unpin(object_id)
        with self._guard:
            self._pins = max(0, self._pins - 1)
            if self._pins == 0:
                self._guard.notify_all()
            if self._closing or not self._h:
                return   # unpin bookkeeping only; segment may be gone
            self._active += 1
        try:
            self._libref.store_release(self._h, object_id)
        finally:
            self._exit()

    # -- spilling -----------------------------------------------------------

    def _spill_path(self, object_id: bytes) -> str:
        return os.path.join(self.spill_dir, object_id.hex())

    def _spilled_path_if_exists(self, object_id: bytes) -> str | None:
        if not self.spill_dir:
            return None
        p = self._spill_path(object_id)
        return p if os.path.exists(p) else None

    def _spill_write(self, object_id: bytes, data):
        """data: one buffer or a list of buffers (parts path). Atomic:
        tmp file + rename, so readers never see a half-written spill."""
        p = self._spill_path(object_id)
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            if isinstance(data, (list, tuple)):
                for piece in data:
                    f.write(piece)
            else:
                f.write(data)
        os.replace(tmp, p)

    def _spill_restore(self, object_id: bytes):
        """Try to reload a spilled object into shm; on shm pressure return a
        bytes-backed stand-in buffer."""
        p = self._spilled_path_if_exists(object_id)
        if p is None:
            return None
        size = os.path.getsize(p)
        if size > self._capacity():
            # can never re-enter shm: serve the file MAPPED — the only
            # full pass over the bytes is the consumer's own read
            # (deserialize), with OS readahead paging it in
            import mmap

            with open(p, "rb") as f:
                mm = mmap.mmap(f.fileno(), 0, prot=mmap.PROT_READ)
            return _BytesBuffer(mm)
        with open(p, "rb") as f:
            data = f.read()
        buf = None
        try:
            buf = self.create(object_id, len(data))
        except StoreError:
            pass  # segment still full → serve from host memory
        if buf is None:
            return _BytesBuffer(data)
        memoryview(buf).cast("B")[:] = data
        self.seal(object_id)
        return None  # caller re-gets from shm (zero-copy)

    # -- lifecycle ----------------------------------------------------------

    def close(self, drain_timeout_s: float = 1.0):
        """Unmap the segment once every in-flight C call and pinned buffer
        is gone. If they don't drain within the timeout (wedged dispatch
        thread, leaked pin), deliberately LEAK the mapping — a few MB of
        leaked shm beats a use-after-munmap segfault in whatever thread
        was still reading (seen: cluster teardown racing a borrower
        fetch)."""
        with self._guard:
            if self._closing or not self._h:
                return
            self._closing = True
        # serving threads (C data server) must also be gone first
        if not self.stop_data_server():
            self._h = None
            return
        with self._guard:
            deadline = time.monotonic() + drain_timeout_s
            while self._active > 0 or self._pins > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._guard.wait(remaining)
            leak = self._active > 0 or self._pins > 0
            h, self._h = self._h, None
        if h and not leak:
            if self._owner:
                self._libref.store_destroy(h)
            else:
                self._libref.store_disconnect(h)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _BytesBuffer:
    """PinnedBuffer-compatible wrapper over host memory (spill fallback:
    plain bytes, or a read-only mmap of the spill file)."""

    def __init__(self, data):
        self._data = data

    def memoryview(self) -> memoryview:
        return memoryview(self._data)

    def to_bytes(self) -> bytes:
        # contract: ALWAYS bytes (the RPC path pickles the result; an
        # mmap object would not survive that)
        if isinstance(self._data, bytes):
            return self._data
        return bytes(self._data)

    def view(self) -> memoryview:
        """Zero-copy view, valid for the buffer's lifetime (release is
        a no-op here, unlike PinnedBuffer whose storage unpins). The
        local get path uses this so an mmap'd spill file is consumed
        without a full-copy to_bytes."""
        return memoryview(self._data)

    def release(self):
        pass

    def __len__(self):
        return len(self._data)
