"""Public API — ray_tpu.init / remote / get / put / wait / actors.

Analog of the reference's python/ray/_private/worker.py (ray.init at :1031,
get/put/wait at :2230,2329,2385, @ray.remote at :2709-2808),
python/ray/remote_function.py and python/ray/actor.py, re-based on the
TPU-native runtime: GCS + raylet run in-process for local mode (the
single-node quickstart), workers are real OS processes sharing the node's
shm object store.
"""
from __future__ import annotations

import atexit
import functools
import inspect
import os
import threading
import time

from ray_tpu import exceptions as exc
from ray_tpu._private.object_ref import ObjectRef, ObjectRefGenerator
from ray_tpu._private.worker_runtime import (
    CoreWorker,
    current_worker,
    set_current_worker,
)

_global_lock = threading.RLock()
_global_node = None     # _LocalNode for locally started clusters
_namespace = "default"
_log_printer = None     # DriverLogPrinter while connected as driver


class _LocalNode:
    """In-process head: GCS + raylet threads (the reference forks gcs_server
    and raylet processes, node.py:1045; in-process keeps the local quickstart
    fast — multi-node tests use cluster_utils.Cluster which adds more raylets,
    and production uses the CLI to run them standalone)."""

    def __init__(self, num_cpus=None, num_tpus=None, resources=None,
                 object_store_memory=None, session_dir=None):
        from ray_tpu._private.gcs import GcsServer
        from ray_tpu._private.raylet import Raylet, detect_resources

        self.session_dir = session_dir or os.path.join(
            "/tmp/ray_tpu", f"session_{os.getpid()}_{int(time.time())}")
        os.makedirs(self.session_dir, exist_ok=True)
        self.gcs = GcsServer(
            snapshot_path=os.path.join(self.session_dir, "gcs_snapshot")
        ).start()
        self.raylet = Raylet(
            self.gcs.addr,
            resources=detect_resources(num_cpus, num_tpus, resources=resources),
            store_size=object_store_memory or 256 * 1024 * 1024,
            session_dir=self.session_dir,
        )

    def stop(self):
        self.raylet.stop()
        self.gcs.stop()


def init(address=None, *, num_cpus=None, num_tpus=None, num_gpus=None,
         resources=None, namespace=None, object_store_memory=None,
         ignore_reinit_error=False, **kwargs):
    """Start (or connect to) a cluster and connect this process as driver.

    address=None starts a local head; address="host:port" connects to an
    existing GCS; address="auto" reads RAY_TPU_ADDRESS.
    `num_gpus` is accepted for reference-API compatibility and maps to TPU
    chips.
    """
    global _global_node, _namespace
    with _global_lock:
        if current_worker() is not None:
            if ignore_reinit_error:
                return RayContext(current_worker())
            raise RuntimeError("ray_tpu.init() called twice "
                              "(pass ignore_reinit_error=True to allow)")
        if namespace:
            _namespace = namespace
        if num_tpus is None and num_gpus is not None:
            num_tpus = num_gpus
        # init(system_config=...) beats env beats defaults (config.py
        # contract; reference: ray.init(_system_config=...)). Applied
        # before any component starts so the in-process GCS/raylet (and
        # their monitors) see the overrides.
        from ray_tpu._private.config import GlobalConfig

        GlobalConfig.apply_system_config(
            kwargs.pop("system_config", None)
            or kwargs.pop("_system_config", None))
        if isinstance(address, str) and address.startswith("ray://"):
            # client mode: everything proxies through one endpoint
            # (reference: util/client/, ray.init("ray://...") at
            # worker.py:1031)
            from ray_tpu.util.client import connect

            ctx = connect(address[len("ray://"):])
            set_current_worker(ctx)
            atexit.register(shutdown)
            return RayContext(ctx)
        if address in (None, "local"):
            _global_node = _LocalNode(num_cpus, num_tpus, resources,
                                      object_store_memory)
            gcs_addr = _global_node.gcs.addr
            raylet_addr = _global_node.raylet.addr
        else:
            if address == "auto":
                address = os.environ["RAY_TPU_ADDRESS"]
            host, port = address.rsplit(":", 1)
            gcs_addr = (host, int(port))
            raylet_addr = _find_raylet(gcs_addr)
        worker = CoreWorker(gcs_addr, raylet_addr, mode="driver")
        set_current_worker(worker)
        # Stream worker stdout/stderr to this console (reference:
        # worker.py:1733 print_worker_logs; disable with
        # log_to_driver=False or RAY_TPU_LOG_TO_DRIVER=0).
        from ray_tpu._private.config import get_config

        global _log_printer
        if kwargs.get("log_to_driver", get_config("log_to_driver")) \
                and not os.environ.get("RAY_TPU_QUIET"):
            from ray_tpu._private.log_monitor import DriverLogPrinter

            try:
                _log_printer = DriverLogPrinter(gcs_addr)
            except Exception:
                _log_printer = None
        atexit.register(shutdown)
        return RayContext(worker)


def _find_raylet(gcs_addr):
    """Pick this host's raylet from the GCS node table (or any alive one)."""
    from ray_tpu._private.protocol import RpcClient

    client = RpcClient(gcs_addr)
    try:
        nodes = [n for n in client.call("get_nodes") if n["Alive"]]
    finally:
        client.close()
    if not nodes:
        raise RuntimeError("no alive nodes in cluster")
    hostname = os.uname().nodename
    for n in nodes:
        if n.get("hostname") == hostname:
            return (n["NodeManagerAddress"], n["NodeManagerPort"])
    return (nodes[0]["NodeManagerAddress"], nodes[0]["NodeManagerPort"])


def shutdown():
    global _global_node, _log_printer
    with _global_lock:
        if _log_printer is not None:
            try:
                _log_printer.stop()
            except Exception:
                pass
            _log_printer = None
        worker = current_worker()
        if worker is not None:
            worker.shutdown()
            set_current_worker(None)
        if _global_node is not None:
            _global_node.stop()
            _global_node = None
        from ray_tpu._private.config import GlobalConfig

        GlobalConfig.reset_system_config()
        try:
            atexit.unregister(shutdown)
        except Exception:
            pass


def is_initialized() -> bool:
    return current_worker() is not None


def _require_worker() -> CoreWorker:
    worker = current_worker()
    if worker is None:
        raise RuntimeError(
            "ray_tpu has not been initialized — call ray_tpu.init()")
    return worker


# --------------------------------------------------------------------- basics

def put(value) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("put() on an ObjectRef is not allowed")
    return _require_worker().put(value)


def get(refs, *, timeout=None):
    worker = _require_worker()
    if isinstance(refs, list):
        bad = [r for r in refs if not isinstance(r, ObjectRef)]
        if bad:
            raise TypeError(f"get() takes ObjectRefs, got {type(bad[0])}")
    elif not isinstance(refs, ObjectRef):
        raise TypeError(f"get() takes an ObjectRef or list, got {type(refs)}")
    return worker.get(refs, timeout=timeout)


def wait(refs, *, num_returns=1, timeout=None, fetch_local=True):
    if not isinstance(refs, list):
        raise TypeError("wait() takes a list of ObjectRefs")
    return _require_worker().wait(refs, num_returns=num_returns,
                                  timeout=timeout, fetch_local=fetch_local)


def kill(actor, *, no_restart=True):
    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() takes an ActorHandle")
    worker = _require_worker()
    if getattr(worker, "mode", None) == "client":
        # raylet addresses are cluster-internal; the proxy kills for us
        worker.kill_actor(actor._actor_id, no_restart=no_restart)
        return
    info = worker.gcs.call("get_actor", actor_id=actor._actor_id)
    if info is None:
        return
    node_id = None
    # find the actor's raylet via its node
    snap = worker.gcs.call("list_actors")
    for a in snap:
        if a["ActorID"] == actor._actor_id.hex():
            node_id = a["NodeID"]
            break
    from ray_tpu._private.protocol import RpcClient

    for n in worker.gcs.call("get_nodes"):
        if n["NodeID"] == node_id and n["Alive"]:
            c = RpcClient((n["NodeManagerAddress"], n["NodeManagerPort"]))
            try:
                c.call("kill_actor", actor_id=actor._actor_id,
                       no_restart=no_restart)
            finally:
                c.close()
            return


def cancel(ref: ObjectRef, *, force=False, recursive=True):
    """Best-effort cancellation of the task producing `ref`: a queued task
    is dropped, a running one is flagged (force interrupts the executing
    thread). get(ref) raises TaskCancelledError if the cancel won."""
    if not isinstance(ref, ObjectRef):
        raise TypeError("cancel() takes an ObjectRef")
    _require_worker().cancel_task(ref, force=force)


def get_actor(name: str, namespace: str | None = None) -> "ActorHandle":
    worker = _require_worker()
    info = worker.gcs.call("get_actor", name=name,
                           namespace=namespace or _namespace)
    if info is None or info["state"] == "DEAD":
        raise ValueError(f"actor {name!r} not found")
    meta = info.get("spec_meta") or {}
    return ActorHandle(info["actor_id"],
                       max_task_retries=meta.get("max_task_retries", 0))


def nodes():
    return _require_worker().gcs.call("get_nodes")


def cluster_resources():
    return _require_worker().gcs.call("cluster_resources")


def available_resources():
    worker = _require_worker()
    if getattr(worker, "mode", None) == "client":
        return worker.available_resources()
    from ray_tpu._private.protocol import RpcClient

    total = {}
    for n in worker.gcs.call("get_nodes"):
        if not n["Alive"]:
            continue
        try:
            c = RpcClient((n["NodeManagerAddress"], n["NodeManagerPort"]),
                          timeout=5.0)
            try:
                info = c.call("node_info")
            finally:
                c.close()
            for k, v in info["resources_available"].items():
                total[k] = total.get(k, 0) + v
        except Exception:
            continue
    return total


def get_gpu_ids():
    return []   # compatibility shim; TPU chips are addressed via jax.devices


def timeline(filename=None):
    """Cluster-wide task/actor execution spans in chrome://tracing format
    (reference: `ray timeline`, scripts.py:1757 over core-worker profiling
    events). Open the written file at chrome://tracing or Perfetto."""
    from ray_tpu._private import profiling
    from ray_tpu.experimental.state.api import _each_raylet

    worker = _require_worker()
    if getattr(worker, "mode", None) == "client":
        trace = worker._rpc.call("client_timeline")
    else:
        # drop markers ride along (ph "M" metadata rows): a ring that
        # evicted spans must say so in the merged timeline
        events = profiling.snapshot(with_drop_marker=True)  # driver
        events.extend(_each_raylet(worker.gcs.call, "profile_events"))
        trace = profiling.to_chrome_trace(events)
    if filename:
        import json

        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace


class RayContext:
    def __init__(self, worker):
        self._worker = worker
        self.address_info = {
            "gcs_address": f"{worker.gcs.addr[0]}:{worker.gcs.addr[1]}",
            "node_id": worker.node_id,
        }

    def __enter__(self):
        return self

    def __exit__(self, *a):
        shutdown()

    def __getitem__(self, key):
        return self.address_info[key]


class RuntimeContext:
    def __init__(self, worker: CoreWorker):
        self._worker = worker

    def get_node_id(self):
        return self._worker.node_id

    def get_job_id(self):
        return self._worker.job_id

    def get_worker_id(self):
        return self._worker.worker_id

    def get_actor_id(self):
        return self._worker.actor_id.hex() if self._worker.actor_id else None

    @property
    def namespace(self):
        return _namespace

    @property
    def was_current_actor_restarted(self):
        return False

    def get_actor_name(self):
        spec = self._worker._actor_spec
        return spec.get("name") if spec else None


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(_require_worker())


# ----------------------------------------------------------- options handling

_TASK_DEFAULTS = dict(num_cpus=1.0, num_tpus=0.0, memory=None, resources=None,
                      num_returns=1, max_retries=3, retry_exceptions=False,
                      scheduling_strategy=None, runtime_env=None,
                      # Opt-in: execute on the worker's transport pump
                      # instead of the main-thread loop — skips a queue
                      # handoff + thread wake per task. ONLY for tasks that
                      # never block (no nested get()/wait(), no runtime
                      # envs) and import no thread-hostile native libs
                      # (pyarrow). Reference analog: direct-call execution
                      # without an executor hop.
                      inline_exec=False)
_ACTOR_DEFAULTS = dict(num_cpus=1.0, num_tpus=0.0, memory=None, resources=None,
                       max_restarts=0, max_task_retries=0, max_concurrency=1,
                       concurrency_groups=None, name=None, namespace=None,
                       lifetime=None, get_if_exists=False,
                       scheduling_strategy=None, runtime_env=None)


def _build_resources(opts: dict) -> dict:
    """Pure: never mutates opts. Zero-valued entries are dropped, so
    num_cpus=0 yields {} — which the submit path must treat as 'no resource
    requirement', NOT as 'use defaults'."""
    res = dict(opts.get("resources") or {})
    if opts.get("num_cpus") is not None:
        res["CPU"] = float(opts["num_cpus"])
    if opts.get("num_gpus"):   # compat alias
        res["TPU"] = float(opts["num_gpus"])
    if opts.get("num_tpus"):
        res["TPU"] = float(opts["num_tpus"])
    if opts.get("memory"):
        res["memory"] = float(opts["memory"])
    return {k: v for k, v in res.items() if v}


def _build_strategy(opts: dict) -> dict | None:
    strategy = opts.get("scheduling_strategy")
    if strategy is None or strategy == "DEFAULT":
        pg = opts.get("placement_group")
        if pg is not None:
            return {"placement_group_id": pg.id,
                    "bundle_index":
                        opts.get("placement_group_bundle_index", -1)}
        return None
    if strategy == "SPREAD":
        return {"spread": True}
    # strategy objects (duck-typed; see ray_tpu.util.scheduling_strategies)
    if hasattr(strategy, "node_id"):
        return {"node_id": strategy.node_id,
                "soft": getattr(strategy, "soft", False)}
    if hasattr(strategy, "placement_group"):
        pg = strategy.placement_group
        return {"placement_group_id": pg.id,
                "bundle_index":
                    getattr(strategy, "placement_group_bundle_index", -1)}
    raise ValueError(f"unknown scheduling strategy {strategy!r}")


class RemoteFunction:
    """@ray_tpu.remote function wrapper (reference: remote_function.py:35)."""

    def __init__(self, fn, **options):
        self._fn = fn
        self._options = {**_TASK_DEFAULTS, **options}
        self._func_hash = None
        self._registered_with = None   # CoreWorker the hash was pushed via
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._fn.__name__}() cannot be called "
            f"directly; use {self._fn.__name__}.remote()")

    def options(self, **overrides):
        return RemoteFunction(self._fn, **{**self._options, **overrides})

    def remote(self, *args, **kwargs):
        worker = _require_worker()
        if self._registered_with is not worker:
            # (re-)register against THIS runtime: a new init() means a fresh
            # GCS function table that has no copy of the function
            self._func_hash = worker.register_function(self._fn)
            self._registered_with = worker
        opts = self._options
        refs = worker.submit_task(
            self._func_hash, args, kwargs,
            num_returns=opts["num_returns"],
            resources=_build_resources(opts),
            strategy=_build_strategy(opts),
            max_retries=opts["max_retries"],
            runtime_env=opts.get("runtime_env"),
            task_desc=f"task {self._fn.__name__}()",
            inline_exec=bool(opts.get("inline_exec")),
        )
        if opts["num_returns"] == "streaming":
            return ObjectRefGenerator(refs[0].id, refs[0].owner_addr,
                                      None, worker)
        if opts["num_returns"] in (1, "dynamic"):
            return refs[0]
        return refs

    @property
    def bind(self):
        from ray_tpu.dag import FunctionNode

        def _bind(*args, **kwargs):
            return FunctionNode(self, args, kwargs)

        return _bind


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns=1):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def options(self, num_returns=1, **_):
        return ActorMethod(self._handle, self._name, num_returns)

    def remote(self, *args, **kwargs):
        worker = _require_worker()
        refs = worker.submit_actor_task(
            self._handle._actor_id, self._name, args, kwargs,
            num_returns=self._num_returns,
            max_task_retries=self._handle._max_task_retries,
            task_desc=f"actor method {self._name}()",
        )
        if self._num_returns == "streaming":
            return ObjectRefGenerator(refs[0].id, refs[0].owner_addr,
                                      None, worker)
        if self._num_returns in (1, "dynamic"):
            return refs[0]
        return refs

    @property
    def bind(self):
        from ray_tpu.dag import ClassMethodNode

        def _bind(*args, **kwargs):
            return ClassMethodNode(self, args, kwargs)

        return _bind


class ActorHandle:
    def __init__(self, actor_id: bytes, max_task_retries: int = 0):
        self._actor_id = actor_id
        self._max_task_retries = max_task_retries

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._max_task_retries))

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return (isinstance(other, ActorHandle)
                and other._actor_id == self._actor_id)

    @property
    def __ray_terminate__(self):
        return ActorMethod(self, "__ray_terminate__")


class ActorClass:
    """@ray_tpu.remote class wrapper (reference: actor.py:377)."""

    def __init__(self, cls, **options):
        self._cls = cls
        self._options = {**_ACTOR_DEFAULTS, **options}
        self._class_hash = None
        self._registered_with = None

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._cls.__name__} cannot be instantiated "
            f"directly; use {self._cls.__name__}.remote()")

    def options(self, **overrides):
        out = ActorClass(self._cls, **{**self._options, **overrides})
        return out

    def remote(self, *args, **kwargs):
        worker = _require_worker()
        if self._registered_with is not worker:
            self._class_hash = worker.register_function(self._cls)
            self._registered_with = worker
        opts = dict(self._options)
        _validate_concurrency_groups(self._cls, opts["concurrency_groups"])
        resources = _build_resources(opts)   # {} = explicit zero request
        actor_id, existed = worker.create_actor(
            self._class_hash, args, kwargs,
            options={
                "class_name": self._cls.__name__,
                "resources": resources,
                "strategy": _build_strategy(opts),
                "max_restarts": opts["max_restarts"],
                "max_task_retries": opts["max_task_retries"],
                "max_concurrency": opts["max_concurrency"],
                "concurrency_groups": opts["concurrency_groups"],
                "name": opts["name"],
                "namespace": opts["namespace"] or _namespace,
                "lifetime": opts["lifetime"],
                "get_if_exists": opts["get_if_exists"],
                "runtime_env": opts.get("runtime_env"),
            })
        return ActorHandle(actor_id,
                           max_task_retries=opts["max_task_retries"])

    @property
    def bind(self):
        from ray_tpu.dag import ClassNode

        def _bind(*args, **kwargs):
            return ClassNode(self, args, kwargs)

        return _bind


def _validate_concurrency_groups(cls, groups):
    """Reject a @method(concurrency_group=...) naming an undeclared group at
    actor-creation time (reference: actor.py validates at definition time).
    Catching it here — not at dispatch — keeps a misspelled group from
    failing mid-stream after earlier calls already ran."""
    declared = set(groups or {})
    for attr_name in dir(cls):
        attr = inspect.getattr_static(cls, attr_name, None)
        group = getattr(attr, "__ray_concurrency_group__", None)
        if group is not None and group not in declared:
            raise ValueError(
                f"method {cls.__name__}.{attr_name!r} declares concurrency "
                f"group {group!r}, but the actor is being created with "
                f"groups {sorted(declared)}")


def remote(*args, **kwargs):
    """@ray_tpu.remote / @ray_tpu.remote(num_cpus=..., num_tpus=...)."""
    if len(args) == 1 and not kwargs and (
            inspect.isfunction(args[0]) or inspect.isclass(args[0])):
        target = args[0]
        if inspect.isclass(target):
            return ActorClass(target)
        return RemoteFunction(target)
    if args:
        raise TypeError("@remote takes keyword options only")

    def decorator(target):
        if inspect.isclass(target):
            return ActorClass(target, **kwargs)
        return RemoteFunction(target, **kwargs)

    return decorator


def method(**opts):
    """@ray_tpu.method(num_returns=..., concurrency_group=...) decorator
    for actor methods (reference: actor.py method + concurrency groups,
    transport/concurrency_group_manager.h)."""

    def decorator(fn):
        fn.__ray_num_returns__ = opts.get("num_returns", 1)
        if "concurrency_group" in opts:
            fn.__ray_concurrency_group__ = opts["concurrency_group"]
        return fn

    return decorator
