"""Memory anatomy — store-side provenance ledger + leak attribution.

Every plane composes under adversity, but until this module nobody
could say *where the bytes live*: the shm store serves collective
segments, serve weights, data-staging blocks, and task args with zero
per-owner accounting, and the fire-and-forget free pipeline
(owner → GCS → raylet, all one-way pushes) loses deletes silently.
This module gives every store object a provenance record and every
lost free a counter:

- **Ledger** (one per process, ``LEDGER``): every ``put`` /
  ``put_ephemeral`` / pin / ``delete`` on ``StoreClient`` stamps a
  :class:`Record` — creator (node, pid, task/actor id), category
  (``task_arg | task_return | collective_segment | serve_weights |
  data_staging | checkpoint | other``), owning group/consumer tag, and
  byte size — into a live-object index plus a bounded ring of recent
  ops (the flight recorder's ``memory.jsonl`` window).
- **Category attribution**: call sites that know what they are putting
  wrap the store op in :func:`tagged` (collective ``_push_seg``, serve
  ``_publish_or_adopt``, data ``_stage``, the worker's task-arg /
  task-return paths); objects that arrive untagged fall back to the
  oid-layout classifier (``\\xc0…`` = collective segment, ``dstrm…`` =
  data staging — the layouts host_backend / the streaming executor
  mint).
- **Leak sweep** (:meth:`Ledger.sweep`): reconciles the ledger against
  the store server's actual live set (``list_objects`` — deletes by
  OTHER processes prune records here) and classifies each survivor as
  referenced vs **orphaned**: creator process dead, collective group
  destroyed, or group epoch stale. Orphans emit one ``STORE_LEAK``
  event each (once per object, with the full provenance record in the
  payload) and the ``ray_tpu_store_orphan_bytes`` gauge.
- **Dropped frees**: the three one-way hops of the free pipeline count
  their losses here (``note_free_dropped`` →
  ``ray_tpu_store_frees_dropped_total{stage=owner_push | gcs_fanout |
  raylet_delete | ephemeral_pinned}``) — the
  ``test_shm_segment_transport_oracle`` flake's smoking gun, finally on
  a counter (its root cause, a forwarding hop's pin racing the last
  consumer's delete, is fixed in host_backend._forward; the counter
  remains the tripwire for any recurrence).
- **Train-state accounting**: ``make_train_state`` /
  ``sync_gradients`` report exact per-rank byte sums from the
  deterministic flatten (``ray_tpu_train_state_bytes{kind, rank}``) —
  the gauge the ZeRO arc will diff before/after sharding.

Kill switch: everything here guards on ``telemetry.ENABLED``
(``RAY_TPU_INTERNAL_TELEMETRY=0``) and is a no-op when disabled. Hooks
never raise: accounting must not be able to fail a put. The hot-path
cost is one thread-local read + two dict updates per op (the overhead
guard in tests/test_zz_memory_anatomy.py pins it <5% of a store
round-trip).
"""
from __future__ import annotations

import os
import threading
import time

from ray_tpu._private import telemetry as _tm

CATEGORIES = ("task_arg", "task_return", "collective_segment",
              "serve_weights", "data_staging", "checkpoint", "other")

# oid-layout fallbacks (for objects put by an untagged/foreign path):
# host_backend mints collective segment ids as
# col_oid_prefix(group)=b"\xc0"+blake2b(name)[:5], the streaming
# executor stages under b"dstrm"+urandom. Serve weights and task ids
# are opaque (sha256 / urandom) — those rely on call-site tags.
_COL_PREFIX = b"\xc0"
_DATA_PREFIX = b"dstrm"

_tls = threading.local()


class tagged:
    """Context manager a call site wraps around its store ops so the
    ledger records *what* the bytes are, not just that they exist::

        with memory_anatomy.tagged("collective_segment", group=name,
                                   epoch=epoch, rank=rank):
            store.put_ephemeral(oid, parts)

    Plain-class (not ``@contextmanager``) to keep the hot path one
    attribute write each way. Nests; inner tag wins."""

    __slots__ = ("_tag", "_prev")

    def __init__(self, category: str, **prov):
        self._tag = (category, prov)
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_tls, "tag", None)
        _tls.tag = self._tag
        return self

    def __exit__(self, *exc):
        _tls.tag = self._prev
        return False


class default_tag(tagged):
    """``tagged`` that YIELDS to an already-active tag: the worker's
    task-arg/task-return paths use it so an outer caller-provided
    category (e.g. ``checkpoint``) survives the inner store op."""

    __slots__ = ()

    def __enter__(self):
        self._prev = getattr(_tls, "tag", None)
        if self._prev is None:
            _tls.tag = self._tag
        return self


def current_tag():
    return getattr(_tls, "tag", None)


def classify_oid(oid: bytes) -> str:
    """Category from the oid layout alone (the untagged fallback)."""
    if oid[:1] == _COL_PREFIX:
        return "collective_segment"
    if oid.startswith(_DATA_PREFIX):
        return "data_staging"
    return "other"


def parse_col_oid(oid: bytes) -> tuple:
    """(group_hash_hex, epoch, rank) from a collective-segment oid —
    the 16-byte layout host_backend mints is tag(6) + epoch(4) +
    rank(2) + counter(4), so provenance survives even without a ledger
    record (e.g. the putter was another process)."""
    if len(oid) != 16 or oid[:1] != _COL_PREFIX:
        return (None, None, None)
    return (oid[:6].hex(), int.from_bytes(oid[6:10], "big"),
            int.from_bytes(oid[10:12], "big"))


class Record:
    """Provenance of one live store object, as stamped at put time."""

    __slots__ = ("oid", "category", "nbytes", "node", "pid", "owner",
                 "group", "epoch", "rank", "created", "pins")

    def __init__(self, oid, category, nbytes, node, pid, owner,
                 group, epoch, rank, created):
        self.oid = oid
        self.category = category
        self.nbytes = nbytes
        self.node = node
        self.pid = pid
        self.owner = owner          # task/actor/consumer tag (or None)
        self.group = group          # collective group / serve key / stage
        self.epoch = epoch
        self.rank = rank
        self.created = created
        self.pins = 0

    def to_dict(self) -> dict:
        return {"oid": self.oid.hex(), "category": self.category,
                "nbytes": self.nbytes, "node": self.node,
                "pid": self.pid, "owner": self.owner,
                "group": self.group, "epoch": self.epoch,
                "rank": self.rank, "created": self.created,
                "pins": self.pins}


class Ledger:
    """Per-process provenance ledger over this process's StoreClient
    traffic: live index + bounded op ring + dropped-free counters +
    train-state byte accounting. Thread-safe; every public method is
    exception-free by construction (accounting never fails a put)."""

    def __init__(self, ring_size: int | None = None):
        self._lock = threading.Lock()
        self._live: dict[bytes, Record] = {}
        self._ring: list = []            # bounded [(ts, op, seq, Record)]
        self._ring_size = ring_size      # None: config memory_ring_size,
        #                                  resolved on first push
        self._ring_seq = 0
        self._cat_bytes: dict[str, int] = {}
        self._cat_objects: dict[str, int] = {}
        self._dropped_frees: dict[str, int] = {}
        self._train_state: dict[tuple, int] = {}   # (kind, rank) -> bytes
        self._inflight: dict[str, int] = {}        # rank -> bucket bytes
        self._leaked: set[bytes] = set()           # STORE_LEAK emitted
        self._orphans: list[dict] = []             # last sweep's verdicts
        self._last_sweep = 0.0
        # store objects with no ledger record in THIS process (put by
        # another — possibly dead — process): classified by oid layout,
        # aged from first sighting. Kept out of the category gauges (a
        # node's N processes would each re-count the same bytes) — they
        # exist purely so a SURVIVOR's sweep can name a dead putter's
        # stranded segments.
        self._foreign: dict[bytes, Record] = {}

    # ------------------------------------------------------------- hooks
    # Called from StoreClient under telemetry.ENABLED only. The lock is
    # held for dict ops only; category gauges flush lazily at
    # snapshot/sweep time (see _account).

    def note_put(self, oid: bytes, nbytes: int, *, node=None, pid=None,
                 ephemeral: bool = False):
        try:
            now = time.time()
            tag = getattr(_tls, "tag", None)
            if tag is not None:
                category, prov = tag
                owner = prov.get("owner")
                group = prov.get("group")
                epoch = prov.get("epoch")
                rank = prov.get("rank")
            else:
                category = classify_oid(oid)
                owner = group = epoch = rank = None
            if category == "collective_segment" and group is None:
                _, epoch, rank = parse_col_oid(oid)
            rec = Record(oid, category, int(nbytes), node,
                         pid if pid is not None else os.getpid(),
                         owner, group, epoch, rank, now)
            op = "put_ephemeral" if ephemeral else "put"
            with self._lock:
                prev = self._live.get(oid)
                if prev is not None:      # overwrite (put_ephemeral
                    self._account(prev, -1)  # EXISTS-recreate path)
                self._live[oid] = rec
                self._account(rec, +1)
                self._ring_push(op, rec, now)
        except Exception:
            pass

    def note_delete(self, oid: bytes):
        try:
            with self._lock:
                self._foreign.pop(oid, None)
                self._leaked.discard(oid)
                rec = self._live.pop(oid, None)
                if rec is None:
                    return
                self._account(rec, -1)
                self._ring_push("delete", rec, time.time())
        except Exception:
            pass

    def note_pin(self, oid: bytes):
        try:
            with self._lock:
                rec = self._live.get(oid) or self._foreign.get(oid)
                if rec is not None:
                    rec.pins += 1
        except Exception:
            pass

    def note_unpin(self, oid: bytes):
        try:
            with self._lock:
                rec = self._live.get(oid) or self._foreign.get(oid)
                if rec is not None and rec.pins > 0:
                    rec.pins -= 1
        except Exception:
            pass

    def note_free_dropped(self, stage: str, count: int = 1):
        """One lost delete on the one-way free pipeline
        (stage=owner_push|gcs_fanout|raylet_delete|ephemeral_pinned)."""
        try:
            with self._lock:
                self._dropped_frees[stage] = \
                    self._dropped_frees.get(stage, 0) + count
            if _tm.ENABLED:
                _tm.counter_inc("ray_tpu_store_frees_dropped_total",
                                float(count), tags={"stage": stage})
        except Exception:
            pass

    def note_train_state(self, kind: str, rank, nbytes: int):
        """Exact per-rank train-state bytes from the deterministic
        flatten (kind=params|grads|opt_state|bucket_inflight)."""
        try:
            with self._lock:
                self._train_state[(kind, str(rank))] = int(nbytes)
            if _tm.ENABLED:
                _tm.gauge_set("ray_tpu_train_state_bytes", float(nbytes),
                              tags={"kind": kind, "rank": str(rank)})
        except Exception:
            pass

    def add_inflight(self, rank, delta: int):
        """Bucket bytes currently on the wire (launched, not yet
        harvested) — incremented at allreduce launch, decremented at
        ``PendingGradSync.result``."""
        try:
            rank = str(rank)
            with self._lock:
                cur = max(0, self._inflight.get(rank, 0) + int(delta))
                self._inflight[rank] = cur
            self.note_train_state("bucket_inflight", rank, cur)
        except Exception:
            pass

    # ------------------------------------------------------- internals

    def _account(self, rec: Record, sign: int):
        # lock held. Dict math only: the category GAUGES flush lazily in
        # _flush_gauges (snapshot / sweep time, i.e. at worst one
        # memory_sweep_interval_s stale on a scrape) — two gauge_set
        # calls per store op would be ~half the put/get hot-path budget
        # the overhead guard pins.
        c = rec.category
        b = self._cat_bytes
        b[c] = b.get(c, 0) + sign * rec.nbytes
        o = self._cat_objects
        o[c] = o.get(c, 0) + sign

    def _ring_push(self, op: str, rec: Record, now: float):
        # lock held. The ring holds (ts, op, seq, Record) tuples —
        # materializing the row dict here would double the hot-path
        # cost; snapshot() renders them on read.
        if self._ring_size is None:
            self._ring_size = int(
                _get_config_float("memory_ring_size", 2048.0))
        self._ring_seq += 1
        self._ring.append((now, op, self._ring_seq, rec))
        if len(self._ring) > self._ring_size:
            del self._ring[:len(self._ring) - self._ring_size]

    def _flush_gauges(self):
        # lock held
        if not _tm.ENABLED:
            return
        for c, n in self._cat_bytes.items():
            _tm.gauge_set("ray_tpu_store_bytes", float(max(0, n)),
                          tags={"category": c, "state": "live"})
        for c, n in self._cat_objects.items():
            _tm.gauge_set("ray_tpu_store_objects", float(max(0, n)),
                          tags={"category": c})

    # ----------------------------------------------------------- sweep

    def sweep(self, store=None, *, known_groups: dict | None = None,
              poisoned: dict | None = None,
              grace_s: float | None = None) -> list[dict]:
        """Reconcile against the store's actual live set and classify
        every surviving object as referenced vs orphaned. Returns the
        orphan list (dict rows with a ``reason``); each NEW orphan oid
        additionally emits one ``STORE_LEAK`` event with the full
        provenance record.

        ``known_groups`` maps live collective group name → epoch (the
        worker runtime's ``_col_epochs``); when provided, collective
        segments for a destroyed group / stale epoch classify as
        orphaned even while their creator lives.
        ``poisoned`` maps poisoned group name → dead-ranks tuple (the
        worker's ``_col_poison``): a segment of a poisoned gang put by a
        DEAD rank classifies ``owner_dead`` even though the sweeper
        never saw the put (cross-process: the creator's ledger died with
        it; the oid itself carries the rank).
        ``grace_s`` (config ``memory_sweep_grace_s``) spares
        just-created objects — an in-flight segment between put and
        consume is referenced, not leaked."""
        if grace_s is None:
            grace_s = _get_config_float("memory_sweep_grace_s", 5.0)
        now = time.time()
        listed = None
        if store is not None:
            try:
                listed = dict(store.list_objects())
            except Exception:
                listed = None
        col_prefixes = {}
        if known_groups:
            for g, ep in known_groups.items():
                col_prefixes[_col_prefix(g)] = (g, ep)
        poison_prefixes = {}
        if poisoned:
            for g, dead_ranks in poisoned.items():
                poison_prefixes[_col_prefix(g)] = (g, tuple(dead_ranks))
        orphans: list[dict] = []
        new_leaks: list[tuple] = []
        with self._lock:
            if listed is not None:
                # deletes by other processes land here: prune records
                # the store no longer holds
                for oid in [o for o in self._live if o not in listed]:
                    rec = self._live.pop(oid)
                    self._account(rec, -1)
                    self._leaked.discard(oid)
                for oid in [o for o in self._foreign if o not in listed]:
                    del self._foreign[oid]
                    self._leaked.discard(oid)
                for oid, nbytes in listed.items():
                    if oid in self._live or oid in self._foreign:
                        continue
                    _, ep, rk = parse_col_oid(oid)
                    self._foreign[oid] = Record(
                        oid, classify_oid(oid), int(nbytes), None, None,
                        None, None, ep, rk, now)
            for oid, rec in list(self._live.items()) \
                    + list(self._foreign.items()):
                reason = self._classify(rec, now, grace_s, col_prefixes,
                                        poison_prefixes,
                                        known_groups is not None)
                if reason is None:
                    continue
                row = rec.to_dict()
                row["reason"] = reason
                hit = col_prefixes.get(oid[:6]) \
                    or poison_prefixes.get(oid[:6])
                if row["group"] is None and hit is not None:
                    row["group"] = hit[0]   # name the group even when
                    #                         the putter was untagged
                php = poison_prefixes.get(oid[:6])
                if php is not None:
                    row["dead_ranks"] = list(php[1])
                orphans.append(row)
                if oid not in self._leaked:
                    self._leaked.add(oid)
                    new_leaks.append(row)
            self._orphans = orphans
            self._last_sweep = now
            self._flush_gauges()
            by_cat: dict[tuple, int] = {}
            for row in orphans:
                key = (row["category"], row["reason"])
                by_cat[key] = by_cat.get(key, 0) + row["nbytes"]
        if _tm.ENABLED:
            total = 0
            for (cat, reason), nbytes in by_cat.items():
                total += nbytes
                _tm.gauge_set("ray_tpu_store_orphan_bytes", float(nbytes),
                              tags={"category": cat, "reason": reason})
            _tm.gauge_set("ray_tpu_store_orphan_bytes", float(total),
                          tags={"category": "all", "reason": "all"})
            for row in new_leaks:
                _emit_store_leak(row)
        return orphans

    def _classify(self, rec: Record, now: float, grace_s: float,
                  col_prefixes: dict, poison_prefixes: dict,
                  groups_known: bool):
        # lock held. None = referenced.
        if rec.pins > 0:
            return None
        if now - rec.created < grace_s:
            return None
        if rec.pid is not None and rec.pid != os.getpid() \
                and not _pid_alive(rec.pid):
            return "owner_dead"
        if rec.category == "collective_segment":
            php = poison_prefixes.get(rec.oid[:6])
            if php is not None:
                # poisoned gang: the oid's rank field says who put it —
                # a dead rank's segment has no owner left to free it
                _group, dead_ranks = php
                _, _, oid_rank = parse_col_oid(rec.oid)
                if not dead_ranks or oid_rank is None \
                        or oid_rank in dead_ranks:
                    return "owner_dead"
                return "group_destroyed"
            if groups_known:
                hit = col_prefixes.get(rec.oid[:6])
                if hit is None:
                    return "group_destroyed"
                group, live_epoch = hit
                _, oid_epoch, _ = parse_col_oid(rec.oid)
                if oid_epoch is not None and \
                        oid_epoch != (live_epoch % (1 << 32)):
                    return "epoch_stale"
        return None

    # -------------------------------------------------------- snapshot

    def snapshot(self, *, top_k: int = 10, window_s: float | None = None,
                 ring: bool = True) -> dict:
        """One process's ledger view — the fan-out unit behind
        ``summarize_memory`` / ``/api/memory`` / the flight recorder's
        ``memory.jsonl``."""
        with self._lock:
            self._flush_gauges()
            cats = {c: {"bytes": max(0, self._cat_bytes.get(c, 0)),
                        "objects": max(0, self._cat_objects.get(c, 0))}
                    for c in set(self._cat_bytes) | set(self._cat_objects)
                    if self._cat_bytes.get(c) or self._cat_objects.get(c)}
            live = sorted(self._live.values(),
                          key=lambda r: -r.nbytes)
            top = [r.to_dict() for r in live[:top_k]]
            ring_rows = []
            if ring:
                cutoff = (time.time() - window_s) if window_s else 0.0
                ring_rows = [{"ts": ts, "op": op, "op_seq": seq,
                              **rec.to_dict()}
                             for (ts, op, seq, rec) in self._ring
                             if ts >= cutoff]
            return {
                "pid": os.getpid(),
                "categories": cats,
                "live_objects": sum(
                    max(0, n) for n in self._cat_objects.values()),
                "live_bytes": sum(
                    max(0, n) for n in self._cat_bytes.values()),
                "top_owners": top,
                "orphans": list(self._orphans),
                "dropped_frees": dict(self._dropped_frees),
                "train_state": {f"{k}:{r}": v for (k, r), v
                                in self._train_state.items()},
                "last_sweep": self._last_sweep,
                "ring": ring_rows,
            }

    def reset(self):
        """Test hook: drop all state (a fresh runtime in-process)."""
        with self._lock:
            self._live.clear()
            self._ring.clear()
            self._cat_bytes.clear()
            self._cat_objects.clear()
            self._dropped_frees.clear()
            self._train_state.clear()
            self._inflight.clear()
            self._leaked.clear()
            self._foreign.clear()
            self._orphans = []


def _emit_store_leak(row: dict):
    try:
        from ray_tpu._private import events

        payload = dict(row)
        # pid/node are reserved envelope keys in events.record (they
        # would WIN over the payload's) — carry the CREATOR's under
        # owner_* so the event names the dead owner, not the sweeper
        payload["owner_pid"] = payload.pop("pid", None)
        payload["owner_node"] = payload.pop("node", None)
        events.record("STORE_LEAK", **payload)
    except Exception:
        pass


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(int(pid), 0)
        return True
    except ProcessLookupError:
        return False
    except Exception:
        return True   # permission error etc: assume alive (same-node
        #               store means same-uid in practice)


def _col_prefix(group: str) -> bytes:
    from ray_tpu._private.worker_runtime import col_oid_prefix

    return col_oid_prefix(group)


def _get_config_float(name: str, default: float) -> float:
    try:
        from ray_tpu._private.config import get_config

        return float(get_config(name))
    except Exception:
        return default


# The process singleton every hook writes to. Import the MODULE and use
# `memory_anatomy.LEDGER` (tests monkeypatch it for isolation).
LEDGER = Ledger()


def sweep_local(worker=None) -> list[dict]:
    """Sweep this process's ledger against its worker's store + live
    collective-group registry (the per-process unit the periodic sweep
    and the snapshot RPC both call)."""
    if worker is None:
        try:
            from ray_tpu._private.worker_runtime import current_worker

            worker = current_worker()
        except Exception:
            worker = None
    store = getattr(worker, "store", None) if worker is not None else None
    groups = None
    poisoned = None
    if worker is not None:
        col_epochs = getattr(worker, "_col_epochs", None)
        if col_epochs is not None:
            try:
                groups = dict(col_epochs)
            except Exception:
                groups = None
        col_poison = getattr(worker, "_col_poison", None)
        if col_poison is not None:
            try:
                poisoned = {g: dr for g, (dr, _reason)
                            in dict(col_poison).items()}
            except Exception:
                poisoned = None
    return LEDGER.sweep(store, known_groups=groups, poisoned=poisoned)


def local_snapshot(*, sweep: bool = True, top_k: int = 10,
                   window_s: float | None = None) -> dict:
    """Sweep-then-snapshot for RPC / flight-recorder consumption."""
    if sweep and _tm.ENABLED:
        try:
            sweep_local()
        except Exception:
            pass
    snap = LEDGER.snapshot(top_k=top_k, window_s=window_s)
    snap["enabled"] = _tm.ENABLED
    return snap


_sweep_thread = None
_sweep_stop = threading.Event()


def start_periodic_sweep(worker) -> bool:
    """Background leak sweep for a worker process (daemon thread;
    cadence = config ``memory_sweep_interval_s``, 0 disables). Idempotent
    per process; dies with it. No-op under the telemetry kill switch."""
    global _sweep_thread
    if not _tm.ENABLED:
        return False
    interval = _get_config_float("memory_sweep_interval_s", 30.0)
    if interval <= 0:
        return False
    if _sweep_thread is not None and _sweep_thread.is_alive():
        return True

    def _loop():
        while not _sweep_stop.wait(interval):
            try:
                sweep_local(worker)
            except Exception:
                pass

    _sweep_stop.clear()
    _sweep_thread = threading.Thread(target=_loop, daemon=True,
                                     name="memory-anatomy-sweep")
    _sweep_thread.start()
    return True


def stop_periodic_sweep():
    global _sweep_thread
    _sweep_stop.set()
    _sweep_thread = None
