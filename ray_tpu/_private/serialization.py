"""Value serialization for the data plane.

Equivalent of python/ray/_private/serialization.py in the reference:
cloudpickle for code/closures, pickle protocol 5 with out-of-band buffers so
large numpy/jax arrays are written into the shared-memory store without an
extra copy, and in-band ObjectRef capture (refs inside values are recorded so
the runtime can track borrows and resolve nested refs).

Wire layout of a serialized value:
    [u32 meta_len][meta pickle][buffer 0][buffer 1]...
meta = {"payload": <pickled-with-oob-markers>, "buffer_sizes": [...],
        "refs": [(id, owner_addr), ...], "raised": bool}
"raised" is True only for payloads produced by serialize_error (the task
RAISED); a task that merely *returns* an exception object has raised=False
and ray_tpu.get() returns it instead of raising (reference parity: only
RayTaskError wrappers re-raise, worker.py get path).
"""
from __future__ import annotations

import pickle
import struct

import cloudpickle

from ray_tpu._private.object_ref import ObjectRef
from ray_tpu.exceptions import RayError, RayTaskError

_U32 = struct.Struct(">I")

# Arrays below this go in-band; above, out-of-band into the store buffer.
_OOB_THRESHOLD = 8 * 1024


def dumps_function(fn) -> bytes:
    return cloudpickle.dumps(fn)


def loads_function(blob: bytes):
    return pickle.loads(blob)


class _RefPlaceholder:
    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index


def serialize(value, raised: bool = False) -> bytearray:
    """Serialize a Python value; returns the framed payload as a
    BYTEARRAY (bytes-like but unhashable/mutable — a bytes() of it would
    be a second full copy of every out-of-band buffer). raised=True marks
    the payload as a shipped task failure (set by serialize_error only)."""
    return assemble_parts(serialize_parts(value, raised))


def serialize_parts(value, raised: bool = False) -> list:
    """The frame as a PARTS LIST [header+meta, oob_buffer, ...], NOT
    assembled: callers that stream (shm segment copy, spill-file write)
    skip a full copy of every out-of-band buffer — gigabytes for big
    arrays. Writing the parts sequentially reproduces serialize()
    byte-for-byte."""
    buffers: list = []
    refs: list = []
    ref_index: dict[bytes, int] = {}

    def buffer_callback(buf: pickle.PickleBuffer):
        raw = buf.raw()
        if raw.nbytes < _OOB_THRESHOLD:
            return True  # keep small buffers in-band
        buffers.append(raw)
        return False

    def persistent_ref(obj):
        if isinstance(obj, ObjectRef):
            idx = ref_index.get(obj.id)
            if idx is None:
                idx = len(refs)
                ref_index[obj.id] = idx
                refs.append((obj.id, obj.owner_addr))
            return _RefPlaceholder(idx)
        return obj

    marked = _map_matching(value, ObjectRef, persistent_ref)
    try:
        if _is_plain(marked):
            # builtins/numpy-only tree: the C pickler is ~10x cloudpickle
            # for small frames (the sync-task hot path). NOT a blind
            # pickle-first fallback: plain pickle would serialize
            # __main__-defined classes BY REFERENCE and the worker can't
            # import __main__ — the type scan admits only trees where
            # both picklers agree byte-semantically.
            payload = pickle.dumps(
                marked,
                protocol=pickle.HIGHEST_PROTOCOL,
                buffer_callback=buffer_callback,
            )
        else:
            payload = cloudpickle.dumps(
                marked,
                protocol=pickle.HIGHEST_PROTOCOL,
                buffer_callback=buffer_callback,
            )
    except Exception:
        # Fall back without oob buffers (some objects misbehave under
        # buffer_callback); correctness over zero-copy.
        buffers = []
        payload = cloudpickle.dumps(marked)

    meta = pickle.dumps(
        {
            "payload": payload,
            "buffer_sizes": [b.nbytes for b in buffers],
            "refs": refs,
            "raised": raised,
        },
        protocol=pickle.HIGHEST_PROTOCOL,
    )
    header = bytearray()
    header += _U32.pack(len(meta))
    header += meta
    return [header, *buffers]


_PLAIN_TYPES = frozenset({int, float, bool, bytes, str, type(None),
                          _RefPlaceholder})


def _is_plain(v, depth: int = 0) -> bool:
    """True iff pickle and cloudpickle agree on this tree: builtins,
    numpy arrays/scalars, and plain containers only — nothing pickled
    by reference to a module the executor may lack, nothing cloudpickle
    would ship by value."""
    t = type(v)
    if t in _PLAIN_TYPES:
        return True
    if depth >= 6:
        return False
    if t is list or t is tuple:
        return all(_is_plain(x, depth + 1) for x in v)
    if t is dict:
        return all(type(k) in (str, int, bytes)
                   and _is_plain(x, depth + 1) for k, x in v.items())
    mod = getattr(t, "__module__", "")
    if mod == "numpy" or mod.startswith("numpy."):
        dtype = getattr(v, "dtype", None)
        # hasobject (NOT kind != 'O'): structured dtypes are kind 'V'
        # yet can embed object fields whose classes plain pickle would
        # serialize by unimportable reference
        return dtype is None or not dtype.hasobject
    return False


_EMPTY_ARGS_BLOB: bytes | None = None


def serialize_empty_args() -> bytes:
    """Cached frame for ((), {}) — the no-arg task submission's payload
    is a constant; re-pickling it per submit is hot-path waste."""
    global _EMPTY_ARGS_BLOB
    if _EMPTY_ARGS_BLOB is None:
        _EMPTY_ARGS_BLOB = bytes(serialize(((), {})))
    return _EMPTY_ARGS_BLOB


_NONE_BLOB: bytes | None = None


def serialize_none() -> bytes:
    """Cached frame for None — the overwhelmingly common task result on
    control-plane-bound workloads."""
    global _NONE_BLOB
    if _NONE_BLOB is None:
        _NONE_BLOB = bytes(serialize(None))
    return _NONE_BLOB


def assemble_parts(parts: list) -> bytearray:
    """Concatenate a serialize_parts frame (for consumers that need one
    contiguous payload, e.g. inline task-reply results)."""
    out = bytearray(parts[0])
    for b in parts[1:]:
        out += b
    return out


def parts_size(parts: list) -> int:
    return sum(memoryview(p).nbytes for p in parts)


def contained_refs(value) -> list[ObjectRef]:
    """Collect ObjectRefs reachable from value (top-level containers only —
    same scope the reference inlines through, not a full graph walk; deeply
    nested refs inside arbitrary objects are found at pickle time instead)."""
    found: list[ObjectRef] = []

    def visit(obj, depth=0):
        if isinstance(obj, ObjectRef):
            found.append(obj)
        elif depth < 4:
            if isinstance(obj, (list, tuple, set)):
                for item in obj:
                    visit(item, depth + 1)
            elif isinstance(obj, dict):
                for item in obj.values():
                    visit(item, depth + 1)

    visit(value)
    return found


def _map_matching(value, kind, fn, depth=0):
    """Map fn over instances of `kind` found in plain containers (refs nested
    deeper inside arbitrary objects are caught by ObjectRef.__reduce__, which
    re-binds on load but loses borrow tracking — acceptable v1)."""
    if isinstance(value, kind):
        return fn(value)
    if depth >= 8:
        return value
    if isinstance(value, list):
        return [_map_matching(v, kind, fn, depth + 1) for v in value]
    if isinstance(value, tuple) and type(value) is tuple:
        return tuple(_map_matching(v, kind, fn, depth + 1) for v in value)
    if isinstance(value, dict) and type(value) is dict:
        return {k: _map_matching(v, kind, fn, depth + 1)
                for k, v in value.items()}
    return value


def deserialize(data, worker=None, with_meta: bool = False):
    """Inverse of serialize. `data` may be bytes or memoryview (zero-copy from
    the shm store). If the value is a shipped exception it is returned (not
    raised) — callers decide via meta["raised"] (with_meta=True returns
    (value, meta))."""
    view = memoryview(data)
    (meta_len,) = _U32.unpack(view[:4])
    meta = pickle.loads(view[4:4 + meta_len])
    offset = 4 + meta_len
    buffers = []
    for size in meta["buffer_sizes"]:
        buffers.append(view[offset:offset + size])
        offset += size

    refs = [
        ObjectRef(rid, owner, worker)
        for rid, owner in meta["refs"]
    ]

    value = pickle.loads(meta["payload"], buffers=buffers)
    value = _map_matching(value, _RefPlaceholder, lambda ph: refs[ph.index])
    if with_meta:
        return value, meta
    return value


def serialize_error(exc: BaseException, task_desc: str = "") -> bytearray:
    """Ship an exception; always picklable (falls back to a stringly copy)."""
    wrapped = exc if isinstance(exc, RayError) else RayTaskError(
        type(exc).__name__, _format_tb(exc), cause=exc, task_desc=task_desc)
    try:
        return serialize(wrapped, raised=True)
    except Exception:
        return serialize(
            RayTaskError(type(exc).__name__, _format_tb(exc),
                         cause=None, task_desc=task_desc),
            raised=True)


def _format_tb(exc: BaseException) -> str:
    import traceback

    return "".join(
        traceback.format_exception(type(exc), exc, exc.__traceback__))


def value_nbytes(data) -> int:
    return memoryview(data).nbytes
