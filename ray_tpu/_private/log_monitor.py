"""Worker log capture and streaming to the driver.

Reference: python/ray/_private/log_monitor.py (a per-node process tails
the session's worker log files and publishes batches over GCS pubsub)
and python/ray/_private/worker.py:1733 print_worker_logs (the driver
subscribes and prints each batch prefixed with the producing worker's
identity). Here the monitor is a raylet-owned thread instead of a
separate process — same tail→batch→publish pipeline, one fewer process
per node — and the transport is the existing long-poll pubsub
(_private/pubsub.py) instead of Redis/GCS channels.

Message shape on channel ``worker_logs``::

    {"node_id": str, "worker_id": str, "pid": int, "actor_name": str|None,
     "stream": "out"|"err", "lines": [str, ...]}

Consecutive duplicate lines are collapsed monitor-side into one line
with a ``[repeated N times]`` suffix (the dedup the reference applies in
its log deduplicator) so a worker spinning on one print cannot flood the
driver console.

Design delta vs the reference: batches are NOT job-scoped. Workers here
are shared across jobs (the reference dedicates workers per job, so a
log file maps 1:1 to a job), which makes byte-stream attribution
ambiguous; every connected driver therefore sees every worker's output.
Right for the single-tenant clusters this targets; multi-tenant scoping
needs per-job worker pools first. Suppress with log_to_driver=False or
RAY_TPU_QUIET=1.
"""
from __future__ import annotations

import os
import sys
import threading
import time

MAX_LINES_PER_BATCH = 500        # flood guard per worker per tick
_MAX_PARTIAL = 64 * 1024         # cap an unterminated line's buffer


class _Tail:
    def __init__(self, worker_id: str, pid: int, path: str, stream: str):
        self.worker_id = worker_id
        self.pid = pid
        self.path = path
        self.stream = stream          # "out" | "err"
        self.pos = 0
        self.partial = ""             # bytes after the last newline
        self.dead = False             # drain once more, then drop
        self.actor_name = None


class LogMonitor:
    """Tails registered worker log files; publishes new lines in batches.

    ``publish(channel, message)`` is the transport (the raylet passes a
    GCS-pubsub push). Files are read incrementally by byte offset, so a
    tick costs one stat+read per active file.
    """

    def __init__(self, publish, node_id: str, interval_s: float = 0.25):
        self._publish = publish
        self.node_id = node_id
        self.interval_s = interval_s
        self._tails: dict[tuple, _Tail] = {}   # (worker_id, stream) -> tail
        self._lock = threading.Lock()
        # serializes whole ticks: stop()'s final drain would otherwise
        # race the monitor thread's in-progress tick over the same _Tail
        # (duplicated lines / torn partial buffer)
        self._tick_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def track(self, worker_id: str, pid: int, stdout_path: str,
              stderr_path: str):
        with self._lock:
            self._tails[(worker_id, "out")] = _Tail(
                worker_id, pid, stdout_path, "out")
            self._tails[(worker_id, "err")] = _Tail(
                worker_id, pid, stderr_path, "err")

    def set_actor_name(self, worker_id: str, name: str | None):
        with self._lock:
            for stream in ("out", "err"):
                t = self._tails.get((worker_id, stream))
                if t is not None:
                    t.actor_name = name

    def mark_dead(self, worker_id: str):
        """The worker exited: drain whatever it flushed, then drop."""
        with self._lock:
            for stream in ("out", "err"):
                t = self._tails.get((worker_id, stream))
                if t is not None:
                    t.dead = True

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="log-monitor")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self.tick()        # final drain so shutdown doesn't eat output

    def tick(self):
        with self._tick_lock:
            self._tick()

    def _tick(self):
        with self._lock:
            tails = list(self._tails.values())
        for t in tails:
            lines = self._read_new(t)
            if lines:
                try:
                    self._publish("worker_logs", {
                        "node_id": self.node_id, "worker_id": t.worker_id,
                        "pid": t.pid, "actor_name": t.actor_name,
                        "stream": t.stream, "lines": lines,
                    })
                except Exception:
                    pass          # pubsub down: logs stay in the files
            elif t.dead:
                with self._lock:
                    self._tails.pop((t.worker_id, t.stream), None)

    def _read_new(self, t: _Tail) -> list[str]:
        try:
            size = os.path.getsize(t.path)
        except OSError:
            return []
        if size <= t.pos:
            return []
        try:
            with open(t.path, "r", errors="replace") as f:
                f.seek(t.pos)
                chunk = f.read(size - t.pos)
                t.pos = f.tell()
        except OSError:
            return []
        text = t.partial + chunk
        lines = text.split("\n")
        t.partial = lines.pop()[-_MAX_PARTIAL:]
        if t.dead and t.partial:
            # the worker will never terminate this line; flush it
            lines.append(t.partial)
            t.partial = ""
        lines = [ln for ln in lines if ln.strip()]
        return _collapse_repeats(lines)[:MAX_LINES_PER_BATCH]

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self.tick()


def _collapse_repeats(lines: list[str]) -> list[str]:
    """Collapse runs of identical lines: a worker printing the same
    message in a tight loop becomes one line + a repeat count."""
    out: list[str] = []
    i = 0
    while i < len(lines):
        j = i
        while j < len(lines) and lines[j] == lines[i]:
            j += 1
        n = j - i
        out.append(lines[i] if n == 1
                   else f"{lines[i]} [repeated {n} times]")
        i = j
    return out


# --------------------------------------------------------------- driver side

def format_log_batch(msg: dict) -> list[str]:
    """Prefix each line with the producing worker's identity, the
    reference's ``(pid=..., ip=...)`` convention (worker.py:1733)."""
    who = f"{msg['actor_name']} " if msg.get("actor_name") else ""
    prefix = f"({who}pid={msg['pid']}, node={msg['node_id'][:8]})"
    return [f"{prefix} {line}" for line in msg["lines"]]


class DriverLogPrinter:
    """Driver-side subscriber: prints worker log batches to this
    process's stdout/stderr as they arrive."""

    def __init__(self, gcs_addr, out=None, err=None):
        # ReconnectingRpcClient, same reasoning as watch_actor_deaths
        # (PR 5 round 4): a fault-tolerant-mode GCS restart would
        # otherwise permanently and silently kill the driver's log
        # stream — the poll loop erroring forever on a dead socket. On
        # heal, the unknown-subscriber KeyError drives the Subscriber's
        # own re-announce. (Lost log lines stay lost: logs need no
        # snapshot-resync, unlike the death feed.)
        from ray_tpu._private.protocol import ReconnectingRpcClient
        from ray_tpu._private.pubsub import Subscriber

        self._rpc = ReconnectingRpcClient(tuple(gcs_addr))
        self._sub = Subscriber(self._rpc, poll_timeout=5.0)
        self._out = out or sys.stdout
        self._err = err or sys.stderr
        self._sub.subscribe("worker_logs", self._on_batch)

    def _on_batch(self, msg: dict):
        stream = self._err if msg.get("stream") == "err" else self._out
        try:
            for line in format_log_batch(msg):
                print(line, file=stream)
        except Exception:
            pass

    def stop(self):
        try:
            self._sub.stop()
        finally:
            try:
                self._rpc.close()
            except Exception:
                pass
