"""Central catalog of every ``RAY_TPU_*`` environment knob.

The runtime grew knobs in three places — explicit ``os.environ`` reads
scattered through modules, the config table (`_private/config.py`, where
every ``_CONFIG_DEFS`` key is overridable as ``RAY_TPU_<NAME>``), and
process-spawn plumbing variables the runtime sets for its own children.
Nothing tied them together: a typo'd ``getenv`` silently read nothing,
and README drifted from reality.

This module is the single source of truth. The contract (enforced by the
``knob-registry`` static-analysis pass, ``ray_tpu/_private/analysis/``):

- every explicit ``RAY_TPU_*`` environment read in ``ray_tpu/`` must name
  a knob declared in ``KNOBS`` (or a config-table-derived name) — an
  undeclared read is finding ``RTK201``;
- every cataloged knob must appear in README (finding ``RTK202``), which
  holds by construction because README's knob tables are GENERATED from
  this catalog (``readme_knob_table()``).

Declaring a knob: add a ``Knob`` entry here, regenerate the README table
(``python -m ray_tpu.scripts.cli lint --knob-table``), paste it into
README's "Static analysis" section.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Knob:
    name: str          # full env var name, RAY_TPU_*
    default: str       # default as the env layer sees it ("" = unset)
    type: str          # bool / int / float / str / path / json
    doc: str           # one line, README-ready
    internal: bool = False   # plumbing the runtime sets for its own
    #                          child processes — cataloged (so reads
    #                          lint) but listed in README's internal
    #                          table, not the user-facing one


def _k(name, default, type_, doc, internal=False):
    return Knob("RAY_TPU_" + name, default, type_, doc, internal)


# One entry per EXPLICIT env read in ray_tpu/ (config-table-derived
# RAY_TPU_<CONFIG_KEY> names are declared implicitly by _CONFIG_DEFS and
# recognized by is_declared()). Keep alphabetical within each group.
KNOBS: dict[str, Knob] = {k.name: k for k in [
    # --- kill switches / feature gates -----------------------------------
    _k("COLLECTIVE_DEATH_POISONING", "1", "bool",
       "0 disables gang poisoning on member death; detection falls back "
       "to the collective op timeout."),
    _k("COLLECTIVE_PIPELINE", "1", "bool",
       "0 restores the legacy synchronous collective ring "
       "(bit-identical kill switch for the pipelined data path)."),
    _k("COLLECTIVE_SHM", "1", "bool",
       "0 keeps same-node collective segments off the shm object store "
       "(sockets only)."),
    _k("CHECKPOINT_ASYNC", "1", "bool",
       "0 makes sharded-checkpoint shard writes fully synchronous "
       "(train.sharded_checkpoint; default runs the disk write on a "
       "background thread and commits at the caller's harvest point)."),
    _k("CHECKPOINT_FSYNC", "1", "bool",
       "0 skips the fsync-file + fsync-dir calls in the atomic-write "
       "durability idiom — TEST-ONLY kill switch; production crash "
       "consistency requires it on."),
    _k("DATA_STREAMING", "1", "bool",
       "0 restores the legacy materialize-then-iterate dataset path "
       "(bit-identical kill switch for the streaming data plane)."),
    _k("DATA_SHUFFLE_COLLECTIVE", "0", "bool",
       "1 routes random_shuffle's partition all-to-all over the "
       "pipelined host-collective plane (actor gang exchange) instead "
       "of object-store reduce tasks; identical rows per seed."),
    _k("COLLECTIVE_WIRE_DTYPE", "off", "str",
       "wire format for float32 sum ring segments: off = bit-exact "
       "(default), bf16 = 2x smaller wire, int8 = per-block-scaled "
       "~4x smaller (bounded error; see README Data plane)."),
    _k("INTERNAL_TELEMETRY", "1", "bool",
       "0 turns off the whole internal metrics + events plane."),
    _k("NATIVE_RPC", "1", "bool",
       "0 forces the pure-Python RPC transport (native C core off)."),
    _k("SERVE_SHAPE_BUCKETS", "1", "bool",
       "0 restores the pad-free legacy batcher (no bucketing, one "
       "compile per observed batch size)."),
    _k("TRAIN_BUCKET_DDP", "1", "bool",
       "0 restores the legacy single synchronous gradient allreduce in "
       "train.ddp.sync_gradients (no bucketing, no async overlap)."),
    _k("TRAIN_DEATH_MONITOR", "1", "bool",
       "0 disables the driver-side gang death monitor (rank death then "
       "surfaces via collective poison or the op timeout)."),
    _k("VALIDATE_SPECS", "1", "bool",
       "0 disables producer-side control-RPC shape validation (only for "
       "bisecting the validator itself)."),
    _k("TIMELINE", "1", "bool",
       "0 removes chrome-timeline span recording."),
    _k("DETECT_CHIPS", "0", "bool",
       "1 lets the raylet probe for real TPU chips at startup "
       "(subprocess jax.devices())."),
    # --- tuning ----------------------------------------------------------
    _k("CHECKPOINT_DIR", "", "path",
       "sharded-checkpoint generation root for standalone (non-trainer) "
       "use; trainers plumb RunConfig.storage_path instead."),
    _k("DATA_PREFETCH_BLOCKS", "4", "int",
       "streaming data plane: blocks a consumer may have buffered or "
       "in flight at once (the bounded-memory prefetch budget; "
       "producers park when the buffer is full)."),
    _k("COLLECTIVE_QUANT_BLOCK", "1024", "int",
       "elements per int8 wire-quantization scale block (one float32 "
       "scale per block; sub-block tails travel exact)."),
    _k("DEVICE_GAUGE_POLL_S", "0", "float",
       "period of the raylet's per-device HBM gauge poller; 0 = one "
       "probe at raylet start."),
    _k("EVENT_LOG_SIZE", "4096", "int",
       "bounded structured-event ring size per process (drop-oldest)."),
    _k("FLIGHT_RECORDER_WINDOW_S", "120", "float",
       "flight recorder: how far back the per-process black box reaches "
       "when a dump is cut (spans/events older than this are dropped "
       "from the dump)."),
    _k("FLIGHT_RECORDER_DIR", "", "path",
       "flight recorder: directory dump folders are written under "
       "(default <tmpdir>/ray_tpu/blackbox)."),
    _k("LEASE_SOFT_CAP", "0", "int",
       "max concurrent worker leases per node; 0 = auto (2x cluster "
       "CPUs)."),
    _k("MEMORY_RING_SIZE", "2048", "int",
       "memory anatomy: bounded provenance-op ring per process (the "
       "window the flight recorder's memory.jsonl covers)."),
    _k("MEMORY_SWEEP_GRACE_S", "5.0", "float",
       "memory anatomy: leak-sweep grace window — store objects younger "
       "than this are referenced by definition (an in-flight collective "
       "segment between put and consume must not classify as a leak)."),
    _k("MEMORY_SWEEP_INTERVAL_S", "30.0", "float",
       "memory anatomy: periodic background leak-sweep cadence per "
       "worker; 0 disables the timer (sweeps still run on demand from "
       "summarize_memory / the flight recorder)."),
    _k("STORE_FREE_RESEND", "1", "int",
       "bounded re-send of a dropped object-store free: one retry of a "
       "GCS free fan-out with no live holder connection, and of an "
       "ephemeral delete that lands while the segment is still pinned; "
       "every drop is counted either way. 0 disables the retry."),
    _k("STORE_SIZE", "268435456", "int",
       "shm object store size in bytes for a spawned node."),
    _k("TRAIN_DDP_MODE", "allreduce", "str",
       "gradient-sync shape (train.ddp): allreduce = legacy full-tree "
       "sync on every rank (bit-identical default); reducescatter = "
       "ZeRO-style sharded sync — each rank receives only its shard of "
       "every bucket (pair with ZeroOptimizer for sharded optimizer "
       "state + async param allgathers)."),
    _k("TRAIN_GRAD_BUCKET_BYTES", "4194304", "int",
       "target size of one gradient-sync bucket (train.ddp): grads are "
       "packed into buckets of about this many bytes and each bucket's "
       "allreduce is launched asynchronously as soon as it is packed."),
    # --- chaos / debugging -----------------------------------------------
    _k("FAULT_SCHEDULE", "", "str",
       "deterministic fault-injection schedule DSL; activates the "
       "injector in every process that inherits it."),
    _k("FAULT_SEED", "0", "int",
       "seed for the fault-injection schedule's probabilistic rules."),
    _k("FAULT_ROLE", "*", "str",
       "restricts which cluster role (gcs/raylet/worker/driver) the "
       "inherited schedule fires in.", internal=True),
    _k("RPC_DEBUG", "", "bool",
       "1 prints transport-level connection lifecycle diagnostics."),
    _k("WORKER_PROFILE", "", "path",
       "directory to write per-worker cProfile dumps into."),
    _k("TESTING", "", "bool",
       "set by the test harness; relaxes timing-sensitive defaults."),
    _k("TEST_FILE_BUDGET_S", "120", "float",
       "tier-1 duration guard: per-file wall-clock budget for "
       "early-alphabet test files (0 disables; see tests/conftest.py)."),
    _k("SOAK_NODES", "100", "int",
       "default fleet size for the cluster-scale soak harness "
       "(_private/sim_cluster.py / benchmarks/soak_bench.py)."),
    # --- client / logging ------------------------------------------------
    _k("ADDRESS", "", "str",
       "default cluster address for ray_tpu.init() / ray://."),
    _k("LOG_TO_DRIVER", "1", "bool",
       "0 stops streaming worker stdout/stderr to the driver."),
    _k("QUIET", "", "bool",
       "1 suppresses the init() banner and log-monitor chatter."),
    _k("WORKFLOW_STORAGE", "", "path",
       "workflow checkpoint storage root (default under the session "
       "dir)."),
    # --- process-spawn plumbing (set BY the runtime for its children) ----
    _k("GCS_ADDR", "", "str",
       "host:port of the GCS, set for spawned raylets/workers.",
       internal=True),
    _k("RAYLET_ADDR", "", "str",
       "host:port of the owning raylet, set for spawned workers.",
       internal=True),
    _k("RAYLET_PORT", "", "int",
       "port a spawned raylet should bind.", internal=True),
    _k("NODE_ID", "", "str",
       "node id a spawned process belongs to.", internal=True),
    _k("WORKER_ID", "", "str",
       "worker id assigned to a spawned worker process.", internal=True),
    _k("STORE_NAME", "", "str",
       "shm store segment name a spawned process attaches to.",
       internal=True),
    _k("SPILL_DIR", "", "path",
       "object-spill directory a spawned process uses.", internal=True),
    _k("SESSION_DIR", "", "path",
       "session directory for logs/sockets of a spawned node.",
       internal=True),
    _k("RESOURCES", "", "json",
       "JSON resource map for a spawned raylet.", internal=True),
    _k("ENV_OK", "", "str",
       "marker the runtime-env builder sets inside a prepared venv.",
       internal=True),
]}


def config_knob_names() -> set[str]:
    """``RAY_TPU_<NAME>`` for every config-table entry — declared
    implicitly by ``_CONFIG_DEFS`` (each is env-overridable)."""
    from ray_tpu._private.config import _CONFIG_DEFS

    return {"RAY_TPU_" + name.upper() for name in _CONFIG_DEFS}


def is_declared(name: str) -> bool:
    """Is ``name`` (a full RAY_TPU_* env var) a declared knob?"""
    return name in KNOBS or name in config_knob_names()


def readme_knob_table(internal: bool = False) -> str:
    """The generated markdown knob table for README (user-facing by
    default; ``internal=True`` renders the plumbing table). The
    knob-registry pass asserts every cataloged name appears in README,
    which holds as long as README carries both generated tables."""
    rows = [k for k in KNOBS.values() if k.internal == internal]
    rows.sort(key=lambda k: k.name)
    head = ("| knob | default | type | what it does |\n"
            "|---|---|---|---|")
    body = "\n".join(
        f"| `{k.name}` | `{k.default or '(unset)'}` | {k.type} | {k.doc} |"
        for k in rows)
    return head + "\n" + body
