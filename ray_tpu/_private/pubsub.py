"""Standalone long-poll pubsub — the reference's publisher/subscriber pair.

Reference: src/ray/pubsub/publisher.h:298 (Publisher with per-subscriber
mailboxes and long-poll replies), subscriber.h:213 (SubscriberInterface
with a polling thread). The GCS's connection-push channels cover the
common case; this subsystem adds the reference's other delivery mode:
subscribers that cannot hold a persistent inbound push channel (e.g.
behind NAT/proxies, or polling processes) long-poll the publisher, which
parks the request until a message arrives or the poll times out.

Semantics (matching publisher.h):
- per-subscriber bounded mailbox; overflow drops the OLDEST messages
  (slow consumers lose the head of the stream, never block publishers);
- sequence numbers ack delivery: messages at or below the polled
  `after_seq` are pruned, anything above re-delivers (at-least-once);
- subscribers are garbage-collected after `subscriber_timeout_s` with no
  poll AND no poll currently parked (the reference GCs on connection
  death; a long-poller's liveness signal IS the poll);
- channels may register a SNAPSHOT PROVIDER (``set_snapshot_provider``):
  a subscriber whose mailbox overflowed past the gap counter, or whose
  mailbox was GC'd while it was away, can ``rpc_psub_resync`` — one
  call that re-registers it and returns the channel's current state
  snapshot plus the seq floor to resume from, so a slow consumer
  reconverges from state instead of permanently missing the dropped
  head of the stream (the 100-subscriber soak's backlog-pressure fix).
"""
from __future__ import annotations

import threading
import time
import uuid


class Publisher:
    """Embeddable in any RpcServer handler: expose
    ``rpc_psub_poll``/``rpc_psub_subscribe`` by delegation and call
    ``publish`` from the owning service."""

    def __init__(self, max_mailbox: int | None = None,
                 subscriber_timeout_s: float | None = None):
        from ray_tpu._private.config import get_config

        if max_mailbox is None:
            max_mailbox = get_config("pubsub_max_mailbox")
        if subscriber_timeout_s is None:
            subscriber_timeout_s = get_config("pubsub_subscriber_timeout_s")
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.max_mailbox = max_mailbox
        self.subscriber_timeout_s = subscriber_timeout_s
        # sub_id -> {"channels": set, "mail": list[(seq, channel, msg)],
        #            "last_seen": float, "waiters": int}
        self._subs: dict[str, dict] = {}
        self._seq = 0
        # channel -> zero-arg callable returning a state snapshot for
        # gap-resync (owners register; absent = resync returns None)
        self._snapshot_providers: dict[str, object] = {}
        self.resyncs_served = 0

    def set_snapshot_provider(self, channel: str, provider):
        """Register ``provider()`` as the channel's resync source. The
        provider is called OUTSIDE the publisher lock (it usually reads
        the owning service's tables under that service's own lock)."""
        self._snapshot_providers[channel] = provider

    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._subs)

    # ---------------------------------------------------------- subscriber
    def subscribe(self, channels: list[str], sub_id: str | None = None) -> str:
        with self._lock:
            return self._register_locked(channels, sub_id)

    def _register_locked(self, channels, sub_id) -> str:
        sub_id = sub_id or uuid.uuid4().hex
        sub = self._subs.setdefault(sub_id, {
            "channels": set(), "mail": [],
            "last_seen": time.monotonic(), "waiters": 0, "dropped": 0,
        })
        sub["channels"].update(channels)
        sub["last_seen"] = time.monotonic()
        return sub_id

    def unsubscribe(self, sub_id: str, channels: list[str] | None = None):
        with self._lock:
            sub = self._subs.get(sub_id)
            if sub is None:
                return
            if channels is None:
                del self._subs[sub_id]
                return
            sub["channels"].difference_update(channels)
            if not sub["channels"]:
                del self._subs[sub_id]

    def poll(self, sub_id: str, after_seq: int, timeout: float = 30.0):
        """Long-poll: block until a message with seq > after_seq exists for
        this subscriber (or timeout). Returns (messages, max_seq) where
        messages is [(seq, channel, payload)]."""
        deadline = time.monotonic() + timeout
        with self._cond:
            sub = self._subs.get(sub_id)
            if sub is None:
                raise KeyError(f"unknown subscriber {sub_id!r}")
            sub["waiters"] += 1   # a parked poll is proof of life — no GC
            try:
                while True:
                    sub["last_seen"] = time.monotonic()
                    # after_seq acks everything at or below it
                    # (at-least-once: unacked messages re-deliver)
                    sub["mail"] = [m for m in sub["mail"]
                                   if m[0] > after_seq]
                    mail = list(sub["mail"])
                    if mail:
                        return mail, mail[-1][0]
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return [], after_seq
                    self._cond.wait(remaining)
            finally:
                sub["waiters"] -= 1
                sub["last_seen"] = time.monotonic()

    # ------------------------------------------------------------ publisher
    def publish(self, channel: str, message) -> int:
        """Deliver to every subscriber of `channel`; returns the seq."""
        return self.publish_many(channel, (message,))

    def publish_many(self, channel: str, messages) -> int:
        """Coalesced delivery: append every message to each subscriber's
        mailbox under ONE lock hold with ONE wakeup, instead of paying
        the per-subscriber walk + notify_all per message (at 100
        subscribers a 10-death storm is 100 mailbox walks either way,
        but 1000 → 100 lock/notify rounds). Returns the LAST seq."""
        now = time.monotonic()
        overflow = 0
        messages = list(messages)
        if not messages:
            return self._seq
        with self._cond:
            first_seq = self._seq + 1
            self._seq += len(messages)
            seq = self._seq
            stale = []
            for sub_id, sub in self._subs.items():
                if (sub["waiters"] == 0
                        and now - sub["last_seen"]
                        > self.subscriber_timeout_s):
                    stale.append(sub_id)
                    continue
                if channel in sub["channels"]:
                    sub["mail"].extend(
                        (first_seq + i, channel, m)
                        for i, m in enumerate(messages))
                    if len(sub["mail"]) > self.max_mailbox:
                        # drop-oldest; slow consumers never block
                        # publishers — but the loss is COUNTED so the
                        # subscriber can surface it as a gap
                        n_drop = len(sub["mail"]) - self.max_mailbox
                        sub["dropped"] = sub.get("dropped", 0) + n_drop
                        overflow += n_drop
                        del sub["mail"][:n_drop]
            for sub_id in stale:
                del self._subs[sub_id]
            backlog = sum(len(s["mail"]) for s in self._subs.values())
            self._cond.notify_all()
        # telemetry outside the condition: publishers must not hold the
        # delivery lock across the metrics registry's lock
        from ray_tpu._private import telemetry as _tm

        if _tm.ENABLED:
            _tm.gauge_set("ray_tpu_pubsub_backlog_messages", backlog)
            if overflow:
                _tm.counter_inc("ray_tpu_pubsub_dropped_total", overflow)
        return seq

    # ------------------------------------------------ RpcServer handler glue
    def rpc_psub_subscribe(self, conn, channels: list,
                           sub_id: str | None = None):
        """Returns (sub_id, current_seq, existed): `existed` tells a
        re-subscribing client whether its mailbox survived (False after a
        publisher-side GC — anything since its last ack is gone). Snapshot
        and registration happen under ONE lock hold so a concurrent
        publish/GC can't invalidate the answer."""
        with self._lock:
            existed = sub_id is not None and sub_id in self._subs
            cur = self._seq
            sub_id = self._register_locked(channels, sub_id)
        return sub_id, cur, existed

    def rpc_psub_unsubscribe(self, conn, sub_id: str, channels=None):
        self.unsubscribe(sub_id, channels)

    def rpc_psub_poll(self, conn, sub_id: str, after_seq: int,
                      poll_timeout: float = 30.0):
        """Returns (mail, max_seq, dropped): `dropped` counts messages
        lost to mailbox overflow since the previous poll, so slow
        consumers see the discontinuity instead of a silently thinned
        stream (review finding, round 4)."""
        mail, max_seq = self.poll(sub_id, after_seq, timeout=poll_timeout)
        with self._lock:
            sub = self._subs.get(sub_id)
            dropped = 0
            if sub is not None:
                dropped = sub.get("dropped", 0)
                sub["dropped"] = 0
        return mail, max_seq, dropped

    def rpc_psub_resync(self, conn, sub_id: str, channels: list):
        """Snapshot-resync for a subscriber that detected a gap (mailbox
        overflow past the poll reply's dropped count, or a publisher-side
        GC while it was away): re-register the subscriber, CLEAR its
        mailbox, and return ``(seq_floor, {channel: snapshot})`` — state
        captured at-or-after the floor, so resuming polls from
        ``seq_floor`` re-delivers anything newer than the snapshot
        (at-least-once; consumers already tolerate duplicates). Channels
        without a registered provider map to None."""
        with self._lock:
            self._register_locked(channels, sub_id)
            sub = self._subs.get(sub_id)
            if sub is not None:
                sub["mail"] = []
                sub["dropped"] = 0
            seq_floor = self._seq
            providers = {ch: self._snapshot_providers.get(ch)
                         for ch in channels}
            self.resyncs_served += 1
        # providers run OUTSIDE the publisher lock: they read the owning
        # service's tables under that service's own lock, and state read
        # after the floor only makes the snapshot fresher (messages
        # between floor and the read re-deliver on the next poll)
        snapshots = {}
        for ch, provider in providers.items():
            if provider is None:
                snapshots[ch] = None
                continue
            try:
                snapshots[ch] = provider()
            except Exception:
                snapshots[ch] = None
        return seq_floor, snapshots


class Subscriber:
    """Client side: a polling thread delivering messages to callbacks.

    ``subscribe(channel, callback)`` registers server-side and starts the
    long-poll loop; callbacks run on the poll thread in publish order.
    Poll failures back off and re-subscribe (sequence floor preserved
    across transient disconnects by re-using the subscriber id). If the
    publisher GC'd the mailbox while we were away, the messages between
    our last ack and the re-subscribe are gone — that discontinuity is
    surfaced through ``on_gap(n_missed_upper_bound)`` and counted in
    ``gap_count`` so consumers can re-sync state instead of silently
    believing the stream was contiguous (advisor finding, round 3).
    Mailbox-overflow drops at the publisher (slow consumer) are reported
    the same way via the poll reply's dropped count.

    With ``auto_resync=True`` every detected gap additionally triggers a
    snapshot-resync (``psub_resync``): the publisher clears the mailbox,
    hands back the current per-channel state snapshot, and the
    subscriber delivers it to each channel's callbacks as a synthetic
    ``{"event": "resync", "snapshot": ...}`` message — so consumers
    reconverge from state instead of permanently missing whatever
    overflowed or was GC'd (``on_gap`` still fires first, and
    ``resync_count`` counts the recoveries).
    """

    def __init__(self, rpc_client, poll_timeout: float = 10.0, on_gap=None,
                 auto_resync: bool = False):
        self._rpc = rpc_client
        self._poll_timeout = poll_timeout
        self._callbacks: dict[str, list] = {}
        self._lock = threading.Lock()
        self._sub_id: str | None = None
        self._last_seq = 0
        self._on_gap = on_gap
        self._auto_resync = auto_resync
        self.gap_count = 0
        self.resync_count = 0
        # bumped by every _announce_locked resync: a poll that was already
        # in flight when the floor moved must not write its stale max_seq
        # back over the resynced _last_seq
        self._floor_epoch = 0
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None

    def subscribe(self, channel: str, callback):
        with self._lock:
            self._callbacks.setdefault(channel, []).append(callback)
            # announce ALL channels: if the publisher GC'd our mailbox
            # since the last poll, registering only the new channel would
            # silently drop the earlier subscriptions server-side
            gap = self._announce_locked()
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="pubsub-poll")
                self._thread.start()
        self._note_gap(gap)
        return self._sub_id

    def _announce_locked(self) -> int:
        """(Re-)register every subscribed channel; returns the detected
        gap size (0 = contiguous). Caller holds self._lock."""
        prior = self._sub_id
        self._sub_id, cur_seq, existed = self._rpc.call(
            "psub_subscribe", channels=list(self._callbacks),
            sub_id=prior)
        if prior is None:
            # subscribe-from-now: the new mailbox is empty, so acking the
            # publisher's current seq is exact, not lossy
            self._last_seq = cur_seq
            self._floor_epoch += 1
            return 0
        if not existed and cur_seq != self._last_seq:
            # mailbox dropped: anything after our last ack is gone.
            # cur_seq < _last_seq means the publisher itself restarted
            # (fresh seq space) — resync or every future message would be
            # pruned as already-acked.
            gap = max(1, cur_seq - self._last_seq)
            self._last_seq = cur_seq
            self._floor_epoch += 1
            return gap
        return 0

    def _note_gap(self, gap: int):
        if not gap:
            return
        self.gap_count += 1
        if self._on_gap is not None:
            try:
                self._on_gap(gap)
            except Exception:
                pass
        if self._auto_resync:
            try:
                self._resync()
            except Exception:
                pass   # next gap (or poll failure) retries

    def _resync(self):
        """Snapshot-resync after a detected gap: fetch the per-channel
        state snapshots, move the seq floor, and deliver each snapshot
        to its channel's callbacks as a synthetic resync message. Runs
        on whichever thread detected the gap (poll loop, or the caller
        of subscribe()); the RPC happens OUTSIDE self._lock."""
        with self._lock:
            sub_id = self._sub_id
            channels = list(self._callbacks)
        if sub_id is None or not channels:
            return
        seq_floor, snapshots = self._rpc.call(
            "psub_resync", sub_id=sub_id, channels=channels)
        with self._lock:
            self._last_seq = seq_floor
            self._floor_epoch += 1
            deliver = [(ch, list(self._callbacks.get(ch, ())))
                       for ch in channels]
        self.resync_count += 1
        from ray_tpu._private import events as _events

        _events.record("PUBSUB_RESYNC", channels=channels,
                       seq_floor=seq_floor, resync_count=self.resync_count)
        from ray_tpu._private import telemetry as _tm

        if _tm.ENABLED:
            _tm.counter_inc("ray_tpu_pubsub_resyncs_total")
        for ch, cbs in deliver:
            msg = {"event": "resync", "channel": ch,
                   "snapshot": snapshots.get(ch)}
            for cb in cbs:
                try:
                    cb(msg)
                except Exception:
                    pass

    def unsubscribe(self, channel: str):
        with self._lock:
            self._callbacks.pop(channel, None)
            if self._sub_id is not None:
                try:
                    self._rpc.call("psub_unsubscribe", sub_id=self._sub_id,
                                   channels=[channel])
                except Exception:
                    pass

    def stop(self):
        self._stopped.set()

    def _loop(self):
        from ray_tpu._private.retry import RetryPolicy
        from ray_tpu._private.task_spec import validate_pubsub_ack

        # consecutive-failure backoff rides the unified policy's
        # full-jitter curve (was a hand-rolled *2-capped sleep); no
        # attempt cap — a long-poll loop retries for the process
        # lifetime, the policy only shapes the pauses
        policy = RetryPolicy(base_backoff_s=0.1, max_backoff_s=5.0,
                             deadline_s=None)
        failures = 0
        while not self._stopped.is_set():
            try:
                with self._lock:
                    sub_id = self._sub_id
                    after = self._last_seq
                    epoch = self._floor_epoch
                validate_pubsub_ack(sub_id, after)   # producer-side shape
                from ray_tpu._private.config import get_config

                # transport slack past the server's park window rides the
                # unified control-plane timeout (was a hardcoded +30s —
                # a lost poll request then stalled the loop half a minute)
                mail, max_seq, dropped = self._rpc.call(
                    "psub_poll", sub_id=sub_id,
                    after_seq=after,
                    poll_timeout=self._poll_timeout,
                    timeout=self._poll_timeout +
                    float(get_config("gcs_rpc_timeout_s")))
                with self._lock:
                    # a resync while this poll was in flight makes its
                    # max_seq meaningless in the new seq space
                    if self._floor_epoch == epoch:
                        self._last_seq = max_seq
                failures = 0
            except Exception:
                if self._stopped.is_set():
                    return
                failures += 1
                time.sleep(policy.backoff(failures))
                # re-announce (the publisher may have GC'd us)
                gap = 0
                try:
                    with self._lock:
                        if self._callbacks:
                            gap = self._announce_locked()
                except Exception:
                    pass
                self._note_gap(gap)
                continue
            for _seq, channel, message in mail:
                with self._lock:
                    cbs = list(self._callbacks.get(channel, ()))
                for cb in cbs:
                    try:
                        cb(message)
                    except Exception:
                        pass
            # gap handling (and its auto-resync snapshot) AFTER the
            # in-hand mail: a resync floor covers these messages' seqs,
            # so delivering a retained stale message after the snapshot
            # would let it overwrite fresher snapshot state at a
            # last-writer-wins consumer with no re-delivery to correct it
            self._note_gap(dropped)   # mailbox-overflow losses


class ActorDeathWatch:
    """Handle for one GCS channel subscription (see
    ``watch_channel`` / ``watch_actor_deaths``); ``stop()`` tears down
    both the poll loop and its dedicated GCS connection."""

    def __init__(self, rpc, sub):
        self._rpc = rpc
        self._sub = sub

    def stop(self):
        sub, self._sub = self._sub, None
        rpc, self._rpc = self._rpc, None
        if sub is not None:
            try:
                sub.stop()
            except Exception:
                pass
        if rpc is not None:
            try:
                rpc.close()
            except Exception:
                pass


def watch_channel(channel: str, callback, gcs_addr,
                  poll_timeout: float = 5.0) -> ActorDeathWatch:
    """One GCS channel subscription on a DEDICATED
    ``ReconnectingRpcClient`` with ``auto_resync`` — the shared
    plumbing under ``watch_actor_deaths``, the placement-group waiter,
    and the Train plane's preemption monitor, so the
    reconnect/resync semantics cannot drift between them. ``callback``
    receives raw channel messages INCLUDING the synthetic
    ``{"event": "resync", "snapshot": ...}``. Raises on setup failure
    (callers pick their degraded mode); returns a handle whose
    ``stop()`` tears down the loop + connection."""
    from ray_tpu._private.protocol import ReconnectingRpcClient

    rpc = ReconnectingRpcClient(tuple(gcs_addr), timeout=30.0)
    try:
        sub = Subscriber(rpc, poll_timeout=poll_timeout,
                         auto_resync=True)
        sub.subscribe(channel, callback)
    except Exception:
        try:
            rpc.close()
        except Exception:
            pass
        raise
    return ActorDeathWatch(rpc, sub)


def watch_actor_deaths(on_death, poll_timeout: float = 5.0,
                       gcs_addr=None):
    """Subscribe to the GCS actor-lifecycle feed from this process and
    invoke ``on_death(actor_id, reason)`` for every actor death or
    out-from-under restart. The one place that knows the feed's event
    vocabulary — every "watch these actors, tell me when one dies"
    consumer (train gang monitor, collective rendezvous) filters its own
    actor_ids in the callback rather than re-implementing the
    subscription. Returns an ``ActorDeathWatch`` (call ``stop()``), or
    ``None`` when no worker runtime is attached to this process;
    transport errors propagate so callers choose their degraded mode.
    ``gcs_addr`` overrides the attached worker's GCS (the scale soak
    opens 100 watches against a harness GCS with no worker runtime).

    The connection is a ``ReconnectingRpcClient``: the GCS may RESTART
    in fault-tolerant mode, and a plain client would leave this watch
    dead forever after one — every psub_poll raising into the
    Subscriber's backoff loop while ``active()`` still reads True, so
    rank-death detection would silently degrade to op-timeout-only. On
    heal, the poll's unknown-subscriber KeyError drives the
    Subscriber's own re-announce, restoring the feed.

    The subscription rides ``auto_resync``: a mailbox overflow or a
    GC'd subscription (a death STORM outpacing this consumer, or a GCS
    restart losing the mailbox) resyncs against the GCS actor-table
    snapshot, and any actor the snapshot shows DEAD/RESTARTING is
    re-reported through ``on_death`` — so a watcher can miss feed
    messages but never a death (consumers are duplicate-tolerant by
    the at-least-once contract).
    """
    if gcs_addr is None:
        from ray_tpu._private.worker_runtime import current_worker

        worker = current_worker()
        if worker is None:
            return None
        gcs_addr = worker.gcs.addr

    def _cb(msg):
        if not isinstance(msg, dict):
            return
        if msg.get("event") == "resync":
            for row in (msg.get("snapshot") or ()):
                if row.get("state") in ("DEAD", "RESTARTING") and \
                        row.get("actor_id") is not None:
                    on_death(row["actor_id"],
                             str(row.get("reason")
                                 or row["state"].lower()))
            return
        if msg.get("event") not in ("dead", "restarting"):
            return
        actor_id = msg.get("actor_id")
        if actor_id is None:
            return
        on_death(actor_id, str(msg.get("reason") or msg["event"]))

    return watch_channel("actors", _cb, gcs_addr,
                         poll_timeout=poll_timeout)
