"""Raylet — the per-node manager.

TPU-native analog of the reference's raylet (/root/reference/src/ray/raylet/
node_manager.h): owns this node's shared-memory object store segment, a pool
of worker processes (worker_pool.h:152), and the local half of the two-level
scheduler — lease requests are granted locally when resources fit, spilled
back to another node otherwise (the hybrid policy of
scheduling/policy/hybrid_scheduling_policy.h:24-47: pack onto the local node
below a utilization threshold, then spread).

Differences from the reference, by design:
- the object store is a mapped library, not a forked daemon, so "starting
  plasma" is just creating the segment;
- GCS holds the authoritative cluster resource view (the RaySyncer gossip is
  replaced by raylets reporting load on heartbeat);
- TPU chips are a first-class resource: the raylet detects locally attached
  chips via jax and advertises them as "TPU" alongside "CPU"/"memory".
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
import uuid

from ray_tpu._private.protocol import ConnectionLost, RpcClient, RpcServer
from ray_tpu._private.store_client import StoreClient

_LEASE_QUEUE_POLL = 0.02


def _chip_detection_enabled() -> bool:
    # On by default on real deployments; off under tests (importing jax per
    # in-process raylet is slow and every virtual node would claim the same
    # tunneled chip).
    default = "0" if os.environ.get("RAY_TPU_TESTING") == "1" else "1"
    return os.environ.get("RAY_TPU_DETECT_CHIPS", default) == "1"


def detect_tpu_topology() -> dict | None:
    """Structured TPU topology for this host (the ICI-aware scheduler's
    input; reference role: the flat `resources: {"TPU": n}` of
    autoscaler/gcp/tpu.yaml:29, which loses slice/coord structure).

    Sources: the TPU runtime env (TPU_ACCELERATOR_TYPE / TPU_TOPOLOGY /
    TPU_WORKER_ID / TPU_NAME are set on GCE/GKE TPU VMs) plus jax device
    coords when available. Returns None off-TPU.
    """
    env = os.environ
    info: dict = {}
    if env.get("TPU_ACCELERATOR_TYPE"):
        info["accelerator_type"] = env["TPU_ACCELERATOR_TYPE"]
    if env.get("TPU_TOPOLOGY"):
        info["topology"] = env["TPU_TOPOLOGY"]
    if env.get("TPU_WORKER_ID") is not None and env.get("TPU_WORKER_ID") != "":
        try:
            info["worker_id"] = int(env["TPU_WORKER_ID"])
        except ValueError:
            pass
    slice_id = env.get("TPU_NAME") or env.get("TPU_SLICE_ID")
    if slice_id:
        info["slice_id"] = slice_id
    if _chip_detection_enabled():
        # SUBPROCESS probe with a timeout: an in-process jax.devices()
        # hangs FOREVER on a wedged axon tunnel, which would wedge
        # raylet startup (and with it ray_tpu.init) on any box where the
        # tunnel is down — learned the hard way in rounds 3-4.
        from ray_tpu._private.config import get_config
        from ray_tpu._private.tpu_probe import probe_chips

        chips = probe_chips(timeout_s=float(get_config("chip_probe_timeout_s")))
        if chips:
            for k, v in chips.items():
                info.setdefault(k, v)   # env-derived identity wins
    if not info:
        return None
    info.setdefault("slice_id", "slice-0")
    info.setdefault("worker_id", 0)
    return info


def detect_resources(num_cpus=None, num_tpus=None, memory=None,
                     resources=None) -> dict:
    out = dict(resources or {})
    out["CPU"] = float(num_cpus if num_cpus is not None else os.cpu_count() or 1)
    if num_tpus is None:
        num_tpus = 0
        if _chip_detection_enabled():
            # SUBPROCESS probe (shared with detect_tpu_topology): an
            # in-process jax.devices() hangs forever on a wedged axon
            # tunnel, which would hang ray_tpu.init itself.
            from ray_tpu._private.config import get_config
            from ray_tpu._private.tpu_probe import probe_chips

            chips = probe_chips(
                timeout_s=float(get_config("chip_probe_timeout_s")))
            num_tpus = (chips or {}).get("chips", 0)
    if num_tpus:
        out["TPU"] = float(num_tpus)
    if memory is None:
        try:
            memory = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
        except (ValueError, OSError):
            memory = 8 << 30
    out["memory"] = float(memory)
    return out


class WorkerHandle:
    def __init__(self, proc: subprocess.Popen, worker_id: str):
        self.proc = proc
        self.worker_id = worker_id
        self.addr = None            # set when the worker registers
        self.registered = threading.Event()
        self.idle_since = time.time()
        self.assigned_lease = None  # lease_id when leased out
        self.is_actor = False
        self.actor_id = None


class Lease:
    def __init__(self, lease_id: str, resources: dict, worker: WorkerHandle,
                 lessee: tuple | None = None, job: str | None = None):
        self.lease_id = lease_id
        self.resources = resources
        self.worker = worker
        self.granted_at = time.time()   # OOM victim ranking (newest first)
        # (worker_id, addr) of the requesting core worker: leases die with
        # their lessee (reference: leases are tied to the lease client's
        # connection; a dead lessee's resources must be reclaimed)
        self.lessee_id = lessee[0] if lessee else None
        self.lessee_addr = tuple(lessee[1]) if lessee else None
        # multi-tenant label: per-job lease usage is gossiped to the GCS
        # (quota accounting) and over-quota jobs are throttled at grant
        self.job = job or None


class Raylet:
    def __init__(self, gcs_addr, node_id: str | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 resources: dict | None = None,
                 store_size: int = 256 * 1024 * 1024,
                 session_dir: str | None = None,
                 tpu_topology: dict | None = None):
        self.node_id = node_id or uuid.uuid4().hex[:16]
        self.gcs_addr = tuple(gcs_addr)
        self.resources_total = dict(resources or detect_resources())
        # structured TPU info for the ICI-aware PG scheduler; tests inject
        # fake slices, real deployments auto-detect
        self.tpu_topology = (tpu_topology if tpu_topology is not None
                             else detect_tpu_topology())
        if (tpu_topology is None and self.tpu_topology
                and self.tpu_topology.get("chips")):
            # real chips detected (not test-injected topology): seed the
            # per-device HBM gauges now, while no worker owns the chips
            # (one subprocess probe by default; recurring polling is the
            # opt-in RAY_TPU_DEVICE_GAUGE_POLL_S — live in-use numbers
            # come from the owning train workers in-process). Never runs
            # on CPU CI boxes.
            from ray_tpu._private.tpu_probe import start_device_gauge_poller

            start_device_gauge_poller()
        self.resources_avail = dict(self.resources_total)
        self.session_dir = session_dir or os.path.join(
            "/tmp/ray_tpu", f"session_{os.getpid()}")
        os.makedirs(self.session_dir, exist_ok=True)
        self.store_name = f"rtpu-{self.node_id[:12]}"
        self.spill_dir = os.path.join(self.session_dir,
                                      f"spill_{self.node_id[:8]}")
        self.store = StoreClient(self.store_name, create=True,
                                 size=store_size, spill_dir=self.spill_dir)
        # native (C++) chunk server: remote pulls stream object bytes out
        # of the mmap'd segment GIL-free (src/store/data_server.cc)
        try:
            self.data_port = self.store.start_data_server()
        except Exception:
            self.data_port = None
        self._lock = threading.RLock()
        self._workers: dict[str, WorkerHandle] = {}    # worker_id -> handle
        self._idle: list[WorkerHandle] = []
        self._leases: dict[str, Lease] = {}
        self._pending: list[dict] = []                 # queued lease requests
        self._pg_reserved: dict[tuple, dict] = {}      # (pg_id,bundle) -> res
        # resource shapes of requests currently queued on this node — the
        # autoscaler's demand signal (reference: LoadMetrics resource_load)
        self._queued_demand: list[dict] = []
        # jobs the GCS currently reports over quota (`jobs` channel):
        # lease grants for these queue until the throttle clears.
        # Replaced wholesale per quota push, never grown per id.
        self._job_throttle: frozenset[str] = frozenset()
        self._stopped = False

        # Monitors are CONSTRUCTED before the RPC server starts: the
        # moment the server is up (and register_node lands), a remote
        # driver can send request_lease → _spawn_worker, which needs
        # logs_dir/_log_monitor. Their threads start only after the GCS
        # connection exists (their publish/kill hooks ride it).
        from ray_tpu._private.log_monitor import LogMonitor
        from ray_tpu._private.memory_monitor import MemoryMonitor

        self.logs_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(self.logs_dir, exist_ok=True)
        # Worker log capture → GCS pubsub → driver console (reference:
        # _private/log_monitor.py as a thread instead of a process).
        from ray_tpu._private.config import get_config

        self._log_monitor = LogMonitor(
            lambda ch, msg: self._gcs.push("publish", channel=ch,
                                           message=msg),
            node_id=self.node_id,
            interval_s=get_config("log_monitor_interval_ms") / 1000.0)
        # OOM protection: poll node memory; above the threshold kill the
        # newest-task worker with a retriable OutOfMemoryError instead of
        # letting the kernel OOM-killer take the node (reference:
        # common/memory_monitor.h:88 + raylet/worker_killing_policy.h:30).
        self._oom_reasons: dict[str, str] = {}   # worker_id -> message
        self._mem_monitor = MemoryMonitor(self._on_memory_pressure)
        # worker-pool spawn state — must exist before the server starts
        # accepting lease requests (they reach _spawn_worker)
        self._idle_cap = int(get_config("idle_worker_cap"))
        self._prestart_target = min(
            int(self.resources_total.get("CPU", 1)), self._idle_cap,
            int(get_config("prestart_workers")))
        self._spawning = 0
        startup_conc = int(get_config("max_startup_concurrency"))
        if startup_conc <= 0:
            startup_conc = os.cpu_count() or 2
        self._spawn_gate = threading.BoundedSemaphore(max(2, startup_conc))

        self._server = RpcServer(self, host, port).start()
        self.addr = self._server.addr
        # Self-healing GCS channel: survives a GCS restart by
        # re-registering this node and re-announcing its live actors
        # (reference: node_manager.cc:1179 HandleNotifyGCSRestart)
        from ray_tpu._private.protocol import ReconnectingRpcClient

        self._gcs = ReconnectingRpcClient(
            self.gcs_addr, on_push=self._on_gcs_push,
            on_reconnect=self._replay_gcs_registration)
        self._replay_gcs_registration(self._gcs)
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True,
                                        name=f"raylet-reap-{self.node_id[:6]}")
        self._reaper.start()
        self._log_monitor.start()
        self._mem_monitor.start()
        # Warm pool: prestart workers so the first leases don't eat Python
        # startup latency, and REFILL toward this watermark whenever the
        # pool is drawn down (reference: worker_pool.h PrestartWorkers +
        # idle-pool maintenance) — on-demand cold spawns under load cost
        # ~300ms each of lease-grant latency (profiled round 4).
        if self._prestart_target > 0:
            self._maybe_refill()

    def _replay_gcs_registration(self, gcs):
        """Initial registration AND the reconnect replay: (re-)register
        this node, re-subscribe, and re-announce actors still running
        here so a restarted GCS repopulates its actor table with live
        addresses instead of restarting healthy actors."""
        gcs.call("register_node", node_id=self.node_id, addr=self.addr,
                 resources=self.resources_total,
                 meta={"store_name": self.store_name,
                       "spill_dir": self.spill_dir,
                       "session_dir": self.session_dir,
                       "hostname": os.uname().nodename,
                       "pid": os.getpid(),
                       "object_data_port": self.data_port,
                       "tpu": self.tpu_topology})
        gcs.call("subscribe", channels=["placement_groups", "jobs"])
        try:
            # seed the over-quota view: the jobs channel is
            # publish-on-change, so a fresh (or re-registering) node
            # can't wait for the next transition to learn the CURRENT
            # set. Best-effort — a miss degrades to unthrottled grants
            # until the next change push, never fails registration.
            self._job_throttle = frozenset(
                gcs.call("get_job_throttle"))
        except Exception:
            pass
        with self._lock:
            live = [(h.actor_id, h.addr)
                    for h in self._workers.values()
                    if h.is_actor and h.actor_id and h.addr
                    and h.proc is not None and h.proc.poll() is None]
        # Failures here MUST propagate: the replay only runs on
        # reconnect, and a swallowed actor_started would leave the actor
        # out of the GCS's re-announce set — the recovery reconcile
        # would then restart a healthy actor (split-brain). Raising
        # aborts this reconnect; the next 600ms report tick retries the
        # whole replay.
        for actor_id, addr in live:
            gcs.call("actor_started", actor_id=actor_id, addr=addr,
                     node_id=self.node_id)

    def _maybe_refill(self):
        """Top the idle pool back up to the prestart watermark in the
        background (never blocks a grant)."""
        if self._stopped:
            return
        with self._lock:
            deficit = (self._prestart_target - len(self._idle)
                       - self._spawning)
            if deficit <= 0:
                return
            self._spawning += deficit
        threading.Thread(target=self._refill, args=(deficit,),
                         daemon=True).start()

    def _refill(self, n: int):
        try:
            handles = [self._spawn_worker() for _ in range(n)]
            for h in handles:
                if h.registered.wait(30.0) and h.proc.poll() is None:
                    with self._lock:
                        if (h.assigned_lease is None
                                and h not in self._idle
                                and len(self._idle) < self._idle_cap):
                            self._idle.append(h)
                        elif h.assigned_lease is None:
                            # pool refilled concurrently (returned leases
                            # beat us): a worker neither idle nor leased
                            # would be an orphan process — kill it
                            self._kill_worker(h)
        except Exception:
            pass   # raylet stopping mid-refill
        finally:
            with self._lock:
                self._spawning -= n

    # ---- GCS pushes ---------------------------------------------------------

    def _on_gcs_push(self, payload):
        """Runs on the GCS RpcClient's reader thread — must NEVER issue a
        synchronous call back over the same connection (the reply could not
        be read). Handlers are either local-only or spawn a thread."""
        method, kwargs = payload
        if method == "free_objects":
            for oid in kwargs["object_ids"]:
                try:
                    self.store.delete(oid)
                except Exception:
                    # last hop of the one-way free pipeline lost: the
                    # object strands in this node's store until the
                    # leak sweep names it — count the drop
                    try:
                        from ray_tpu._private import memory_anatomy

                        memory_anatomy.LEDGER.note_free_dropped(
                            "raylet_delete")
                    except Exception:
                        pass
        elif method == "recreate_actor":
            threading.Thread(target=self._restart_actor,
                             args=(kwargs["actor_id"],), daemon=True).start()
        elif method == "pubsub" and kwargs.get("channel") == "placement_groups":
            msg = kwargs["message"]
            if msg["event"] == "created":
                self._reserve_pg_bundles(msg["pg_id"], msg["bundle_nodes"],
                                         msg["bundles"])
            elif msg["event"] == "removed":
                self._release_pg_bundles(msg["pg_id"])
        elif method == "pubsub" and kwargs.get("channel") == "jobs":
            msg = kwargs["message"]
            if msg.get("event") == "quota":
                # cluster-wide quota view (eventually consistent by one
                # gossip round); queued lease grants re-check it per poll
                self._job_throttle = frozenset(msg.get("over", ()))

    def _reserve_pg_bundles(self, pg_id: bytes, bundle_nodes: list[str],
                            bundles: list[dict]):
        with self._lock:
            for i, (bundle, nid) in enumerate(zip(bundles, bundle_nodes)):
                key = (pg_id, i)
                if nid == self.node_id and key not in self._pg_reserved:
                    for k, v in bundle.items():
                        self.resources_avail[k] = \
                            self.resources_avail.get(k, 0) - v
                    self._pg_reserved[key] = dict(bundle)

    def _release_pg_bundles(self, pg_id: bytes):
        with self._lock:
            for key in [k for k in self._pg_reserved if k[0] == pg_id]:
                for res, v in self._pg_reserved.pop(key).items():
                    self.resources_avail[res] = \
                        self.resources_avail.get(res, 0) + v
        self._pump_pending()

    # ---- worker pool (reference: raylet/worker_pool.h) ----------------------

    def _spawn_worker(self) -> WorkerHandle:
        if self._stopped:
            raise RuntimeError("raylet is stopped")
        # Bound concurrent process STARTUPS (reference: worker_pool.h
        # maximum_startup_concurrency = num_cpus): 400 actors creating at
        # once means 400 interpreters importing simultaneously on however
        # many cores exist — everything times out. The gate is held from
        # fork until the worker registers (or 30 s), so at most gate-width
        # workers are mid-startup; callers keep their own registered.wait.
        self._spawn_gate.acquire()
        try:
            handle = self._spawn_worker_inner()
        except BaseException:
            self._spawn_gate.release()
            raise

        def _release_when_up():
            try:
                handle.registered.wait(30.0)
            finally:
                self._spawn_gate.release()

        threading.Thread(target=_release_when_up, daemon=True).start()
        return handle

    def _spawn_worker_inner(self) -> WorkerHandle:
        worker_id = uuid.uuid4().hex[:16]
        env = dict(os.environ)
        env["RAY_TPU_WORKER_ID"] = worker_id
        env["RAY_TPU_RAYLET_ADDR"] = f"{self.addr[0]}:{self.addr[1]}"
        env["RAY_TPU_GCS_ADDR"] = f"{self.gcs_addr[0]}:{self.gcs_addr[1]}"
        env["RAY_TPU_STORE_NAME"] = self.store_name
        env["RAY_TPU_SPILL_DIR"] = self.spill_dir
        env["RAY_TPU_NODE_ID"] = self.node_id
        # driver's init(system_config=...) overrides reach workers as env
        # (config keys consumed worker-side would otherwise silently keep
        # their defaults there)
        from ray_tpu._private.config import GlobalConfig

        env.update(GlobalConfig.system_override_env())
        env.setdefault("JAX_PLATFORMS", os.environ.get("JAX_PLATFORMS", ""))
        # Make ray_tpu importable from anywhere, and on CPU-only runs drop
        # TPU-plugin site dirs from PYTHONPATH: their sitecustomize adds ~10s
        # of tunnel/plugin setup to every worker interpreter start.
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
        if env.get("JAX_PLATFORMS", "").startswith("cpu"):
            parts = [p for p in parts if "axon" not in p]
        if repo_root not in parts:
            parts.insert(0, repo_root)
        env["PYTHONPATH"] = os.pathsep.join(parts)
        # Workers log to per-worker files in the session dir (reference:
        # workers write session_latest/logs/worker-*.out/.err, tailed by
        # the log monitor); the raylet's LogMonitor streams new lines to
        # the driver over pubsub.
        out_path = os.path.join(self.logs_dir, f"worker-{worker_id}.out")
        err_path = os.path.join(self.logs_dir, f"worker-{worker_id}.err")
        with open(out_path, "ab") as out_f, open(err_path, "ab") as err_f:
            proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.worker_main"],
                env=env, cwd=os.getcwd(),
                stdout=out_f, stderr=err_f)
        handle = WorkerHandle(proc, worker_id)
        self._log_monitor.track(worker_id, proc.pid, out_path, err_path)
        with self._lock:
            self._workers[worker_id] = handle
        return handle

    def _pop_worker(self, timeout: float | None = None) -> WorkerHandle:
        if timeout is None:
            from ray_tpu._private.config import get_config

            timeout = float(get_config("worker_register_timeout_s"))
        with self._lock:
            while self._idle:
                handle = self._idle.pop()
                if handle.proc.poll() is None:
                    break
            else:
                handle = None
        if handle is not None:
            self._maybe_refill()   # keep the next burst warm
            return handle
        handle = self._spawn_worker()
        self._maybe_refill()
        if not handle.registered.wait(timeout):
            raise TimeoutError(
                f"worker {handle.worker_id} failed to register in {timeout}s")
        return handle

    def rpc_register_worker(self, conn, worker_id: str, addr, pid: int):
        with self._lock:
            handle = self._workers.get(worker_id)
            if handle is None:      # externally started (driver) — track it
                handle = WorkerHandle(None, worker_id)
                self._workers[worker_id] = handle
            handle.addr = tuple(addr)
            conn.meta["worker_id"] = worker_id
        handle.registered.set()
        # `node` is the snapshot shape _pull_remote consumes — workers hand
        # it to object OWNERS when announcing copies (owner-based directory)
        return {"node_id": self.node_id, "store_name": self.store_name,
                "spill_dir": self.spill_dir,
                "node": {"NodeID": self.node_id,
                         "NodeManagerAddress": self.addr[0],
                         "NodeManagerPort": self.addr[1],
                         "object_data_port": self.data_port}}

    def on_disconnect(self, conn):
        worker_id = conn.meta.get("worker_id")
        if worker_id:
            self._on_worker_exit(worker_id)

    def _reap_loop(self):
        ticks = 0
        while not self._stopped:
            time.sleep(0.2)
            ticks += 1
            dead = []
            with self._lock:
                for wid, h in self._workers.items():
                    if h.proc is not None and h.proc.poll() is not None:
                        dead.append(wid)
            for wid in dead:
                self._on_worker_exit(wid)
            if ticks % 25 == 0:   # every ~5s: GC leases of remote lessees
                self._gc_remote_lessee_leases()
                self._reap_idle_workers()
            if ticks % 3 == 0:    # ~600ms: resource view → GCS (the
                # RaySyncer-gossip analog; the PG scheduler packs against
                # this instead of node totals)
                try:
                    with self._lock:
                        avail = dict(self.resources_avail)
                        demand = [dict(d) for d in self._queued_demand]
                        busy = len(self._leases) + sum(
                            1 for w in self._workers.values() if w.is_actor)
                        job_busy: dict[str, dict] = {}
                        for lease in self._leases.values():
                            if lease.job:
                                agg = job_busy.setdefault(lease.job, {})
                                for k, v in lease.resources.items():
                                    agg[k] = agg.get(k, 0.0) + v
                    from ray_tpu._private import telemetry as _tm

                    _tm.gauge_set("ray_tpu_scheduler_queue_tasks",
                                  len(demand),
                                  tags={"node_id": self.node_id})
                    self._gcs.push("report_resources",
                                   node_id=self.node_id, available=avail,
                                   pending_demand=demand, busy=busy,
                                   job_busy=job_busy)
                except Exception:
                    pass

    def _on_memory_pressure(self, used: int, total: int):
        """Kill one worker to relieve node memory pressure. Victim choice
        is newest-task-first (memory_monitor.pick_victim); the kill reason
        is recorded in GCS KV *before* the SIGKILL so the task's owner —
        observing the dropped connection — can surface OutOfMemoryError
        instead of a generic WorkerCrashedError."""
        from ray_tpu._private.memory_monitor import pick_victim, process_rss

        with self._lock:
            cands = []
            for h in self._workers.values():
                if h.proc is None or h.proc.poll() is not None:
                    continue
                started = None
                if h.assigned_lease:
                    lease = self._leases.get(h.assigned_lease)
                    started = lease.granted_at if lease else None
                cands.append({"pid": h.proc.pid, "task_started_at": started,
                              "worker_id": h.worker_id, "addr": h.addr,
                              "handle": h})
        # Leases outlive tasks (they pipeline many), so the grant time
        # ranks by LEASE age. Ask each candidate what it is actually
        # running — task_state answers inline, so this stays fast even
        # under pressure. Actors keep the lease (creation) time: killing
        # an old actor loses state, and newest-first already deprioritizes
        # them. Probe failures fall back to the lease age.
        for c in cands:
            if c["addr"] is None or c["handle"].is_actor:
                continue
            try:
                client = RpcClient(tuple(c["addr"]), timeout=1.0, retry=1)
                try:
                    state = client.call("task_state", timeout=1.0)
                finally:
                    client.close()
                c["task_started_at"] = state.get("task_started_at")
            except Exception:
                pass
        victim = pick_victim(cands)
        if victim is None:
            return
        rss = process_rss(victim["pid"])
        msg = (f"Worker {victim['worker_id']} (pid {victim['pid']}) on node "
               f"{self.node_id} was killed due to the node running low on "
               f"memory: worker RSS {rss / 2**30:.2f} GB, node usage "
               f"{used / 2**30:.2f}/{total / 2**30:.2f} GB above threshold "
               f"{self._mem_monitor.threshold:.0%}. The task is retriable; "
               f"reduce its memory footprint or lower task parallelism.")
        self._oom_reasons[victim["worker_id"]] = msg
        try:
            self._gcs.call("kv_put", ns="oom_kill",
                           key=victim["worker_id"].encode(),
                           value=msg.encode(), timeout=5.0)
        except Exception:
            pass   # owners fall back to WorkerCrashedError
        try:
            os.kill(victim["pid"], signal.SIGKILL)
        except OSError:
            pass

    def _release_leases_of_lessee(self, lessee_id: str):
        with self._lock:
            doomed = [lease for lease in self._leases.values()
                      if lease.lessee_id == lessee_id]
            for lease in doomed:
                self._leases.pop(lease.lease_id, None)
                self._give_back(lease.resources)
                worker = lease.worker
                worker.assigned_lease = None
                # The dead lessee may have left a task mid-execution on this
                # worker; it is not safely reusable — kill it (reference
                # kills leased workers when the lease client disconnects).
                self._kill_worker(worker)

    def _reap_idle_workers(self):
        """Reap idle workers past `worker_pool_idle_timeout_s`, keeping
        the prestart watermark warm (reference: worker_pool.h
        TryKillingIdleWorkers — idle processes beyond the pool target
        are returned to the OS instead of lingering forever)."""
        from ray_tpu._private.config import get_config

        timeout_s = float(get_config("worker_pool_idle_timeout_s"))
        if timeout_s <= 0:
            return
        now = time.time()
        doomed = []
        with self._lock:
            keep = []
            for h in self._idle:
                if (len(self._idle) - len(doomed) > self._prestart_target
                        and now - h.idle_since > timeout_s):
                    doomed.append(h)
                else:
                    keep.append(h)
            if doomed:
                self._idle = keep
                for h in doomed:
                    self._kill_worker(h)

    def _gc_remote_lessee_leases(self):
        """Leases whose lessee lives on another node (spillback grants) are
        not covered by local worker reaping — ping the lessee and reclaim on
        failure."""
        with self._lock:
            remote = [(lease.lessee_id, lease.lessee_addr)
                      for lease in self._leases.values()
                      if lease.lessee_addr is not None
                      and lease.lessee_id not in self._workers]
        for lessee_id, addr in {(i, a) for i, a in remote}:
            # Reclaiming a LIVE lessee's leases kills its workers mid-task,
            # so this probe errs toward patience: the lessee answers ping
            # inline on its transport pump (no GIL-bound dispatch thread),
            # but a loaded single-core host can still stall a reply for
            # seconds — probe twice with generous timeouts before the
            # verdict.
            alive = False
            for _ in range(2):
                try:
                    client = RpcClient(addr, timeout=5.0, retry=1)
                    try:
                        client.call("ping", timeout=5.0)
                        alive = True
                        break
                    finally:
                        client.close()
                except Exception:
                    time.sleep(0.2)
            if not alive:
                self._release_leases_of_lessee(lessee_id)

    def _on_worker_exit(self, worker_id: str):
        with self._lock:
            handle = self._workers.pop(worker_id, None)
            if handle is None:
                return
            if handle in self._idle:
                self._idle.remove(handle)
            lease = None
            if handle.assigned_lease:
                lease = self._leases.pop(handle.assigned_lease, None)
            if lease:
                self._give_back(lease.resources)
        if not handle.is_actor:
            # retire the OOM-kill attribution for non-actor victims —
            # only the actor death path consumed it, so every task-worker
            # OOM kill leaked one reason string per worker id (RTL106
            # class: keyed by worker id, no removal on this death path)
            self._oom_reasons.pop(worker_id, None)
        # Leases this worker REQUESTED (as lessee) die with it: its
        # submission queues can never return them.
        self._release_leases_of_lessee(worker_id)
        self._log_monitor.mark_dead(worker_id)
        if handle.is_actor and handle.actor_id is not None:
            self._handle_actor_death(handle)
        self._pump_pending()

    def _handle_actor_death(self, handle: WorkerHandle):
        if self._stopped:
            # Node teardown: GCS sees our disconnect and re-drives restarts
            # on a surviving node — restarting here would race the shutdown.
            return
        reason = (self._oom_reasons.pop(handle.worker_id, None)
                  or "worker process died")
        try:
            decision = self._gcs.call_once("actor_failed",
                                      actor_id=handle.actor_id,
                                      reason=reason)
        except ConnectionLost:
            return
        if decision and decision.get("restart"):
            spec_key = handle.actor_id
            threading.Thread(
                target=self._restart_actor, args=(spec_key,),
                daemon=True).start()

    def _restart_actor(self, actor_id: bytes):
        if self._stopped:
            return
        blob = self._gcs.call("kv_get", ns="actor_spec", key=actor_id)
        if blob is None:
            return
        import pickle

        spec = pickle.loads(blob)
        try:
            self._create_actor_locally(actor_id, spec)
        except Exception:
            try:
                self._gcs.call_once("actor_failed", actor_id=actor_id,
                               reason="restart failed")
            except ConnectionLost:
                pass

    # ---- scheduling / leasing ----------------------------------------------

    def _fits(self, resources: dict) -> bool:
        return all(self.resources_avail.get(k, 0) + 1e-9 >= v
                   for k, v in resources.items())

    def _take(self, resources: dict):
        for k, v in resources.items():
            self.resources_avail[k] = self.resources_avail.get(k, 0) - v

    def _give_back(self, resources: dict):
        for k, v in resources.items():
            self.resources_avail[k] = self.resources_avail.get(k, 0) + v

    def _pick_spillback(self, resources: dict):
        """Pick an alive node whose totals fit the request, from a briefly
        cached GCS view (every queued lease/actor waiter re-checks spillback
        twice a second — one shared snapshot serves them all)."""
        now = time.time()
        cached = getattr(self, "_nodes_cache", None)
        if cached is not None and now - cached[0] < 0.5:
            nodes = cached[1]
        else:
            try:
                nodes = self._gcs.call("get_nodes")
            except ConnectionLost:
                return None
            self._nodes_cache = (now, nodes)
        best = None
        for n in nodes:
            if not n["Alive"] or n["NodeID"] == self.node_id:
                continue
            total = n["Resources"]
            if all(total.get(k, 0) >= v for k, v in resources.items()):
                if best is None:
                    best = n
        if best is None:
            return None
        return (best["NodeManagerAddress"], best["NodeManagerPort"])

    def rpc_request_worker_lease(self, conn, resources: dict,
                                 strategy: dict | None = None,
                                 grant_or_reject: bool = False,
                                 lessee: tuple | None = None):
        """Returns {"granted": {...}} | {"spillback": addr} | queues until
        resources free (long-poll: the reply is sent when granted)."""
        t0 = time.monotonic()
        strategy = strategy or {}
        job = strategy.get("job")
        # Placement-group leases consume the reserved bundle resources —
        # their job's quota was already enforced at PG admission (the
        # all-or-nothing gang check), so no second gate here.
        pg_id = strategy.get("placement_group_id")
        if pg_id is not None:
            return self._pg_lease(pg_id, strategy.get("bundle_index", -1),
                                  resources, lessee)
        node_hint = strategy.get("node_id")
        if node_hint and node_hint != self.node_id:
            target = self._node_addr(node_hint)
            if target is None:
                if not strategy.get("soft", False):
                    raise ValueError(f"node {node_hint} not found/alive")
            else:
                return {"spillback": target}
        spread = strategy.get("spread", False)
        if spread and not strategy.get("no_spill"):
            # SPREAD policy: coin-flip toward a remote capable node first
            # (reference: scheduling/policy/spread_scheduling_policy).
            target = self._pick_spillback(resources)
            if target is not None and os.urandom(1)[0] < 128:
                return {"spillback": target}
        # zero-resource leases (utility tasks like the PG-ready waiter)
        # consume nothing — parking them on the quota throttle would
        # hang control work without protecting any capacity
        consumes = any(v > 0 for v in resources.values())
        throttled = job is not None and consumes \
            and job in self._job_throttle
        if throttled:
            # lease-grant quota enforcement: the job is over its
            # cluster-wide quota — queue (don't grant, don't bounce
            # around the cluster) until the GCS clears the throttle
            from ray_tpu._private import telemetry as _tm

            if _tm.ENABLED:
                _tm.counter_inc("ray_tpu_quota_rejections_total",
                                tags={"job": job})
        elif self._try_reserve(resources):
            return self._observe_grant(t0,
                                       self._grant(resources, lessee, job))
        # no_spill: the caller exhausted its spillback hops on a saturated
        # cluster — queue here instead of bouncing (the reference keeps the
        # request in ClusterTaskManager's queue in this state).
        if not throttled and not strategy.get("no_spill"):
            target = self._pick_spillback(resources)
            if target is not None:
                return {"spillback": target}
        # Queue until local resources free up (reference: lease request stays
        # in ClusterTaskManager queue). Block this handler thread.
        deadline = time.time() + 300.0
        with self._lock:
            self._queued_demand.append(resources)
        try:
            warned = False
            next_spill_check = time.time() + 0.5
            while time.time() < deadline:
                if self._stopped:
                    raise ConnectionLost("raylet shutting down")
                if job is not None and consumes \
                        and job in self._job_throttle:
                    time.sleep(_LEASE_QUEUE_POLL)
                    continue   # quota throttle: park without reserving
                if self._try_reserve(resources):
                    return self._observe_grant(
                        t0, self._grant(resources, lessee, job))
                # Re-evaluate spillback while queued: a node that joined
                # (autoscaler, chaos replacement) after we started waiting
                # may be able to serve this request right now.
                if (not strategy.get("no_spill")
                        and time.time() >= next_spill_check):
                    target = self._pick_spillback(resources)
                    if target is not None:
                        return {"spillback": target}
                    next_spill_check = time.time() + 0.5
                if not self._feasible(resources) and not warned:
                    # Reference semantics: infeasible work stays PENDING
                    # (with a warning) rather than failing — the queued
                    # shape is the autoscaler's scale-up signal, and chaos
                    # recovery transiently empties resource types.
                    warned = True
                    print(f"[raylet {self.node_id[:8]}] warning: request "
                          f"{resources} is currently infeasible; waiting "
                          f"for capacity (autoscaler signal)", flush=True)
                time.sleep(_LEASE_QUEUE_POLL)
            raise TimeoutError(f"lease request {resources} timed out")
        finally:
            with self._lock:
                try:
                    self._queued_demand.remove(resources)
                except ValueError:
                    pass

    def _observe_grant(self, t0: float, reply: dict) -> dict:
        """Record the lease-grant latency (request arrival → local grant;
        spillbacks never reach here — they are another node's grant)."""
        from ray_tpu._private import telemetry as _tm

        if _tm.ENABLED:
            _tm.observe("ray_tpu_lease_grant_latency_seconds",
                        time.monotonic() - t0,
                        tags={"node_id": self.node_id})
        return reply

    def _try_reserve(self, resources: dict) -> bool:
        with self._lock:
            if self._fits(resources):
                self._take(resources)
                return True
            return False

    def _feasible(self, resources: dict) -> bool:
        if all(self.resources_total.get(k, 0) >= v
               for k, v in resources.items()):
            return True
        try:
            nodes = self._gcs.call("get_nodes")
        except ConnectionLost:
            return True
        return any(
            n["Alive"] and all(n["Resources"].get(k, 0) >= v
                               for k, v in resources.items())
            for n in nodes)

    def _grant(self, resources: dict, lessee: tuple | None = None,
               job: str | None = None) -> dict:
        """Resources must already be reserved via _try_reserve. Runs outside
        _lock because _pop_worker may block on worker registration."""
        try:
            worker = self._pop_worker()
        except Exception:
            with self._lock:
                self._give_back(resources)
            raise
        lease_id = uuid.uuid4().hex
        lease = Lease(lease_id, resources, worker, lessee, job)
        worker.assigned_lease = lease_id
        with self._lock:
            self._leases[lease_id] = lease
        grant = {"lease_id": lease_id,
                 "worker_id": worker.worker_id,
                 "worker_addr": worker.addr,
                 "node_id": self.node_id}
        # producer-side shape check: the lessee reads exactly these keys
        from ray_tpu._private.task_spec import validate_lease_grant

        validate_lease_grant(grant)
        return {"granted": grant}

    def _pg_lease(self, pg_id: bytes, bundle_index: int, resources: dict,
                  lessee: tuple | None = None):
        pg = self._gcs.call("get_placement_group", pg_id=pg_id)
        if pg is None or pg["State"] != "CREATED":
            raise ValueError(f"placement group {pg_id.hex()} not ready")
        nodes = pg["BundleNodes"]
        if bundle_index == -1:
            candidates = [n for n in nodes if n == self.node_id] or nodes
            target_node = candidates[0]
        else:
            target_node = nodes[bundle_index]
        if target_node != self.node_id:
            addr = self._node_addr(target_node)
            if addr is None:
                raise ValueError("placement group node died")
            return {"spillback": addr}
        return self._grant({}, lessee)  # bundle resources were pre-reserved

    def _node_addr(self, node_id: str):
        """Resolve one node's raylet address. Rides the O(1)
        ``get_node_addr`` RPC — the old full-table pull paid an
        O(cluster) payload per PG-target/spillback resolution, which at
        100 nodes made this the dominant GCS read traffic (soak
        round 12)."""
        try:
            addr = self._gcs.call("get_node_addr", node_id=node_id)
        except ConnectionLost:
            return None
        return tuple(addr) if addr else None

    def rpc_return_worker(self, conn, lease_id: str,
                          dispose: bool = False):
        with self._lock:
            lease = self._leases.pop(lease_id, None)
            if lease is None:
                return False
            self._give_back(lease.resources)
            worker = lease.worker
            worker.assigned_lease = None
            if dispose or len(self._idle) >= self._idle_cap:
                self._kill_worker(worker)
            elif worker.proc is not None and worker.proc.poll() is None:
                worker.idle_since = time.time()
                self._idle.append(worker)
        self._pump_pending()
        return True

    def _pump_pending(self):
        pass  # lease queue is handled by blocking handler threads

    def _kill_worker(self, worker: WorkerHandle):
        self._workers.pop(worker.worker_id, None)
        if worker.proc is not None and worker.proc.poll() is None:
            try:
                worker.proc.terminate()
            except OSError:
                pass

    # ---- actors -------------------------------------------------------------

    def rpc_create_actor(self, conn, actor_id: bytes, spec: dict):
        """Create the actor on this node or spill back. The spec's class blob
        lives in GCS KV under ns=actor_spec (function-table analog)."""
        resources = spec.get("resources", {"CPU": 1.0})
        strategy = spec.get("strategy") or {}
        pg_id = strategy.get("placement_group_id")
        if pg_id is not None:
            # A PENDING group just means its resources are currently held
            # (e.g. by other gang-scheduled trials): queue until the GCS
            # reserves the bundles, like the plain-resource path queues.
            deadline = time.time() + 300.0
            poll = _LEASE_QUEUE_POLL
            while True:
                pg = self._gcs.call("get_placement_group", pg_id=pg_id)
                if pg is None or pg["State"] == "REMOVED":
                    raise ValueError("placement group removed")
                if pg["State"] == "CREATED":
                    break
                if time.time() > deadline:
                    raise TimeoutError(
                        "placement group not ready within 300s")
                time.sleep(poll)
                poll = min(poll * 1.5, 0.5)   # back off: dozens of queued
                # creations at 50 polls/s each would hammer the GCS
            idx = strategy.get("bundle_index", -1)
            target = (pg["BundleNodes"][idx] if idx >= 0
                      else next((n for n in pg["BundleNodes"]
                                 if n == self.node_id),
                                pg["BundleNodes"][0]))
            if target != self.node_id:
                addr = self._node_addr(target)
                if addr is None:
                    raise ValueError("placement group node died")
                return {"spillback": addr}
            return self._create_actor_locally(actor_id, spec, reserved={})
        node_hint = strategy.get("node_id")
        if node_hint and node_hint != self.node_id:
            addr = self._node_addr(node_hint)
            if addr is None and not strategy.get("soft", False):
                raise ValueError(f"node {node_hint} not found/alive")
            if addr is not None:
                return {"spillback": addr}
        if self._try_reserve(resources):
            return self._create_actor_locally(actor_id, spec,
                                              reserved=resources)
        if not strategy.get("no_spill"):
            target = self._pick_spillback(resources)
            if target is not None:
                return {"spillback": target}
        # queue locally until feasible
        deadline = time.time() + 300.0
        with self._lock:
            self._queued_demand.append(resources)
        try:
            next_spill_check = time.time() + 0.5
            while time.time() < deadline:
                if self._stopped:
                    raise ConnectionLost("raylet shutting down")
                if self._try_reserve(resources):
                    return self._create_actor_locally(actor_id, spec,
                                                      reserved=resources)
                if not strategy.get("no_spill") and \
                        time.time() >= next_spill_check:
                    target = self._pick_spillback(resources)
                    if target is not None:
                        return {"spillback": target}
                    next_spill_check = time.time() + 0.5
                time.sleep(_LEASE_QUEUE_POLL)
            raise TimeoutError(
                "actor creation timed out waiting for resources")
        finally:
            with self._lock:
                try:
                    self._queued_demand.remove(resources)
                except ValueError:
                    pass

    def _create_actor_locally(self, actor_id: bytes, spec: dict,
                              reserved: dict | None = None):
        """`reserved` are resources already taken via _try_reserve; pass {}
        for placement-group bundles (pre-reserved at bundle commit)."""
        if reserved is None:
            resources = spec.get("resources", {"CPU": 1.0})
            deadline = time.time() + 300.0
            while not self._try_reserve(resources):
                if time.time() > deadline:
                    raise TimeoutError("actor restart resource wait")
                time.sleep(_LEASE_QUEUE_POLL)
            reserved = resources
        resources = reserved
        worker = None
        try:
            worker = self._pop_worker()
            worker.is_actor = True
            worker.actor_id = actor_id
            lease_id = uuid.uuid4().hex
            lease = Lease(lease_id, resources, worker)
            worker.assigned_lease = lease_id
            with self._lock:
                self._leases[lease_id] = lease
            # Tell the worker to become this actor.
            client = RpcClient(worker.addr, timeout=60.0)
            try:
                from ray_tpu._private.config import get_config

                # Under a creation storm on a starved core a worker's
                # become_actor (class-blob fetch + import) legitimately
                # waits behind dozens of peers, so this scales with the
                # storm-sized driver budget — but at 3/4 of it, leaving
                # the driver's outer create_actor call margin to receive
                # our reply (equal budgets would let the driver give up
                # and mark the actor failed moments before the raylet
                # succeeds, leaking the bound worker).
                outer = float(get_config("actor_creation_rpc_timeout_s"))
                client.call("become_actor", actor_id=actor_id, spec=spec,
                            timeout=0.75 * outer)
            finally:
                client.close()
            self._log_monitor.set_actor_name(
                worker.worker_id,
                spec.get("name") or spec.get("class_name"))
        except BaseException:
            # Failed creation must not leak the reservation (or the worker —
            # a half-initialized actor process is not reusable). If the
            # worker died mid-creation, _on_worker_exit may have already
            # popped the lease and returned the resources — only give back
            # when we pop the lease ourselves (or never registered one).
            with self._lock:
                if worker is None or worker.assigned_lease is None:
                    self._give_back(resources)
                elif self._leases.pop(worker.assigned_lease,
                                      None) is not None:
                    self._give_back(resources)
            if worker is not None:
                worker.is_actor = False
                with self._lock:
                    self._kill_worker(worker)
            raise
        return {"granted": {"worker_id": worker.worker_id,
                            "worker_addr": worker.addr,
                            "node_id": self.node_id,
                            "lease_id": lease_id}}

    def rpc_kill_actor(self, conn, actor_id: bytes, no_restart: bool = True):
        with self._lock:
            handle = next((h for h in self._workers.values()
                           if h.actor_id == actor_id), None)
        if handle is None:
            return False
        if no_restart:
            handle.is_actor = False   # suppress restart path
            try:
                self._gcs.call("actor_exited", actor_id=actor_id)
            except ConnectionLost:
                pass
        if handle.proc is not None:
            try:
                handle.proc.send_signal(signal.SIGKILL)
            except OSError:
                pass
        else:
            # actor hosted in an external process (driver) — push a kill rpc
            try:
                c = RpcClient(handle.addr, timeout=5.0)
                c.push("exit_worker")
                c.close()
            except ConnectionLost:
                pass
        return True

    # ---- object plane -------------------------------------------------------

    def rpc_fetch_object(self, conn, object_id: bytes):
        """Whole-object pull (kept for small objects / compatibility)."""
        buf = self.store.get(object_id)
        if buf is None:
            return None
        try:
            return buf.to_bytes()
        finally:
            buf.release()

    def rpc_fetch_object_chunk(self, conn, object_id: bytes, offset: int,
                               length: int):
        """Chunked pull (reference: ObjectManager chunked gRPC transfer,
        object_manager.h + push_manager.h:29). Returns {"size", "data"} or
        None if the object isn't here (pullers retry elsewhere)."""
        buf = self.store.get(object_id)
        if buf is None:
            return None
        try:
            mv = buf.memoryview()
            return {"size": len(mv), "data": bytes(mv[offset:offset + length])}
        finally:
            buf.release()

    def rpc_store_stats(self, conn):
        return self.store.stats()

    def rpc_list_store_objects(self, conn):
        """Per-node object inventory (`ray-tpu memory` source). Under the
        owner-based directory there is no central location table — the
        state API unions these per-node rows instead."""
        return [{"ObjectID": oid.hex(), "Size": size,
                 "Locations": [self.node_id], "Lost": False}
                for oid, size in self.store.list_objects()]

    def rpc_node_info(self, conn):
        with self._lock:
            return {
                "node_id": self.node_id,
                "resources_total": dict(self.resources_total),
                "resources_available": dict(self.resources_avail),
                "num_workers": len(self._workers),
                "num_idle": len(self._idle),
                "num_leases": len(self._leases),
            }

    def rpc_list_leases(self, conn):
        """Active leases = the raylet-level view of running work (state API
        `list tasks` source; reference: NodeManagerService GetNodeStats)."""
        with self._lock:
            return [{
                "lease_id": lease.lease_id,
                "node_id": self.node_id,
                "resources": dict(lease.resources),
                "worker_id": lease.worker.worker_id,
                "worker_pid": lease.worker.proc.pid,
                "worker_addr": lease.worker.addr,
                "is_actor": lease.worker.is_actor,
            } for lease in self._leases.values()]

    def rpc_list_workers(self, conn):
        with self._lock:
            return [{
                "worker_id": w.worker_id,
                "node_id": self.node_id,
                "pid": w.proc.pid,
                "state": ("actor" if w.is_actor
                          else "leased" if w.assigned_lease else "idle"),
                "actor_id": w.actor_id.hex() if w.actor_id else None,
            } for w in self._workers.values()]

    def _fanout_workers(self, method: str) -> list:
        """Collect per-worker state (profiling spans, metrics) from every
        registered worker process on this node."""
        from ray_tpu._private.protocol import RpcClient

        with self._lock:
            addrs = [w.addr for w in self._workers.values()
                     if w.addr is not None]
        out = []
        for addr in addrs:
            try:
                c = RpcClient(tuple(addr), timeout=5.0)
                try:
                    out.extend(c.call(method))
                finally:
                    c.close()
            except Exception:
                continue
        return out

    def rpc_profile_events(self, conn):
        return self._fanout_workers("profile_events")

    def rpc_trace_spans(self, conn):
        return self._fanout_workers("trace_spans")

    def rpc_metrics_snapshot(self, conn):
        """This node's metrics: the raylet process's own registry (the
        scheduler gauges/histograms live HERE) plus every registered
        worker's. aggregate_snapshots dedups by (node, pid) when the
        raylet shares a process with the driver (in-process clusters)."""
        from ray_tpu.util.metrics import registry_snapshot

        return registry_snapshot() + self._fanout_workers(
            "metrics_snapshot")

    def rpc_events_snapshot(self, conn):
        """This node's structured runtime events: the raylet process's own
        ring plus every registered worker's (the state API dedups by
        (node, pid, seq) — in-process clusters share a pid with the
        driver)."""
        from ray_tpu._private import events as _events

        return _events.snapshot() + self._fanout_workers("events_snapshot")

    def rpc_step_records(self, conn):
        """Step-anatomy exports from every registered worker on this
        node (the raylet itself runs no train loop — its own export
        would always be empty)."""
        return self._fanout_workers("step_records")

    def rpc_blackbox_snapshot(self, conn):
        """Flight-recorder windows: the raylet process's own black box
        (its event ring and metrics matter in a post-mortem) plus every
        registered worker's. The dump path dedups by (node, pid)."""
        from ray_tpu._private import flight_recorder

        snap = flight_recorder.local_snapshot()
        own = [snap] if snap else []
        return own + self._fanout_workers("blackbox_snapshot")

    def rpc_memory_snapshot(self, conn):
        """Memory-anatomy ledgers: the raylet process's own (its store
        deletes and dropped frees count HERE) plus every registered
        worker's. summarize_memory dedups by (node, pid)."""
        from ray_tpu._private import memory_anatomy

        snap = memory_anatomy.local_snapshot(top_k=10)
        snap["node"] = self.node_id
        return [snap] + self._fanout_workers("memory_snapshot")

    def rpc_ping(self, conn):
        return "pong"

    def rpc_dump_stacks(self, conn, wait_s: float = 0.6):
        """`ray stack` analog (reference: scripts.py `ray stack` shells
        out to py-spy on every worker): workers register faulthandler on
        SIGUSR1 (worker_main), so signaling them makes each dump every
        thread's Python stack into its own stderr log; this collects the
        fresh tails. No py-spy dependency — the dumps come from the
        interpreter itself."""
        with self._lock:
            targets = [(h.worker_id, h.proc.pid)
                       for h in self._workers.values()
                       if h.proc is not None and h.proc.poll() is None]
        marks = {}
        for worker_id, _pid in targets:
            err = os.path.join(self.logs_dir, f"worker-{worker_id}.err")
            try:
                marks[worker_id] = os.path.getsize(err)
            except OSError:
                # no file yet — mark its CURRENT end once it appears, so
                # historical stderr is never mistaken for the dump
                marks[worker_id] = None
        for _worker_id, pid in targets:
            try:
                os.kill(pid, signal.SIGUSR1)
            except OSError:
                pass
        out = {}
        deadline = time.monotonic() + max(wait_s, 0.1)
        pending = dict(targets)
        while pending and time.monotonic() < deadline:
            time.sleep(0.1)
            for worker_id, pid in list(pending.items()):
                err = os.path.join(self.logs_dir,
                                   f"worker-{worker_id}.err")
                mark = marks[worker_id]
                try:
                    size = os.path.getsize(err)
                except OSError:
                    continue
                if mark is None:
                    marks[worker_id] = mark = size
                    continue
                if size <= mark:
                    continue
                with open(err, "rb") as f:
                    f.seek(mark)
                    dump = f.read().decode(errors="replace")
                out[worker_id] = {"pid": pid, "node_id": self.node_id,
                                  "stack": dump[-100_000:]}
                del pending[worker_id]
        for worker_id, pid in pending.items():   # no dump in time
            out[worker_id] = {"pid": pid, "node_id": self.node_id,
                              "stack": ""}
        return out

    def rpc_physical_stats(self, conn):
        """Reporter-agent sample for this node (reference:
        dashboard/modules/reporter/reporter_agent.py:296 — here the
        raylet plays the per-node agent; the dashboard fans this out at
        /api/reporter)."""
        from ray_tpu.dashboard.reporter import collect_stats

        with self._lock:
            pids = [h.proc.pid for h in self._workers.values()
                    if h.proc is not None and h.proc.poll() is None]
        stats = collect_stats(pids)
        stats["node_id"] = self.node_id
        return stats

    # ---- lifecycle ----------------------------------------------------------

    def stop(self, kill_workers: bool = True):
        self._stopped = True
        self._mem_monitor.stop()
        try:
            self._log_monitor.stop()   # final drain rides the live GCS conn
        except Exception:
            pass
        # Drop the GCS connection first: node-death handling (including actor
        # failover to surviving nodes) starts before local worker reaping can
        # misreport deaths as per-worker failures.
        try:
            self._gcs.close()
        except Exception:
            pass
        if kill_workers:
            with self._lock:
                workers = list(self._workers.values())
            for h in workers:
                if h.proc is not None and h.proc.poll() is None:
                    try:
                        h.proc.terminate()
                    except OSError:
                        pass
            deadline = time.time() + 2.0
            for h in workers:
                if h.proc is None:
                    continue
                remaining = max(0.05, deadline - time.time())
                try:
                    h.proc.wait(remaining)
                except subprocess.TimeoutExpired:
                    try:
                        h.proc.kill()
                    except OSError:
                        pass
        self._server.stop()
        try:
            self.store.close()
        except Exception:
            pass


def main():  # pragma: no cover - exercised as a subprocess
    """`python -m ray_tpu._private.raylet` with env-provided config."""
    gcs_host, gcs_port = os.environ["RAY_TPU_GCS_ADDR"].split(":")
    resources = None
    if os.environ.get("RAY_TPU_RESOURCES"):
        import json

        resources = json.loads(os.environ["RAY_TPU_RESOURCES"])
    raylet = Raylet(
        (gcs_host, int(gcs_port)),
        node_id=os.environ.get("RAY_TPU_NODE_ID"),
        port=int(os.environ.get("RAY_TPU_RAYLET_PORT", "0")),
        resources=resources,
        store_size=int(os.environ.get("RAY_TPU_STORE_SIZE",
                                      str(256 * 1024 * 1024))),
        session_dir=os.environ.get("RAY_TPU_SESSION_DIR"),
    )
    print(f"RAYLET_READY {raylet.addr[0]}:{raylet.addr[1]} {raylet.node_id}",
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        raylet.stop()


if __name__ == "__main__":  # pragma: no cover
    main()
