"""Cluster-scale soak harness: O(100) simulated raylets, one process.

The control plane has only ever seen single-digit raylets; the Ray
paper's GCS/distributed-scheduler design (PAPERS.md, arXiv:1712.05889
§4) is sized for thousands. This module stands up production node
counts CHEAPLY: every simulated raylet holds REAL RPC connections to a
real GCS (registration, heartbeats via ``report_resources`` pushes, a
conn-push ``nodes`` subscription, and a long-poll death-watch
subscription riding the same ``Subscriber``/``psub_*`` machinery real
consumers use) — but spawns no worker processes and runs no object
store, so one driver process soaks a 100-node control plane.

Chaos rides the fault-injection DSL's node-level primitives
(``kill_node`` / ``flap_node``, fault_injection.py): the DRIVER LOOP
consults ``FaultInjector.on_node(tag, method)`` for every node at
deterministic tick boundaries, so a seeded schedule like
``kill_node:*.mass_kill:p0.1`` kills a deterministic ~10% of the fleet
simultaneously, and two runs with the same seed produce byte-identical
chaos journals (``journal_text()`` — the reproducibility artifact; all
wall-clock measurements live in ``metrics``, never in the journal).

What the soak PROVES (the pass criteria asserted by
``tests/test_zz_soak.py`` and measured by ``benchmarks/soak_bench.py``):

- **no lost accepted leases** — every lease a surviving raylet accepted
  is still in its ledger AND durably recorded in GCS KV after the storm
  (kv writes ride the retry plane across the GCS restart);
- **no permanently dead subscriptions** — every death watch either saw
  every death through the feed or reconverged via snapshot-resync /
  rejoin reconciliation (``deaths_seen`` covers the killed set);
- **bounded reconvergence** — after the chaos window the GCS's alive
  set equals the survivor set and a probe message published on the
  feed reaches every survivor, within a measured window.

The SERVE plane rides the same harness (``SimServeApp`` /
``sim_serve_deployment_cls``): each app is the REAL Serve
``_DeploymentState`` FSM (controller.py — reconcile, autoscaling,
capacity gangs, preemption-warning drains all production code) with the
replica actors stubbed to inert slots, driven by a deterministic
open-loop request model. The soak's serving acceptance — zero lost
accepted requests through preemption storms — is journaled the same
way (``serve_final <app> ... lost=0``).
"""
from __future__ import annotations

import collections
import os
import subprocess
import sys
import threading
import time

from ray_tpu._private import fault_injection as _fi
from ray_tpu._private.protocol import (ConnectionLost,
                                       ReconnectingRpcClient, RpcClient)

# sim raylets advertise tiny fake endpoints; nothing ever dials them
_FAKE_PORT_BASE = 20000


class SimRaylet:
    """One lightweight simulated raylet: real GCS connections, no
    workers. Driven synchronously by the cluster's tick loop."""

    def __init__(self, cluster: "SimCluster", index: int):
        self.cluster = cluster
        self.index = index
        self.tag = f"sim{index:03d}"
        self.node_id = f"simnode-{index:03d}"
        self.resources = {"CPU": 4.0}
        self.state = "new"              # up / flapping / dead
        self._rejoin_at_tick: int | None = None
        self._gcs: ReconnectingRpcClient | None = None
        self._watch = None              # ActorDeathWatch (prod code path)
        self._sub = None                # nodes-channel long-poll Subscriber
        self._sub_rpc = None
        self._lock = threading.Lock()
        # node_id -> monotonic time this raylet FIRST observed the death
        # (conn-push, long-poll feed, resync snapshot, or rejoin
        # reconciliation — whichever lands first)
        self.deaths_seen: dict[str, float] = {}
        self.actor_deaths_seen: set = set()
        self.probes_seen: set = set()
        self.accepted_leases: dict[str, dict] = {}
        self._lease_counter = 0
        self._watching_actors = False
        # PG bundle reservations on this node (pg_id -> summed resources;
        # released on the `removed` push) — the real raylet's
        # _pg_reserved analog, so the availability this node gossips
        # reflects committed gangs and the multi-tenant scheduler packs
        # against reality instead of forever-full nodes
        self._pg_reserved: dict[bytes, dict] = {}

    # ------------------------------------------------------------ lifecycle
    def start(self):
        self._gcs = ReconnectingRpcClient(
            self.cluster.gcs_addr, timeout=15.0,
            on_push=self._on_push,
            on_reconnect=self._replay_registration)
        self._replay_registration(self._gcs)
        from ray_tpu._private.pubsub import Subscriber

        self._sub_rpc = ReconnectingRpcClient(self.cluster.gcs_addr,
                                              timeout=15.0)
        self._sub = Subscriber(self._sub_rpc,
                               poll_timeout=self.cluster.poll_timeout,
                               auto_resync=True)
        self._sub.subscribe("nodes", self._on_feed)
        self.state = "up"

    def _replay_registration(self, gcs):
        """Initial registration AND the reconnect replay after a GCS
        restart (the same contract as Raylet._replay_gcs_registration)."""
        gcs.call("register_node", node_id=self.node_id,
                 addr=("127.0.0.1", _FAKE_PORT_BASE + self.index),
                 resources=self.resources,
                 meta={"hostname": self.tag, "sim": True})
        gcs.call("subscribe", channels=["nodes", "placement_groups"])

    def _teardown_connections(self):
        for c in (self._watch, self._sub):
            if c is not None:
                try:
                    c.stop()
                except Exception:
                    pass
        for c in (self._sub_rpc, self._gcs):
            if c is not None:
                try:
                    c.close()
                except Exception:
                    pass
        self._watch = self._sub = self._sub_rpc = self._gcs = None

    def kill(self):
        """kill_node: tear down every connection (the GCS observes the
        disconnect and marks this node dead) and never re-register."""
        self.state = "dead"
        self._teardown_connections()

    def flap(self, down_ticks: int):
        """flap_node: disconnect now, re-register after ``down_ticks``
        driver ticks."""
        self.state = "flapping"
        self._rejoin_at_tick = self.cluster.tick_count + max(1, down_ticks)
        self._teardown_connections()
        with self._lock:
            # the GCS reschedules our gangs onto survivors while we are
            # away; a rejoin must not keep gossiping phantom
            # reservations for bundles that moved
            self._pg_reserved.clear()

    def _rejoin(self):
        self.start()
        if self._watching_actors:
            # flap() tore the death watch down with the rest of the
            # connections — a rejoined node must reopen it or the
            # harness itself would carry the dead-subscription defect
            # the soak exists to catch
            self.watch_deaths_of_actors()
        # reconcile the cluster view missed while away: deaths that
        # happened during the outage are in the node table, not the
        # (fresh) mailbox
        try:
            for n in self._gcs.call("get_nodes"):
                if not n["Alive"]:
                    self._note_death(n["NodeID"])
        except Exception:
            pass

    # ------------------------------------------------------------- feeds
    def _note_death(self, node_id: str):
        with self._lock:
            self.deaths_seen.setdefault(node_id, time.monotonic())

    def _on_push(self, payload):
        """Conn-push plane (the raylet's GCS reader thread analog)."""
        try:
            method, kwargs = payload
        except Exception:
            return
        if method == "pubsub" and kwargs.get("channel") == "nodes":
            self._consume_nodes_message(kwargs.get("message"))
        elif method == "pubsub" and \
                kwargs.get("channel") == "placement_groups":
            self._consume_pg_message(kwargs.get("message"))

    def _consume_pg_message(self, msg):
        if not isinstance(msg, dict):
            return
        if msg.get("event") == "created":
            reserved: dict = {}
            for bundle, nid in zip(msg.get("bundles", ()),
                                   msg.get("bundle_nodes", ())):
                if nid == self.node_id:
                    for k, v in bundle.items():
                        reserved[k] = reserved.get(k, 0.0) + v
            if reserved:
                with self._lock:
                    self._pg_reserved[msg["pg_id"]] = reserved
        elif msg.get("event") == "removed":
            with self._lock:
                self._pg_reserved.pop(msg.get("pg_id"), None)

    def available(self) -> dict:
        with self._lock:
            out = dict(self.resources)
            for reserved in self._pg_reserved.values():
                for k, v in reserved.items():
                    out[k] = out.get(k, 0.0) - v
        return out

    def _on_feed(self, msg):
        """Long-poll plane (Subscriber callback, incl. resync)."""
        self._consume_nodes_message(msg)

    def _consume_nodes_message(self, msg):
        if not isinstance(msg, dict):
            return
        event = msg.get("event")
        if event == "dead":
            self._note_death(msg.get("node_id"))
        elif event == "batch_dead":
            for node_id in msg.get("node_ids", ()):
                self._note_death(node_id)
        elif event == "probe":
            with self._lock:
                self.probes_seen.add(msg.get("n"))
        elif event == "resync":
            for row in (msg.get("snapshot") or ()):
                if isinstance(row, dict) and not row.get("alive", True):
                    self._note_death(row.get("node_id"))

    # ------------------------------------------------------------- driving
    def tick(self):
        """One driver-loop step: consult the chaos schedule at this
        node's deterministic send boundary, then heartbeat."""
        if self.state == "dead":
            return
        if self.state == "flapping":
            if self.cluster.tick_count >= (self._rejoin_at_tick or 0):
                self._rejoin()
                self.cluster._journal(f"rejoin {self.tag}")
            return
        for action, param_s in self._consult("heartbeat"):
            if action == "kill_node":
                self.cluster._journal(f"kill_node {self.tag}")
                self.kill()
                return
            if action == "flap_node":
                ticks = max(1, int(round(
                    param_s / self.cluster.tick_interval)))
                self.cluster._journal(
                    f"flap_node {self.tag} down_ticks={ticks}")
                self.flap(ticks)
                return
        try:
            self._gcs.push("report_resources", node_id=self.node_id,
                           available=self.available(),
                           busy=len(self.accepted_leases))
        except Exception:   # ConnectionLost while the GCS restarts —
            pass            # the next tick's push heals the channel

    def consult_mass(self, method: str) -> list[tuple[str, float]]:
        """Driver-designated boundary (e.g. one ``mass_kill`` consult per
        node at the same tick — the simultaneous-failure schedule)."""
        if self.state != "up":
            return []
        return self._consult(method)

    def _consult(self, method: str):
        inj = _fi.ACTIVE
        return inj.on_node(self.tag, method) if inj is not None else []

    def accept_lease(self) -> str:
        """Accept one simulated lease: ledger entry locally + a durable
        GCS KV record (the write rides the retry plane, so a lease
        accepted during a GCS restart is retried, not lost)."""
        self._lease_counter += 1
        lease_id = f"{self.tag}-L{self._lease_counter:04d}"
        self.accepted_leases[lease_id] = {"CPU": 1.0}
        self._gcs.call("kv_put", ns="soak_leases",
                       key=lease_id.encode(), value=self.tag.encode())
        return lease_id

    def watch_deaths_of_actors(self):
        """Open a production ``watch_actor_deaths`` against the harness
        GCS (the PR 5 round-4 heal path, finally at fleet scale)."""
        from ray_tpu._private.pubsub import watch_actor_deaths

        def _on_death(actor_id, reason):
            with self._lock:
                self.actor_deaths_seen.add(actor_id)

        self._watch = watch_actor_deaths(
            _on_death, poll_timeout=self.cluster.poll_timeout,
            gcs_addr=self.cluster.gcs_addr)
        self._watching_actors = True

    def stop(self):
        if self.state != "dead":
            self.state = "dead"
            self._teardown_connections()


class _SimHandle:
    """Inert replica-actor stand-in (the sim plane spawns no workers)."""

    _actor_id = b""


_SERVE_DEP_CLS = None


def sim_serve_deployment_cls():
    """The REAL Serve ``_DeploymentState`` (serve/_private/controller.py)
    specialized for the harness: reconcile, autoscaling, capacity-gang
    creation/tracking, preemption-warning drains and the
    drain-through-warning scale-down all run UNMODIFIED against the
    harness GCS; only the worker-runtime edges (actor start/stop, health
    checks, replica metrics) are stubbed. Lazy so node-only soaks never
    load the serve plane."""
    global _SERVE_DEP_CLS
    if _SERVE_DEP_CLS is not None:
        return _SERVE_DEP_CLS
    from ray_tpu.serve._private import controller as _ctl

    class SimServeDeployment(_ctl._DeploymentState):
        """A replica is an inert slot: its only substance is the
        capacity gang the base class creates and tracks in the job
        plane, which is exactly the surface the soak exercises."""

        def _start_replica(self):
            seq = getattr(self, "_sim_seq", 0) + 1
            self._sim_seq = seq
            rid = f"{self.dep_id}#s{seq:04d}"
            used = {r.slot for r in self.replicas}
            slot = next(i for i in range(len(self.replicas) + 1)
                        if i not in used)
            pg_id, requested_ts = self._create_capacity_pg(slot)
            r = _ctl._Replica(rid, f"SIM::{rid}", _SimHandle(), None, slot)
            r.capacity_pg_id = pg_id
            r.pg_requested_ts = requested_ts
            self.replicas.append(r)

        def _check_ready(self, r):
            # readiness is pure capacity here: reconcile() already gates
            # STARTING tenant replicas on the gang turning CREATED
            return "ready"

        def _check_drained(self, r):
            return True

        def _begin_stop(self, r, deadline_s=None):
            # drains complete next tick — well inside any grace window,
            # so a warned gang is always removed PRE-fire (the
            # controlled-drain escape hatch the scale-down path proves)
            r.state = _ctl.STOPPING
            r.drain_ref = None
            r.drain_deadline = time.monotonic()

        def _health_checks(self):
            return False

        def _poll_replica_metrics(self):
            pass

        def _kill(self, r):
            if r.capacity_pg_id is not None:
                try:
                    self._gcs_call("remove_placement_group",
                                   pg_id=r.capacity_pg_id)
                except Exception:
                    pass
                r.capacity_pg_id = None
            if r in self.replicas:
                self.replicas.remove(r)

    _SERVE_DEP_CLS = SimServeDeployment
    return SimServeDeployment


class SimServeApp:
    """One Serve app as a first-class job-plane tenant, driven by the
    real controller FSM (``sim_serve_deployment_cls``) under a
    deterministic open-loop request model.

    Request model (app-level aggregate, one FIFO): each tick admits a
    deterministic arrival cohort (``base_rate`` x the active spike
    multiplier) bounded by ``max_queued_per_replica`` per live replica —
    overflow is SHED at admission, before acceptance — then serves up to
    ``service_rate`` x live replicas FIFO. A live replica is RUNNING and
    not preemption-warned/draining, so a warning instantly removes that
    slot's throughput (warned = already-lost capacity) while every
    accepted request stays queued until served: lost accepted requests
    are structurally zero EXACTLY when the drain/requeue story holds,
    and the final count is journaled (``serve_final ... lost=0``).

    Chaos composes through the fault DSL's job plane: every tick
    consults ``preempt_job`` rules once per replica SLOT over the fixed
    range ``0..max_replicas-1`` (fixed so injector counters stay
    deterministic regardless of how many replicas currently exist), with
    ``job=<slot tag>`` and ``tags={app job, dep tag}`` — so one rule
    scoped to the app's job warns a seed-deterministic subset of slots,
    and a fired rule issues the real GCS ``preempt_job`` narrowed by
    ``pg_name`` to that slot's capacity gang.
    """

    def __init__(self, cluster: "SimCluster", name: str, job: str, *,
                 priority: int = 10, quota: dict | None = None,
                 base_rate: int = 1000, service_rate: int = 400,
                 min_replicas: int = 1, max_replicas: int = 4,
                 capacity_cpu: float = 2.0,
                 max_queued_per_replica: int = 4000,
                 spikes: tuple = ()):
        from ray_tpu.serve._private.constants import (deployment_id,
                                                      dep_tag, slot_tag)
        from ray_tpu.serve._private.long_poll import LongPollHost

        self.cluster = cluster
        self.name = name
        self.job = job
        self.base_rate = int(base_rate)
        self.service_rate = int(service_rate)
        self.max_replicas = int(max_replicas)
        self.spikes = tuple(spikes)   # (start_tick, end_tick, multiplier)
        self.dep_id = deployment_id(name, "main")
        self._dep_tag = dep_tag(self.dep_id)
        self._slot_tag = slot_tag
        cluster.register_job(job, quota=quota, priority=priority)
        spec = {
            "name": "main",
            "user_callable": None,
            "init_args": (),
            "init_kwargs": {},
            "version": "1",
            "config": {
                "max_ongoing_requests": int(service_rate),
                "max_queued_requests": int(max_queued_per_replica),
                "graceful_shutdown_timeout_s": 1.0,
                # sim replicas have no health surface; the capacity poll
                # is the liveness signal
                "health_check_period_s": 3600.0,
                "autoscaling_config": {
                    "min_replicas": int(min_replicas),
                    "max_replicas": int(max_replicas),
                    # demand is (admitted + backlog) per tick; one
                    # replica clears service_rate of it per tick
                    "target_ongoing_requests": float(service_rate),
                    "upscale_delay_s": 0.2,
                    "downscale_delay_s": 0.6,
                    "metrics_interval_s": 0.1,
                },
                "ray_actor_options": {"num_cpus": float(capacity_cpu)},
            },
        }
        self.ds = sim_serve_deployment_cls()(
            self.dep_id, spec, LongPollHost(), job=job,
            gcs_call=cluster.gcs_call)
        self.queue: collections.deque = collections.deque()
        self._queued = 0
        self.offered = self.accepted = self.served = self.shed = 0
        self.latency_hist: dict[int, int] = {}   # latency_ticks -> count
        self.max_live_seen = 0
        cluster._journal(
            f"serve_app {name} job={job} rate={self.base_rate} "
            f"svc={self.service_rate} replicas={int(min_replicas)}.."
            f"{self.max_replicas} spikes={list(self.spikes)}")

    # ------------------------------------------------------------- driving
    def live_replicas(self) -> int:
        return sum(1 for r in self.ds.replicas
                   if r.state == "RUNNING" and not r.warned
                   and not r.drain_requested)

    def _consult_chaos(self):
        inj = _fi.ACTIVE
        if inj is None:
            return
        for slot in range(self.max_replicas):
            stag = self._slot_tag(self.dep_id, slot)
            for action, param_s in inj.on_job(
                    stag, "serve_tick",
                    tags=frozenset((self.job, self._dep_tag))):
                if action != "preempt_job":
                    continue
                self.cluster._journal(f"preempt_slot {stag} (serve_tick)")
                try:
                    self.cluster.gcs_call("preempt_job", name=self.job,
                                          grace_s=param_s, pg_name=stag)
                except Exception:
                    pass

    def tick(self):
        t = self.cluster.tick_count
        mult = 1.0
        for start, end, m in self.spikes:
            if t == start:
                self.cluster._journal(f"spike_begin {self.name} x{m:g}")
            elif t == end:
                self.cluster._journal(f"spike_end {self.name}")
            if start <= t < end:
                mult = m
        arrivals = int(round(self.base_rate * mult))
        self.offered += arrivals
        self._consult_chaos()
        n_live = self.live_replicas()
        self.max_live_seen = max(self.max_live_seen, n_live)
        # admission: bound the queue per LIVE replica; only THIS tick's
        # arrivals can be shed — accepted work is never dropped later,
        # whatever happens to the replicas backing it
        room = self.ds.config.max_queued_requests * max(1, n_live)
        admitted = min(arrivals, max(0, room - self._queued))
        self.shed += arrivals - admitted
        self.accepted += admitted
        if admitted:
            self.queue.append([t, admitted])
            self._queued += admitted
        # serve FIFO up to this tick's live capacity
        cap = n_live * self.service_rate
        while cap > 0 and self.queue:
            cohort_t, cohort_n = self.queue[0]
            take = cohort_n if cohort_n <= cap else cap
            # clamp into an overflow bucket: one key per latency value,
            # bounded even under a pathological standing backlog
            lat = min(t - cohort_t + 1, 10_000)
            self.latency_hist[lat] = self.latency_hist.get(lat, 0) + take
            self.served += take
            self._queued -= take
            cap -= take
            if take == cohort_n:
                self.queue.popleft()
            else:
                self.queue[0][1] -= take
        # push the demand signal the real routers would (queued +
        # in-flight at the handle layer) and run the real reconcile
        self.ds.handle_metrics["sim-router"] = (
            float(admitted + self._queued), time.monotonic())
        self.ds.reconcile()

    # ------------------------------------------------------------- results
    def latency_pct(self, q: float) -> float | None:
        """Weighted served-latency percentile in SECONDS (ticks x
        tick_interval); wall-clock-dependent — metrics only."""
        total = sum(self.latency_hist.values())
        if not total:
            return None
        target = q * (total - 1)
        seen = 0
        for lat in sorted(self.latency_hist):
            seen += self.latency_hist[lat]
            if seen - 1 >= target:
                return lat * self.cluster.tick_interval
        return max(self.latency_hist) * self.cluster.tick_interval

    def finalize(self) -> dict:
        """End-of-phase accounting. The deterministic facts (offered
        total, the lost-accepted count — zero on a correct drain path)
        are journaled; throughput/latency/scale numbers are wall-clock
        racing and go to ``metrics``."""
        lost = self.accepted - self.served - self._queued
        self.cluster._journal(
            f"serve_final {self.name} offered={self.offered} lost={lost}")
        out = {
            "app": self.name, "job": self.job,
            "offered": self.offered, "accepted": self.accepted,
            "served": self.served, "shed": self.shed,
            "queued_end": self._queued, "lost": lost,
            "latency_p50_s": self.latency_pct(0.50),
            "latency_p99_s": self.latency_pct(0.99),
            "max_live_replicas": self.max_live_seen,
            "status": self.ds.status(),
        }
        self.latency_hist.clear()   # rolled up into out; flush
        if "serve" not in self.cluster.metrics:
            self.cluster.metrics["serve"] = {}
        serve_metrics = self.cluster.metrics["serve"]
        serve_metrics[self.name] = out
        return out

    def shutdown(self, timeout_s: float = 10.0) -> bool:
        """Delete the app through the real FSM (drains replicas,
        removes capacity gangs — the job plane gets everything back)."""
        self.ds.mark_deleting()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.ds.reconcile():
                return True
            time.sleep(0.02)
        return False


class SimCluster:
    """Owns the GCS (in-process object or subprocess) and the fleet.

    ``n_nodes`` defaults to ``RAY_TPU_SOAK_NODES`` (100): the knob the
    bench/CI use to scale the same harness from smoke (20) to the full
    soak without editing code.
    """

    def __init__(self, n_nodes: int | None = None,
                 tick_interval: float = 0.05,
                 poll_timeout: float = 2.0,
                 gcs: str = "inproc",
                 store_path: str | None = None):
        if n_nodes is None:
            n_nodes = int(os.environ.get("RAY_TPU_SOAK_NODES", "100"))
        self.n_nodes = n_nodes
        self.tick_interval = tick_interval
        self.poll_timeout = poll_timeout
        self.tick_count = 0
        self.journal: list[str] = []
        self.metrics: dict = {}
        self._gcs_mode = gcs
        self._store_path = store_path
        self._gcs_obj = None
        self._gcs_proc = None
        self.gcs_addr: tuple | None = None
        self._probe_n = 0
        self.raylets: list[SimRaylet] = []
        self.serve_apps: list[SimServeApp] = []
        # multi-tenant driving state: job name -> deterministic PG
        # counter (jobs are registered once per soak; `stop()` is the
        # removal path for the whole harness)
        self._jobs: dict[str, int] = {}

    # ------------------------------------------------------------------ GCS
    def start(self):
        self._start_gcs()
        self.raylets = [SimRaylet(self, i) for i in range(self.n_nodes)]
        for r in self.raylets:
            r.start()
        self._journal(f"start n={self.n_nodes} gcs={self._gcs_mode}")
        return self

    def _start_gcs(self, port: int = 0):
        store = (f"sqlite:{self._store_path}" if self._store_path
                 else None)
        if self._gcs_mode == "inproc":
            from ray_tpu._private.gcs import GcsServer

            self._gcs_obj = GcsServer(port=port, store=store,
                                      recovery_grace_s=1.0).start()
            self.gcs_addr = tuple(self._gcs_obj.addr)
            return
        cmd = [sys.executable, "-m", "ray_tpu._private.gcs", str(port)]
        if store:
            cmd += ["--store", store, "--grace", "1.0"]
        self._gcs_proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                          text=True)
        line = self._gcs_proc.stdout.readline()
        if not line.startswith("GCS_READY"):
            raise RuntimeError(f"gcs subprocess failed: {line!r}")
        host, _, p = line.split()[1].partition(":")
        self.gcs_addr = (host, int(p))

    def restart_gcs(self, downtime_s: float = 0.0):
        """Stop the GCS (SIGKILL for the subprocess flavor) and bring a
        fresh one up on the SAME port + store — the reconnect-storm
        scenario every ReconnectingRpcClient in the fleet then heals
        through (with jittered arrival, into the bounded admission
        gate)."""
        port = self.gcs_addr[1]
        if self._gcs_obj is not None:
            self._gcs_obj.stop()
            self._gcs_obj = None
        if self._gcs_proc is not None:
            self._gcs_proc.kill()
            self._gcs_proc.wait(5.0)
            self._gcs_proc = None
        if downtime_s:
            time.sleep(downtime_s)
        deadline = time.monotonic() + 10.0
        while True:
            try:
                self._start_gcs(port=port)
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)   # port still in TIME_WAIT teardown
        self._journal("gcs_restart")

    # ------------------------------------------------------------- driving
    def _journal(self, line: str):
        self.journal.append(f"t={self.tick_count} {line}")

    def journal_text(self) -> str:
        """The reproducibility artifact: chaos actions + deterministic
        outcomes only, appended from the driver thread — byte-identical
        across runs with the same seed/schedule/scale."""
        return "\n".join(self.journal) + "\n"

    def survivors(self) -> list[SimRaylet]:
        return [r for r in self.raylets if r.state == "up"]

    def dead_ids(self) -> set:
        return {r.node_id for r in self.raylets if r.state == "dead"}

    def run_ticks(self, n: int, leases_every: int = 0):
        """Drive ``n`` ticks: each tick walks the fleet in index order
        (chaos consults happen at these deterministic boundaries), and
        every ``leases_every`` ticks each live raylet accepts one
        lease. Serve apps tick after the raylets: chaos consults,
        arrivals and the controller reconcile all happen at the same
        deterministic boundary."""
        for _ in range(n):
            self.tick_count += 1
            for r in self.raylets:
                r.tick()
            for app in self.serve_apps:
                app.tick()
            if leases_every and self.tick_count % leases_every == 0:
                for r in self.raylets:
                    if r.state == "up":
                        r.accept_lease()
                self._journal(
                    f"leases granted to {len(self.survivors())} nodes")
            time.sleep(self.tick_interval)

    def mass_consult(self, method: str = "mass_kill") -> dict[str, list]:
        """Consult the schedule ONCE per node at this tick (the
        simultaneous-failure boundary); apply kill/flap verdicts in
        index order and journal them."""
        self.tick_count += 1
        verdicts: dict[str, list] = {}
        t0 = time.monotonic()
        for r in self.raylets:
            fired = r.consult_mass(method)
            if fired:
                verdicts[r.tag] = fired
        for r in self.raylets:
            for action, param_s in verdicts.get(r.tag, ()):
                if action == "kill_node":
                    self._journal(f"kill_node {r.tag} ({method})")
                    r.kill()
                elif action == "flap_node":
                    ticks = max(1, int(round(param_s / self.tick_interval)))
                    self._journal(
                        f"flap_node {r.tag} down_ticks={ticks} ({method})")
                    r.flap(ticks)
        self.metrics[f"{method}_initiated_at"] = t0
        self._journal(f"{method} fired={sorted(verdicts)}")
        return verdicts

    # ------------------------------------------------------- serve plane
    def add_serve_app(self, name: str, job: str, **kw) -> SimServeApp:
        """Deploy one tenant Serve app into the harness (registers the
        job, stands up the real deployment FSM); it ticks with the
        fleet from the next ``run_ticks`` on."""
        app = SimServeApp(self, name, job, **kw)
        self.serve_apps.append(app)
        return app

    # ----------------------------------------------------- multi-tenancy
    def register_job(self, name: str, quota: dict | None = None,
                     priority: int = 0):
        """Register one tenant against the harness GCS (journaled — the
        registration order is part of the deterministic schedule)."""
        self.gcs_call("register_job", name=name, quota=quota,
                      priority=priority)
        self._jobs.setdefault(name, 0)
        self._journal(f"register_job {name} pri={priority} "
                      f"quota={sorted((quota or {}).items())}")

    def create_job_pg(self, job: str, n_bundles: int = 1,
                      cpu: float = 1.0, strategy: str = "SPREAD") -> bytes:
        """One gang for ``job`` with a DETERMINISTIC pg id (derived from
        the per-job counter, not urandom — pg identity must not vary
        run-to-run or the journal could not stay byte-identical)."""
        import hashlib

        self._jobs.setdefault(job, 0)
        self._jobs[job] += 1
        # hash the FULL job name into the id: a truncated-prefix scheme
        # collides for jobs sharing 8 leading chars, and the GCS's
        # idempotent-create replay would silently alias the second gang
        # onto the first
        pg_id = hashlib.sha256(
            f"simpg|{job}|{self._jobs[job]}".encode()).digest()[:16]
        self.gcs_call("create_placement_group", pg_id=pg_id,
                      bundles=[{"CPU": float(cpu)}] * n_bundles,
                      strategy=strategy,
                      name=f"{job}-g{self._jobs[job]}", job=job)
        self._journal(f"create_pg {job} g{self._jobs[job]} "
                      f"n={n_bundles} cpu={cpu:g}")
        return pg_id

    def jobs_tick(self, method: str = "job_tick") -> dict[str, list]:
        """Consult the chaos schedule ONCE per registered job at this
        deterministic boundary; a fired ``preempt_job`` rule issues the
        GCS preempt RPC (warning + grace + reclaim) against that job's
        newest gang. The consult outcome is journaled; WHICH gang the
        GCS picks is wall-clock-dependent scheduling state and goes to
        ``metrics`` only."""
        self.tick_count += 1
        fired: dict[str, list] = {}
        for job in sorted(self._jobs):
            inj = _fi.ACTIVE
            verdicts = (inj.on_job(job, method)
                        if inj is not None else [])
            if not verdicts:
                continue
            fired[job] = verdicts
            for action, param_s in verdicts:
                if action == "preempt_job":
                    self._journal(f"preempt_job {job} ({method})")
                    try:
                        victim = self.gcs_call("preempt_job", name=job,
                                               grace_s=param_s)
                    except Exception:
                        victim = None
                    stat = self.metrics.setdefault("preempt_rpcs", [])
                    stat.append({"job": job, "victim": victim})
        return fired

    def sample_jobs(self) -> dict:
        """One `list_jobs` sample folded to the soak's acceptance
        numbers; the deterministic violation COUNT is journaled (always
        zero on a correct scheduler — a nonzero count diverges the
        journal exactly when the run fails anyway)."""
        rows = self.gcs_call("list_jobs")
        violations = sorted(r["Job"] for r in rows if r.get("OverQuota"))
        sample = {
            "violations": violations,
            "preemptions": sum(r.get("Preemptions", 0) for r in rows),
            "quota_rejections": sum(r.get("QuotaRejections", 0)
                                    for r in rows),
            "created": sum(r["PlacementGroups"]["created"] for r in rows),
            "pending": sum(r["PlacementGroups"]["pending"] for r in rows),
        }
        self.metrics.setdefault("job_samples", []).append(sample)
        self._journal(f"jobs_sampled violations={len(violations)}")
        return sample

    # -------------------------------------------------------- convergence
    def gcs_call(self, method: str, **kw):
        client = RpcClient(self.gcs_addr, timeout=15.0)
        try:
            return client.call(method, **kw)
        finally:
            client.close()

    def wait_converged(self, timeout: float = 30.0) -> dict:
        """Block until the cluster view reconverges; returns the
        measurement dict (also merged into ``metrics``):

        - the GCS's alive set == the harness's survivor set,
        - every survivor observed every dead node's death,
        - a fresh probe published on the feed reaches every survivor
          (long-poll subscriptions demonstrably healed, not just
          presumed).
        """
        t0 = time.monotonic()
        deadline = t0 + timeout
        expect_dead = self.dead_ids()
        survivors = self.survivors()
        view_ok_at = feed_ok_at = None
        while time.monotonic() < deadline:
            if view_ok_at is None:
                try:
                    state = self.gcs_call("debug_state")
                    if state["alive_nodes"] == len(survivors):
                        view_ok_at = time.monotonic()
                except Exception:
                    pass
            feed_ok = all(expect_dead <= set(r.deaths_seen)
                          for r in survivors)
            if feed_ok and feed_ok_at is None:
                feed_ok_at = time.monotonic()
            if view_ok_at is not None and feed_ok_at is not None:
                break
            time.sleep(0.05)
        # probe: a message published NOW must reach every survivor —
        # with its OWN time budget, so a slow view/feed convergence
        # (reported above) can't leave the subscription-heal proof
        # zero seconds to run
        self._probe_n += 1
        n = self._probe_n
        probe_ok = False
        probe_deadline = max(deadline, time.monotonic() + 10.0)
        try:
            self.gcs_call("publish", channel="nodes",
                          message={"event": "probe", "n": n})
            while time.monotonic() < probe_deadline:
                if all(n in r.probes_seen for r in survivors):
                    probe_ok = True
                    break
                time.sleep(0.05)
        except Exception:
            pass
        out = {
            "converged": view_ok_at is not None and feed_ok_at is not None
            and probe_ok,
            "view_s": (view_ok_at - t0) if view_ok_at else None,
            "feed_s": (feed_ok_at - t0) if feed_ok_at else None,
            "total_s": time.monotonic() - t0,
            "probe_healed": probe_ok,
        }
        self.metrics["reconvergence"] = out
        # journal only the deterministic fact that convergence was
        # checked (and against how many deaths) — converged/probe are
        # wall-clock races and live in `metrics`, or the byte-for-byte
        # journal contract would flake on a loaded box
        self._journal(f"convergence_checked dead={len(expect_dead)}")
        return out

    def fanout_latencies(self, initiated_at: float,
                         dead_ids: set) -> list[float]:
        """Per-(survivor, death) observation latency relative to the
        kill initiation — the death-feed fanout distribution."""
        out = []
        for r in self.survivors():
            for node_id in dead_ids:
                t = r.deaths_seen.get(node_id)
                if t is not None:
                    out.append(t - initiated_at)
        return out

    def verify_leases(self) -> dict:
        """The no-lost-accepted-leases proof: every survivor's ledger
        entry must exist in GCS KV (durable across the restart)."""
        keys = set(self.gcs_call("kv_keys", ns="soak_leases"))
        missing = []
        total = 0
        for r in self.survivors():
            for lease_id in r.accepted_leases:
                total += 1
                if lease_id.encode() not in keys:
                    missing.append(lease_id)
        out = {"accepted": total, "lost": sorted(missing)}
        self.metrics["leases"] = out
        self._journal(f"leases accepted={total} lost={len(missing)}")
        return out

    def stop(self):
        self._jobs.clear()   # tenant counters die with the harness
        self.serve_apps.clear()   # ditto the serve plane
        for r in self.raylets:
            r.stop()
        if self._gcs_obj is not None:
            self._gcs_obj.stop()
            self._gcs_obj = None
        if self._gcs_proc is not None:
            self._gcs_proc.kill()
            self._gcs_proc.wait(5.0)
            self._gcs_proc = None
