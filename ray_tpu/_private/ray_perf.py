"""Core microbenchmark — the ray_perf.py port BASELINE.md names.

Reference: python/ray/_private/ray_perf.py:93-200 (run by
release/microbenchmark/run_microbenchmark.py). Same harness shape: each
benchmark times a loop and reports ops/sec; numbers quantify the control
plane (pure-Python runtime, pickle+TCP per hop), not TPU compute.

Run: `python -m ray_tpu._private.ray_perf` or `ray-tpu microbenchmark`.
Prints one human line per benchmark plus a final JSON summary.
"""
from __future__ import annotations

import json
import time

import numpy as np


def timeit(name, fn, multiplier=1, *, min_time=1.0, results=None):
    # warmup
    fn()
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < min_time:
        fn()
        count += 1
    dt = time.perf_counter() - start
    rate = count * multiplier / dt
    print(f"{name} per second: {rate:.2f}")
    if results is not None:
        results[name] = round(rate, 2)
    return rate


def main(min_time: float = 1.0):
    import ray_tpu

    owns_runtime = not ray_tpu.is_initialized()
    if owns_runtime:
        ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    results: dict = {}

    @ray_tpu.remote(num_cpus=0, max_retries=0)
    def noop():
        return None

    @ray_tpu.remote(num_cpus=0, max_retries=0)
    def noop_arg(x):
        return None

    @ray_tpu.remote(num_cpus=0)
    class Sink:
        def ping(self):
            return None

        def ping_arg(self, x):
            return None

    # --- object store -----------------------------------------------------
    small = np.zeros(64, dtype=np.uint8)
    timeit("single client get calls",
           lambda: ray_tpu.get(ray_tpu.put(small)),
           min_time=min_time, results=results)
    timeit("single client put calls",
           lambda: ray_tpu.put(small),
           min_time=min_time, results=results)
    big = np.zeros(1024 * 1024, dtype=np.uint8)   # 1 MiB
    rate = timeit("single client put (MiB/s)",
                  lambda: ray_tpu.put(big), multiplier=1,
                  min_time=min_time, results=None)
    results["single client put gigabytes per second"] = round(
        rate * big.nbytes / 2**30, 3)
    print(f"single client put gigabytes per second: "
          f"{results['single client put gigabytes per second']}")

    # --- tasks ------------------------------------------------------------
    timeit("single client tasks sync",
           lambda: ray_tpu.get(noop.remote()),
           min_time=min_time, results=results)
    timeit("single client tasks async",
           lambda: ray_tpu.get([noop.remote() for _ in range(100)]),
           multiplier=100, min_time=min_time, results=results)

    # inline_exec: the task runs on the worker's transport pump (no
    # main-thread handoff) — the opt-in hot path for pump-safe tasks
    @ray_tpu.remote(num_cpus=0, max_retries=0, inline_exec=True)
    def noop_inline():
        return None

    ray_tpu.get(noop_inline.remote())
    timeit("single client tasks sync (inline exec)",
           lambda: ray_tpu.get(noop_inline.remote()),
           min_time=min_time, results=results)
    timeit("single client tasks async (inline exec)",
           lambda: ray_tpu.get([noop_inline.remote() for _ in range(100)]),
           multiplier=100, min_time=min_time, results=results)
    obj = ray_tpu.put(small)
    timeit("single client tasks with object ref arg",
           lambda: ray_tpu.get([noop_arg.remote(obj) for _ in range(20)]),
           multiplier=20, min_time=min_time, results=results)

    @ray_tpu.remote(num_cpus=0, max_retries=0, inline_exec=True)
    def noop_arg_inline(x):
        return None

    ray_tpu.get(noop_arg_inline.remote(obj))
    timeit("single client tasks with object ref arg (inline exec)",
           lambda: ray_tpu.get(
               [noop_arg_inline.remote(obj) for _ in range(20)]),
           multiplier=20, min_time=min_time, results=results)

    # --- actors -----------------------------------------------------------
    a = Sink.remote()
    ray_tpu.get(a.ping.remote())
    timeit("single client actor calls sync",
           lambda: ray_tpu.get(a.ping.remote()),
           min_time=min_time, results=results)
    timeit("single client actor calls async",
           lambda: ray_tpu.get([a.ping.remote() for _ in range(100)]),
           multiplier=100, min_time=min_time, results=results)
    pool = [Sink.remote() for _ in range(4)]
    ray_tpu.get([b.ping.remote() for b in pool])
    timeit("n:n actor calls async",
           lambda: ray_tpu.get([b.ping.remote()
                                for _ in range(25) for b in pool]),
           multiplier=100, min_time=min_time, results=results)

    print(json.dumps({"benchmark": "ray_perf", "results": results}))
    if owns_runtime:
        ray_tpu.shutdown()
    return results


if __name__ == "__main__":
    main()
