"""Deterministic RPC fault-injection plane.

Reference tier: python/ray/tests/test_chaos.py drives whole-process
kills; the reference additionally hardens the *message* level with
per-RPC retry policy (grpc channel args, client_call.h retries). This
module adds the missing message-level chaos: a seeded, schedule-based
injector threaded through both transports (protocol.py pure-Python and
native_rpc.py C-core) that can drop, delay, duplicate, disconnect, or
slow-reply individual RPCs — reproducibly.

Design constraints:

- **Zero overhead when disabled.** The transports do one module-global
  load + ``is None`` check per call (``fault_injection.ACTIVE``); no
  allocation, no dict lookup, no env read on the hot path.
- **Deterministic.** Decisions are NOT drawn from a shared RNG (thread
  interleaving would make the sequence irreproducible). Each rule keeps
  a per-method call counter; the verdict for call *n* of method *m* is
  ``sha256(seed, rule_index, m, n)`` mapped to [0, 1). Two runs issuing
  the same calls per method get the identical fault sequence regardless
  of scheduling — asserted via the event log in
  tests/test_fault_injection.py.
- **Reproducible from one line.** Any failure can be replayed from the
  ``RAY_TPU_FAULT_SEED`` + ``RAY_TPU_FAULT_SCHEDULE`` pair (see
  ``banner()``; tests/conftest.py prints it on failure).

Schedule grammar (``;``-separated rules)::

    rule     := action ":" scope "." method ":" selector [":" param_ms]
    action   := drop | delay | dup | disconnect | slow_reply | kill_actor
              | kill_node | flap_node | preempt_job | torn_write
              | corrupt_file
    scope    := "*" | gcs | raylet | worker | driver | <process tag>
    method   := "*" | <rpc method name>
    selector := "p" FLOAT    probability (hash-derived, deterministic)
              | "%" INT      every K-th call (1-indexed: K, 2K, ...)
              | "#" INT[,..] exact 1-indexed call numbers
    param_ms := FLOAT        delay / slow_reply duration (default 10)

The scope matches the process ROLE or any of its TAGS (``add_tag``):
train workers tag themselves ``rank<N>``, so rank-death chaos can target
exactly one gang member deterministically.

``preempt_job`` is a JOB-level primitive: the driver of a named job
(the multi-tenant soak harness, a chaos test loop) consults
``on_job(job, method)`` at its own deterministic boundaries, and a
fired rule means "force-preempt this job's newest running gang now"
(the caller issues the GCS ``preempt_job`` RPC — warning + grace +
reclaim, exactly the organic can't-place path). Counters are
per-(job, method) like the node primitives, so
``preempt_job:train.job_tick:%5`` preempts the ``train`` job on every
5th consult regardless of how many jobs share the schedule — the
seeded preemption-storm generator.

``kill_node`` / ``flap_node`` are NODE-level primitives, consulted at
the same deterministic client-send boundary as the message-level
actions but by the entity that OWNS a node's connections (the scale
harness's simulated raylets, ``_private/sim_cluster.py``) via
``on_node(tag, method)`` rather than by the transports — one process
hosts many simulated nodes, so the decision is scoped by the node's
TAG, and each rule keeps an independent per-(tag, method) counter so
verdicts stay deterministic per node regardless of how many nodes
share the schedule. ``kill_node:<tag>.<method>:<sel>`` tears down the
node's connections and marks it for non-reregistration;
``flap_node:<tag>.<method>:<sel>:<param_ms>`` disconnects it and
re-registers it after param_ms. A wildcard tag scope
(``kill_node:*.mass_kill:p0.1``) with a probabilistic selector is the
"kill 10% of nodes simultaneously" schedule: every node consults the
rule once at the same harness boundary and the hash verdict picks a
deterministic ~10% subset.

``torn_write`` / ``corrupt_file`` are DISK-level primitives, consulted
by the sanctioned durable-write helper (``_private/atomic_write.py``)
via ``on_disk(tag, name)`` at its own deterministic write boundary —
``tag`` is the writer's disk tag (checkpoint writes use ``ckpt``) and
``name`` the file's logical kind (``shard`` / ``manifest``), while the
scope match also covers this process's role + tags so
``torn_write:rank1.shard:#2`` hits exactly one gang member's second
shard write. A fired ``torn_write`` leaves a truncated temp file and
raises (the final path never appears — a crash mid-write); a fired
``corrupt_file`` flips one byte before an otherwise-clean commit (a
latent media error the restore-side digest check must catch). Counters
are per-(tag, name) like the node primitives.

Examples::

    drop:*.kv_put:p0.1              # lose 10% of kv_put requests
    delay:*.*:p0.05:20              # 5% of all sends wait 20ms first
    dup:gcs.kv_put:%3               # every 3rd kv_put sent twice
    disconnect:*.request_worker_lease:#2   # kill the conn on call 2
    slow_reply:*.get_nodes:p0.2:15  # server stalls 15ms before replying
    kill_actor:rank1.next_result:#2 # train rank 1's process dies (hard,
                                    # os._exit) when it serves its 2nd
                                    # next_result — deterministic rank
                                    # death for gang-FT tests

Actions, and where the transports apply them:

- ``drop``       client send: the request/push is never written. A sync
                 call surfaces as TimeoutError after its per-call
                 timeout (exactly what real message loss on a healthy
                 TCP connection looks like) — schedules should only
                 drop methods called with a finite timeout or under a
                 RetryPolicy, or the caller hangs like it would in
                 production. An ASYNC call's future never resolves (the
                 caller's own timeout/retry layer owns recovery, as it
                 must for real loss); its pending slot is reclaimed when
                 the connection closes, so schedules dropping async-path
                 methods (e.g. push_task) trade one pending slot per
                 fault for the soak's duration.
- ``delay``      client send: sleep param_ms before writing.
- ``dup``        client send: the frame is written twice (same seq);
                 exercises server-side idempotency. The duplicate reply
                 is discarded by the reply-correlation map.
- ``disconnect`` client send: the connection is closed and
                 ConnectionLost raised; subsequent calls fail until the
                 owner reconnects (ReconnectingRpcClient heals, plain
                 clients surface the error).
- ``slow_reply`` server dispatch: sleep param_ms before writing the
                 reply (models a GC-pausing / overloaded peer).
- ``kill_actor`` server dispatch: the process dies via os._exit before
                 the reply is written — the SIGKILL/preemption analog
                 for actors, reproducible from the seed+schedule pair
                 like every other action. Scope it by role or tag
                 (``rank<N>`` for train workers); a wildcard scope
                 would kill whatever process serves the call first,
                 including the driver.

Role scoping is process-level: subprocess entrypoints tag themselves
(gcs.main → "gcs", scripts/node → "raylet", worker_main → "worker",
CoreWorker driver mode → "driver"). In-process test clusters share one
process, so their schedules scope by method with role ``*``.
"""
from __future__ import annotations

import hashlib
import os
import struct
import threading
import time

ACTIONS = ("drop", "delay", "dup", "disconnect", "slow_reply",
           "kill_actor", "kill_node", "flap_node", "preempt_job",
           "torn_write", "corrupt_file")
# actions applied at the client send boundary vs the server reply boundary
_SEND_ACTIONS = frozenset({"drop", "delay", "dup", "disconnect"})
_REPLY_ACTIONS = frozenset({"slow_reply", "kill_actor"})
# node-level actions, consulted by the node's owner (sim_cluster) at its
# own deterministic send boundary via on_node(tag, method)
_NODE_ACTIONS = frozenset({"kill_node", "flap_node"})
# job-level actions, consulted by the entity driving a named job
# (multi-tenant soak harness / chaos tests) via on_job(job, method)
_JOB_ACTIONS = frozenset({"preempt_job"})
# disk-level actions, consulted by the durable-write helper
# (_private/atomic_write.py) at its own deterministic write boundary
# via on_disk(tag, name). kill_actor is ALSO a disk action: a rule like
# ``kill_actor:rank1.shard:#2`` dies at the write boundary — "kill a
# rank mid-shard-write" as a seeded primitive. (Such a rule is
# harmlessly double-registered in _reply_rules; no RPC method is named
# ``shard``/``manifest``, so it can only fire here.)
_DISK_ACTIONS = frozenset({"torn_write", "corrupt_file", "kill_actor"})

_DEFAULT_PARAM_MS = 10.0


class FaultPlan:
    """What on_send decided for one outgoing call. Only allocated when at
    least one rule fired (the common no-fault call returns None)."""

    __slots__ = ("drop", "dup", "disconnect", "delay_s")

    def __init__(self):
        self.drop = False
        self.dup = False
        self.disconnect = False
        self.delay_s = 0.0


class _Rule:
    __slots__ = ("action", "role", "method", "mode", "prob", "every",
                 "calls", "param_s", "index", "_counts")

    def __init__(self, action, role, method, mode, prob, every, calls,
                 param_s, index):
        self.action = action
        self.role = role
        self.method = method
        self.mode = mode          # "p" | "%" | "#"
        self.prob = prob
        self.every = every
        self.calls = calls        # frozenset of 1-indexed call numbers
        self.param_s = param_s
        self.index = index        # position in the schedule (hash input)
        self._counts: dict[str, int] = {}   # method -> calls seen

    def matches_scope(self, role: str, method: str,
                      tags: frozenset = frozenset()) -> bool:
        if self.method != "*" and self.method != method:
            return False
        return self.role == "*" or self.role == role or self.role in tags

    def fires(self, seed: int, method: str, lock: threading.Lock) -> int:
        """Count this call; return its 1-indexed number if the rule fires,
        else 0. The counter is per-method so wildcard rules stay
        deterministic per method (global interleaving of different
        methods across threads does not change any verdict)."""
        with lock:
            n = self._counts.get(method, 0) + 1
            self._counts[method] = n
        if self.mode == "%":
            return n if n % self.every == 0 else 0
        if self.mode == "#":
            return n if n in self.calls else 0
        return n if _hash01(seed, self.index, method, n) < self.prob else 0


def _hash01(seed: int, rule_index: int, method: str, n: int) -> float:
    """Deterministic uniform [0,1) from the decision coordinates."""
    h = hashlib.sha256(
        b"%d:%d:%s:%d" % (seed, rule_index, method.encode(), n)).digest()
    return struct.unpack(">Q", h[:8])[0] / 2.0 ** 64


def _note_fault(action: str, role: str, method: str, call_n: int):
    """Mirror a fired rule into the internal telemetry plane: a
    `ray_tpu_faults_injected_total` counter and a `fault_injected`
    cluster event — so injected chaos is visible through the same
    /metrics and list_cluster_events() surfaces as its consequences.
    Lazy imports keep the injector import-light (and the transports'
    disabled-mode cost untouched — this only runs when a rule fires)."""
    try:
        from ray_tpu._private import events as _events
        from ray_tpu._private import telemetry as _tm

        _tm.counter_inc("ray_tpu_faults_injected_total",
                        tags={"action": action, "method": method})
        _events.record("fault_injected", action=action, method=method,
                       call=call_n, fault_role=role)
    except Exception:
        pass   # telemetry must never alter the injected fault sequence


class ScheduleError(ValueError):
    pass


def parse_schedule(schedule: str) -> list[_Rule]:
    rules = []
    for index, raw in enumerate(schedule.split(";")):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        if len(parts) not in (3, 4):
            raise ScheduleError(
                f"fault rule {raw!r}: want action:role.method:selector"
                f"[:param_ms]")
        action, scope, selector = parts[0], parts[1], parts[2]
        if action not in ACTIONS:
            raise ScheduleError(
                f"fault rule {raw!r}: unknown action {action!r} "
                f"(one of {'/'.join(ACTIONS)})")
        if "." not in scope:
            raise ScheduleError(
                f"fault rule {raw!r}: scope must be role.method")
        role, method = scope.split(".", 1)
        prob, every, calls = 0.0, 0, frozenset()
        if selector.startswith("p"):
            mode, prob = "p", float(selector[1:])
            if not 0.0 <= prob <= 1.0:
                raise ScheduleError(
                    f"fault rule {raw!r}: probability out of [0,1]")
        elif selector.startswith("%"):
            mode, every = "%", int(selector[1:])
            if every < 1:
                raise ScheduleError(f"fault rule {raw!r}: %K needs K >= 1")
        elif selector.startswith("#"):
            mode = "#"
            calls = frozenset(int(c) for c in selector[1:].split(","))
        else:
            raise ScheduleError(
                f"fault rule {raw!r}: selector must be pN / %K / #i,j")
        param_s = (float(parts[3]) if len(parts) == 4
                   else _DEFAULT_PARAM_MS) / 1000.0
        rules.append(_Rule(action, role, method, mode, prob, every, calls,
                           param_s, index))
    return rules


class FaultInjector:
    """Seeded, schedule-based fault decisions + an event log.

    The event log records every fired fault as
    ``(action, role, method, call_n)``. Because verdicts are pure
    functions of (seed, rule, method, call_n), two runs driving the same
    per-method call sequences produce equal logs up to thread-order —
    compare with ``trace()`` (sorted) for a stable assertion.
    """

    def __init__(self, seed: int, schedule: str, role: str | None = None):
        self.seed = int(seed)
        self.schedule = schedule
        self.rules = parse_schedule(schedule)
        self._send_rules = [r for r in self.rules
                            if r.action in _SEND_ACTIONS]
        self._reply_rules = [r for r in self.rules
                             if r.action in _REPLY_ACTIONS]
        self._node_rules = [r for r in self.rules
                            if r.action in _NODE_ACTIONS]
        self._job_rules = [r for r in self.rules
                           if r.action in _JOB_ACTIONS]
        self._disk_rules = [r for r in self.rules
                            if r.action in _DISK_ACTIONS]
        self._lock = threading.Lock()
        self.events: list[tuple] = []
        # None = follow the process-global role (set_role); a role given
        # here pins this injector's decisions regardless of the global
        self._pinned_role = role

    def _current_role(self) -> str:
        return self._pinned_role if self._pinned_role is not None else _role

    # ------------------------------------------------------------- decisions

    def on_send(self, method: str) -> FaultPlan | None:
        """Client send boundary. Returns the plan to apply, or None."""
        plan = None
        role = self._current_role()
        tags = get_tags()
        for rule in self._send_rules:
            if not rule.matches_scope(role, method, tags):
                continue
            n = rule.fires(self.seed, method, self._lock)
            if not n:
                continue
            if plan is None:
                plan = FaultPlan()
            if rule.action == "drop":
                plan.drop = True
            elif rule.action == "dup":
                plan.dup = True
            elif rule.action == "disconnect":
                plan.disconnect = True
            elif rule.action == "delay":
                plan.delay_s = max(plan.delay_s, rule.param_s)
            with self._lock:
                self.events.append((rule.action, role, method, n))
            _note_fault(rule.action, role, method, n)
        return plan

    def on_reply(self, method: str) -> float:
        """Server dispatch boundary: seconds to stall before replying —
        or, for a fired ``kill_actor`` rule, the process dies right here
        (os._exit, the preemption/SIGKILL analog; the caller observes a
        dropped connection, the raylet reaps the corpse and reports
        actor_failed exactly as for a real chip/host loss)."""
        delay = 0.0
        role = self._current_role()
        tags = get_tags()
        for rule in self._reply_rules:
            if not rule.matches_scope(role, method, tags):
                continue
            n = rule.fires(self.seed, method, self._lock)
            if not n:
                continue
            with self._lock:
                self.events.append((rule.action, role, method, n))
            _note_fault(rule.action, role, method, n)
            if rule.action == "kill_actor":
                os._exit(1)
            delay = max(delay, rule.param_s)
        return delay

    def on_node(self, tag: str, method: str) -> list[tuple[str, float]]:
        """Node boundary: decisions for the simulated node identified by
        ``tag`` about to issue ``method``. Returns [(action, param_s)]
        for every node rule that fired (kill_node / flap_node); the
        CALLER applies them (tear down connections, schedule the
        re-register) — the transports never see node actions.

        Rules count per (tag, method), not per method: a wildcard-scope
        rule consulted by 100 nodes keeps 100 independent deterministic
        counters, so node k's verdict never depends on how many other
        nodes share the schedule or in what order they consult it."""
        fired: list[tuple[str, float]] = []
        for rule in self._node_rules:
            if not rule.matches_scope(tag, method, frozenset((tag,))):
                continue
            n = rule.fires(self.seed, f"{tag}|{method}", self._lock)
            if not n:
                continue
            with self._lock:
                self.events.append((rule.action, tag, method, n))
            _note_fault(rule.action, tag, method, n)
            fired.append((rule.action, rule.param_s))
        return fired

    def on_job(self, job: str, method: str,
               tags: frozenset | None = None) -> list[tuple[str, float]]:
        """Job boundary: decisions for the named ``job`` at the caller's
        deterministic consult point ``method``. Returns
        [(action, param_s)] for every job rule that fired; the CALLER
        applies them (issue the GCS ``preempt_job`` RPC) — the
        transports never see job actions. Counters are per
        (job, method) like ``on_node``'s per-(tag, method), so one
        schedule shared by several jobs keeps an independent
        deterministic sequence per job.

        ``tags`` widens the scope match beyond the job name itself:
        the Serve plane consults once per replica SLOT with
        ``job=<slot-tag>`` and ``tags={slot-tag, app-job, dep-tag}``,
        so a rule scoped to the APP's job name matches every slot
        while each slot keeps its own deterministic counter/hash
        stream — a p-selector then warns a seed-deterministic SUBSET
        of one app's replicas, and a rule scoped to one slot tag
        (``preempt_job:serve-app-Model-slot0.…``) targets exactly
        that slot's capacity."""
        scope_tags = frozenset((job,)) if tags is None \
            else (frozenset(tags) | {job})
        fired: list[tuple[str, float]] = []
        for rule in self._job_rules:
            if not rule.matches_scope(job, method, scope_tags):
                continue
            n = rule.fires(self.seed, f"{job}|{method}", self._lock)
            if not n:
                continue
            with self._lock:
                self.events.append((rule.action, job, method, n))
            _note_fault(rule.action, job, method, n)
            fired.append((rule.action, rule.param_s))
        return fired

    def on_disk(self, tag: str, name: str) -> list[tuple[str, float]]:
        """Disk boundary: decisions for one durable write identified by
        the writer's ``tag`` (e.g. ``ckpt``, or a train worker's
        ``rank<N>`` process tag) and the file's logical ``name`` (e.g.
        ``shard`` / ``manifest``). A fired ``kill_actor`` dies right
        here (os._exit — a rank killed mid-shard-write). Returns
        [(action, param_s)] for every other disk rule that fired
        (torn_write / corrupt_file); the CALLER —
        ``_private/atomic_write.py`` — applies them, so every byte that
        rides the sanctioned durability idiom is chaos-testable.

        Counters are per (tag, name) like ``on_node``'s, so a schedule
        shared by a whole gang keeps an independent deterministic
        sequence per writer, and the scope match includes this process's
        role + tags: ``torn_write:rank1.shard:#2`` lands on exactly one
        rank's second shard write."""
        role = self._current_role()
        scope_tags = get_tags() | {tag}
        fired: list[tuple[str, float]] = []
        for rule in self._disk_rules:
            if not rule.matches_scope(role, name, scope_tags):
                continue
            n = rule.fires(self.seed, f"{tag}|{name}", self._lock)
            if not n:
                continue
            with self._lock:
                self.events.append((rule.action, tag, name, n))
            _note_fault(rule.action, tag, name, n)
            if rule.action == "kill_actor":
                # a rank dying mid-shard-write: the generation it was
                # contributing to never gets a manifest — torn by
                # definition, invisible to restore
                os._exit(1)
            fired.append((rule.action, rule.param_s))
        return fired

    # ------------------------------------------------------------ inspection

    def trace(self) -> list[tuple]:
        """The event log in a thread-order-independent form (sorted) —
        the reproducibility assertion compares these across runs."""
        with self._lock:
            return sorted(self.events)

    def event_count(self) -> int:
        with self._lock:
            return len(self.events)

    def banner(self) -> str:
        """One line that reproduces this injector exactly."""
        return (f"RAY_TPU_FAULT_SEED={self.seed} "
                f"RAY_TPU_FAULT_SCHEDULE='{self.schedule}'")


# ------------------------------------------------------------------ globals
#
# ACTIVE is read directly by the transports (module-global load + None
# check = the entire disabled-mode cost). _role tags this process for
# role-scoped rules.

ACTIVE: FaultInjector | None = None
_role: str = os.environ.get("RAY_TPU_FAULT_ROLE", "*")
_tags: frozenset = frozenset()
_env_checked = False
_install_lock = threading.Lock()


def set_role(role: str, weak: bool = False):
    """Tag this process for role-scoped rules. ``weak=True`` only sets
    the role if nothing claimed it yet (in-process test clusters host
    several components; the subprocess entrypoint's tag wins)."""
    global _role
    if weak and _role != "*":
        return
    _role = role


def get_role() -> str:
    return _role


def add_tag(tag: str):
    """Add a scope tag to this process (e.g. a train worker's gang rank,
    ``rank3``): schedule rules may target tags exactly like roles, which
    is what makes rank-death chaos (`kill_actor:rank1....`) land on one
    deterministic gang member instead of every worker at once. Tags are
    additive and process-global; an immutable snapshot is read per
    decision so concurrent adds never tear a match."""
    global _tags
    with _install_lock:
        _tags = frozenset(_tags | {str(tag)})


def get_tags() -> frozenset:
    return _tags


def install(seed: int, schedule: str) -> FaultInjector:
    """Activate an injector in this process (tests). Returns it so the
    caller can read the event log."""
    global ACTIVE
    with _install_lock:
        ACTIVE = FaultInjector(seed, schedule)
        return ACTIVE


def uninstall():
    global ACTIVE
    with _install_lock:
        ACTIVE = None


def maybe_init_from_env():
    """Activate from RAY_TPU_FAULT_SCHEDULE (+ RAY_TPU_FAULT_SEED, default
    0) — called once at transport import so spawned cluster processes
    inherit the fault plane through their environment. A malformed
    schedule raises: silently running chaos-free when chaos was asked
    for would invalidate the test."""
    global ACTIVE, _env_checked
    if _env_checked:
        return
    with _install_lock:
        if _env_checked:
            return
        _env_checked = True
        schedule = os.environ.get("RAY_TPU_FAULT_SCHEDULE")
        if schedule:
            ACTIVE = FaultInjector(
                int(os.environ.get("RAY_TPU_FAULT_SEED", "0")), schedule)


# Self-activate on import (idempotent; protocol.py calls this again for
# processes that import the transport first) so `import fault_injection`
# and the transports always agree on whether the plane is live.
maybe_init_from_env()


def apply_send_plan(plan: FaultPlan, close, method: str):
    """Shared pre-send application: sleep the delay, then close+raise on
    disconnect. (drop/dup need transport-specific handling, so the
    transports consume those flags themselves.)"""
    if plan.delay_s:
        time.sleep(plan.delay_s)
    if plan.disconnect:
        try:
            close()
        except Exception:
            pass
        # late import: protocol imports this module at its own top level
        from ray_tpu._private.protocol import ConnectionLost

        raise ConnectionLost(
            f"[fault-injection] disconnect before {method!r} "
            f"(reproduce: {ACTIVE.banner() if ACTIVE else 'n/a'})")
