"""Bounded per-process structured event log for the runtime core.

Reference: Ray's task events + GCS cluster events (task state
transitions with per-state timestamps flow from workers through the
agent into the dashboard/state API; `ray list cluster-events`). Here
every process keeps a bounded ring of structured events; the state API
(`ray_tpu.experimental.state.api.list_cluster_events`) unions the
driver's ring with the GCS process's and every raylet's (which fans out
over its workers, like `rpc_metrics_snapshot`), dedups by
(node, pid, seq) and returns one time-ordered stream.

Event kinds recorded by the runtime:

- ``task_state``   — task lifecycle transitions with timestamps:
                     SUBMITTED (owner, at submit) → LEASE_GRANTED
                     (owner, at dispatch onto a leased worker) →
                     RUNNING (executor) → FINISHED/FAILED (executor or
                     owner), plus RESUBMITTED on dispatch failure /
                     worker death retry. `summarize_tasks()` derives the
                     queue/scheduling/execution latency breakdown from
                     these.
- ``actor_state``  — REGISTERED/ALIVE/RESTARTING/DEAD (GCS process).
- ``node_state``   — ALIVE/DEAD with reason (GCS process).
- ``retry_budget_exhausted`` — the process-wide retry budget drained
                     and a retry was refused (_private/retry.py).
- ``fault_injected`` — a fault-injection rule fired
                     (_private/fault_injection.py): action, method,
                     per-method call number.
- ``COLLECTIVE_STRAGGLER`` — ranks arrived at a collective op late
                     (group rendezvous actor, util/collective/
                     telemetry.py): group, op, seq, ranks, lags.
- ``COMPILE_BEGIN`` / ``COMPILE_END`` — an instrumented jitted
                     function hit a compile-cache miss
                     (parallel/compile_watch.py): fn, duration.
- ``train_step``   — a Train worker streamed a step report
                     (train/worker_group.py): rank, iteration, device
                     identity.
- ``train_group``  — a Train worker gang came up
                     (train/backend_executor.py): per-worker device
                     identities.
- ``GANG_FAILED`` / ``GANG_RESTARTED`` / ``train_gang_retry`` — elastic
                     gang fault tolerance (train/trainer.py): a gang
                     attempt failed (dead ranks, failure counts), a
                     rebuilt gang resumed from checkpoint, and the
                     per-retry backoff draw.
- ``COLLECTIVE_GROUP_POISONED`` — a collective group was poisoned on
                     member death (util/collective/collective.py):
                     group, dead ranks, reason, incarnation epoch.
- ``REPLICA_STARTED`` / ``REPLICA_DIED`` / ``REPLICA_DRAINED`` — Serve
                     replica lifecycle (serve/_private/controller.py):
                     deployment, replica_id; DIED carries the detection
                     source (``death_feed`` / ``health`` / ``init``),
                     DRAINED whether the drain completed gracefully.
- ``SERVE_SCALED``   — an autoscale decision applied after hysteresis
                     (controller): deployment, direction, from/to
                     replica counts, the demand signal.
- ``REQUEST_SHED``   — Serve admission control rejected a request
                     (serve/_private/router.py): deployment, queue
                     occupancy/capacity, the retry-after hint, and
                     whether replicas were draining (the hint then
                     reflects the grace window remaining).
- ``SERVE_APP_REGISTERED`` — a Serve app was deployed as a first-class
                     job-plane tenant (serve/_private/controller.py):
                     app, job, priority, quota.
- ``SERVE_CAPACITY_PLACED`` — a replica's capacity gang turned CREATED
                     in the job plane (controller): deployment,
                     replica_id, job, the spike-to-placed wait.
- ``SERVE_REPLICA_WARNED`` — a preempt_warning landed on a replica's
                     capacity gang (controller): deployment,
                     replica_id, job, reason (``preempted`` external /
                     ``scale_down`` self-requested), grace remaining —
                     the replica drains inside the window and routers
                     drop it from selection.
- ``STEP_REGRESSION`` — the step-anatomy rolling-baseline detector
                     fired (parallel/step_anatomy.py): rank, step_id,
                     recent/baseline p50 step time, the knobbed
                     multiple.
- ``FLIGHT_RECORDER_DUMP`` — a black-box dump directory was written
                     (_private/flight_recorder.py): trigger reason,
                     dump path, number of processes captured.
- ``NODE_BATCH_DEAD`` — a coalesced node-death batch (>=
                     ``gcs_death_batch_min`` deaths inside the coalesce
                     window — a rack loss or seeded mass kill) was
                     swept and fanned out as ONE broadcast
                     (_private/gcs.py): node_ids, count, reasons.
- ``JOB_REGISTERED`` — a named job joined the multi-tenant scheduling
                     plane (_private/gcs.py): job, priority, quota.
- ``PREEMPTION_WARNED`` — a higher-priority placement group could not
                     place and the GCS picked this victim: pg_id, job,
                     the grace window, the preemptor — the Train plane
                     cuts a checkpoint inside the window
                     (_private/gcs.py).
- ``PREEMPTION_FIRED`` — the grace window elapsed and the victim's
                     bundles were reclaimed; the victim re-queued
                     PENDING to resume when capacity returns
                     (_private/gcs.py): pg_id, job, preemptor.
- ``PREEMPTION_CANCELED`` — the grace window elapsed but the preemptor
                     no longer needed the capacity (placed elsewhere,
                     removed, or now placeable as-is): the victim kept
                     its bundles (_private/gcs.py): pg_id, job,
                     preemptor.
- ``PIPELINE_GANG_STARTED`` — a multi-slice MPMD pipeline gang came up
                     (train/pipeline/trainer.py): group, stage count,
                     ranks per stage, microbatches, schedule, and the
                     per-stage slice placement reported by the
                     SPREAD_ACROSS_SLICES scheduler.
- ``STORE_LEAK``   — the memory-anatomy leak sweep classified a live
                     store object as orphaned
                     (_private/memory_anatomy.py): the full provenance
                     record (oid, category, nbytes, creator pid,
                     group/epoch/rank) plus the reason
                     (``owner_dead`` / ``group_destroyed`` /
                     ``epoch_stale``). Emitted once per object.
- ``PUBSUB_RESYNC`` — a long-poll subscriber detected a feed gap
                     (mailbox overflow / publisher GC) and reconverged
                     from the channel's state snapshot
                     (_private/pubsub.py): channels, seq floor,
                     per-subscriber resync count.
- ``CHECKPOINT_COMMITTED`` — rank 0 durably renamed a sharded-checkpoint
                     generation's MANIFEST.json after every rank acked
                     its shard write (train/sharded_checkpoint.py):
                     step, world, path, total shard bytes. Before this
                     event the generation does not exist as far as
                     restore is concerned.
- ``CHECKPOINT_QUARANTINED`` — restore-side verification renamed a
                     bad/torn generation out of sight and fell back to
                     the next older one: path, reason (``torn`` /
                     ``digest_mismatch`` / ``size_mismatch`` /
                     ``shard_missing`` / ``plan_mismatch``) and the
                     offending shard file when one is identifiable.
- ``CHECKPOINT_RESHARDED`` — a gang restored a generation saved at a
                     DIFFERENT world size, re-slicing the saved shards
                     onto the new shard map by index math over the
                     bucket plan: path, step, world_saved, world_now.

Design constraints match the metrics plane: recording is one lock +
deque append (no allocation beyond the event dict), the ring is bounded
(drop-oldest, counted), and ``RAY_TPU_INTERNAL_TELEMETRY=0`` turns the
whole plane off.
"""
from __future__ import annotations

import collections
import os
import sys
import threading
import time

# One kill-switch for the internal telemetry plane (shared with
# _private/telemetry.py): latency-critical deployments drop the
# per-event lock+append and the per-RPC histogram observe together.
ENABLED = os.environ.get("RAY_TPU_INTERNAL_TELEMETRY", "1") != "0"

_MAX_EVENTS = int(os.environ.get("RAY_TPU_EVENT_LOG_SIZE", "4096"))

TASK_STATES = ("SUBMITTED", "LEASE_GRANTED", "RUNNING", "FINISHED",
               "FAILED", "RESUBMITTED")

_lock = threading.Lock()
_events: collections.deque = collections.deque(maxlen=_MAX_EVENTS)
_seq = 0
_dropped = 0
# cached per process: workers are spawned (fresh interpreters), never forked
_PID = os.getpid()
_NODE = os.uname().nodename


def _role() -> str:
    """This process's cluster role, reusing the fault plane's tag (gcs /
    raylet / worker / driver) without importing it into the module graph."""
    fi = sys.modules.get("ray_tpu._private.fault_injection")
    if fi is None:
        return "driver"
    role = fi.get_role()
    return "driver" if role == "*" else role


def record(kind: str, **fields):
    """Append one structured event. Never raises; ~1µs when enabled.

    The envelope keys (ts/seq/pid/node/role/kind) are reserved and WIN
    over same-named caller fields: `seq` is the (node, pid, seq) dedup
    key `list_cluster_events` relies on — a caller shadowing it would
    make its events silently vanish as "duplicates" of unrelated ones
    (this bit the collective straggler events; carry domain sequence
    numbers under another name, e.g. ``op_seq``)."""
    global _seq, _dropped
    if not ENABLED:
        return
    with _lock:
        _seq += 1
        dropped = len(_events) == _events.maxlen
        if dropped:
            _dropped += 1
        _events.append({**fields,
                        "ts": time.time(), "seq": _seq, "pid": _PID,
                        "node": _NODE, "role": _role(), "kind": kind})
    if dropped:
        # rare (ring full) — counted into /metrics so silent loss of the
        # event stream's head is itself observable
        try:
            from ray_tpu._private import telemetry as _tm

            _tm.counter_inc("ray_tpu_events_dropped_total")
        except Exception:
            pass


def task_event(task_id, state: str, **extra):
    """Record one task state transition (`kind="task_state"`)."""
    if not ENABLED:
        return
    record("task_state",
           task_id=task_id.hex() if isinstance(task_id, bytes) else task_id,
           state=state, **extra)


def snapshot() -> list[dict]:
    """This process's events, oldest first (each a copy — callers and the
    RPC pickle path must not alias the live ring entries)."""
    with _lock:
        return [dict(e) for e in _events]


def clear():
    global _dropped
    with _lock:
        _events.clear()
        _dropped = 0


def stats() -> dict:
    with _lock:
        return {"recorded": _seq, "buffered": len(_events),
                "dropped": _dropped, "capacity": _events.maxlen}
