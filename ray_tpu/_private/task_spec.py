"""Task/actor spec schema — the typed contract for the dicts that cross
the control plane.

Reference: src/ray/common/task/task_spec.h (+ common.proto TaskSpec) —
the reference compiles its spec into protobuf; here the wire form stays
a plain dict (pickled by the RPC layer), and THIS module is the single
place that says which keys exist, who writes them, and what they mean.
`validate_task_spec` runs unconditionally at submission so schema drift
fails loudly at the producer, not as a KeyError deep inside a worker
(the check is set arithmetic over <=17 keys — cheap enough to always
pay; set RAY_TPU_VALIDATE_SPECS=0 only to bisect the validator itself).
"""
from __future__ import annotations

import os
from typing import Any, TypedDict


class TaskSpec(TypedDict, total=False):
    """A normal-task submission (producer: CoreWorker.submit_task)."""

    task_id: bytes               # 16-byte unique id
    func_hash: bytes             # function-table key (GCS ns=functions)
    args: bytes                  # ser.serialize((args, kwargs))
    return_ids: list             # [16-byte object id, ...]
    owner_addr: tuple            # (host, port) of the owning worker
    retries_left: int            # worker-death retry budget
    reconstructions_left: int    # lineage re-execution budget
    task_desc: str               # human-readable ("task f()")
    job_id: int
    runtime_env: dict            # normalized (content keys, not paths)
    inline_exec: bool            # pump-safe: execute on the transport pump
    inlined: dict                # {ref_id: frame bytes} for small resolved
                                 # args (executor skips the owner round trip)
    dynamic_returns: bool        # num_returns="dynamic"/"streaming": the
                                 # task yields items, each its own object
    trace_ctx: dict              # {"trace_id", "parent_span_id"}
    # actor-call extension (producer: submit_actor_task)
    actor_id: bytes
    method_name: str
    caller_id: str               # submitting worker id (seq scoping)
    caller_epoch: int            # bumped per reconnect
    seq: int                     # per-caller submission order


# Keys every normal-task spec MUST carry (actor calls add their own).
REQUIRED_TASK_KEYS = frozenset({
    "task_id", "func_hash", "args", "return_ids", "owner_addr",
    "retries_left", "task_desc", "job_id",
})

REQUIRED_ACTOR_KEYS = frozenset({
    "task_id", "actor_id", "method_name", "args", "return_ids",
    "owner_addr", "caller_id",
})

# Prefix for driver-local bookkeeping that must NEVER cross the wire
# (CoreWorker._strip_spec removes these before pushing).
LOCAL_KEY_PREFIX = "_"

# Precomputed so the per-submission validator doesn't rebuild the allowed
# set from TypedDict.__annotations__ on every task (hot path).
_DECLARED_KEYS = frozenset(TaskSpec.__annotations__)


def _validation_enabled() -> bool:
    return os.environ.get("RAY_TPU_VALIDATE_SPECS", "1") != "0"


def validate_task_spec(spec: dict[str, Any], *, actor: bool = False):
    """Schema check at the PRODUCER (always on; see module docstring).
    Raises ValueError naming exactly what drifted."""
    if not _validation_enabled():
        return
    required = REQUIRED_ACTOR_KEYS if actor else REQUIRED_TASK_KEYS
    missing = required - spec.keys()
    if missing:
        raise ValueError(
            f"task spec missing required keys {sorted(missing)} "
            f"(schema: _private/task_spec.py)")
    # set-difference FIRST: the per-key startswith loop only runs over
    # leftovers, which are empty for every well-formed spec (hot path)
    unknown = spec.keys() - _DECLARED_KEYS
    if unknown:
        unknown = {k for k in unknown
                   if not k.startswith(LOCAL_KEY_PREFIX)}
    if unknown:
        raise ValueError(
            f"task spec carries undeclared keys {sorted(unknown)} — "
            f"declare them in _private/task_spec.py (the schema is the "
            f"contract both ends compile against)")
    if len(spec.get("task_id", b"")) != 16:
        raise ValueError("task_id must be 16 bytes")
    for rid in spec.get("return_ids", ()):
        if len(rid) != 16:
            raise ValueError("return ids must be 16 bytes")


# --------------------------------------------------------- control RPCs
#
# Producer-side shape checks for the top non-task control messages
# (lease request/grant, actor creation, KV put, pubsub ack). Same
# contract as validate_task_spec: a typo'd field fails AT THE PRODUCER
# with the schema location in the message, instead of a KeyError (or a
# silently-ignored kwarg) on the consumer side. Gated by the same
# RAY_TPU_VALIDATE_SPECS switch.

# strategy keys the raylet lease scheduler understands
# (raylet.rpc_request_worker_lease + the PG/spread policies)
LEASE_STRATEGY_KEYS = frozenset({
    "placement_group_id", "bundle_index", "node_id", "soft", "spread",
    "no_spill", "job",
})

# keys the lessee reads off a grant (_LeasedWorker + return_lease)
REQUIRED_GRANT_KEYS = frozenset({
    "lease_id", "worker_id", "worker_addr", "node_id",
})

# actor-creation spec keys (producer: CoreWorker.create_actor; consumers:
# GCS actor table + raylet _create_actor_locally + worker become_actor)
REQUIRED_ACTOR_SPEC_KEYS = frozenset({
    "class_hash", "class_name", "args", "resources", "max_restarts",
    "max_task_retries", "owner_addr", "job_id",
})


def _fail(what: str, detail: str):
    raise ValueError(
        f"{what}: {detail} (schema: _private/task_spec.py)")


def validate_lease_request(resources: dict, strategy: dict | None):
    if not _validation_enabled():
        return
    if not isinstance(resources, dict):
        _fail("lease request", f"resources must be a dict, "
              f"got {type(resources).__name__}")
    for k, v in resources.items():
        if not isinstance(k, str):
            _fail("lease request", f"resource name {k!r} is not a str")
        if not isinstance(v, (int, float)) or v < 0:
            _fail("lease request",
                  f"resource {k!r} amount {v!r} is not a number >= 0")
    if strategy:
        unknown = strategy.keys() - LEASE_STRATEGY_KEYS
        if unknown:
            _fail("lease request",
                  f"unknown strategy keys {sorted(unknown)} — declare "
                  f"them in LEASE_STRATEGY_KEYS")


def validate_lease_grant(grant: dict):
    if not _validation_enabled():
        return
    missing = REQUIRED_GRANT_KEYS - grant.keys()
    if missing:
        _fail("lease grant", f"missing keys {sorted(missing)}")


def validate_actor_spec(actor_id: bytes, spec: dict):
    if not _validation_enabled():
        return
    if len(actor_id) != 16:
        _fail("actor registration", "actor_id must be 16 bytes")
    if not isinstance(spec, dict):
        _fail("actor registration", "spec must be a dict")
    missing = REQUIRED_ACTOR_SPEC_KEYS - spec.keys()
    if missing:
        _fail("actor registration", f"missing spec keys {sorted(missing)}")


def validate_kv_put(ns: str, key: bytes, value: bytes):
    if not _validation_enabled():
        return
    if not isinstance(ns, str):
        _fail("kv_put", f"namespace must be str, got {type(ns).__name__}")
    if not isinstance(key, (bytes, bytearray)):
        _fail("kv_put", f"key must be bytes, got {type(key).__name__}")
    if not isinstance(value, (bytes, bytearray, memoryview)):
        _fail("kv_put",
              f"value must be bytes, got {type(value).__name__} — "
              f"serialize before the control plane, not after")


def validate_pubsub_ack(sub_id: str, after_seq: int):
    if not _validation_enabled():
        return
    if not isinstance(sub_id, str) or not sub_id:
        _fail("pubsub poll/ack", f"sub_id must be a non-empty str, "
              f"got {sub_id!r}")
    if not isinstance(after_seq, int) or after_seq < 0:
        _fail("pubsub poll/ack",
              f"after_seq must be an int >= 0, got {after_seq!r}")


# method -> kwargs validator, consulted by the GCS client boundary
# (protocol.ReconnectingRpcClient) so every producer of these messages
# is covered without per-call-site plumbing.
def _check_kv_put(kw):
    validate_kv_put(kw.get("ns"), kw.get("key"), kw.get("value"))


def _check_register_actor(kw):
    validate_actor_spec(kw.get("actor_id", b""), kw.get("spec", {}))


def _check_psub_poll(kw):
    validate_pubsub_ack(kw.get("sub_id", ""), kw.get("after_seq", -1))


def _check_lease_request(kw):
    validate_lease_request(kw.get("resources", {}), kw.get("strategy"))


CONTROL_RPC_VALIDATORS = {
    "kv_put": _check_kv_put,
    "register_actor": _check_register_actor,
    "psub_poll": _check_psub_poll,
    "request_worker_lease": _check_lease_request,
}


def validate_control_rpc(method: str, kwargs: dict):
    """Producer-boundary dispatch: validates the message shape of the
    top control RPCs; unknown methods pass through untouched."""
    fn = CONTROL_RPC_VALIDATORS.get(method)
    if fn is not None:
        fn(kwargs)
