"""Task/actor spec schema — the typed contract for the dicts that cross
the control plane.

Reference: src/ray/common/task/task_spec.h (+ common.proto TaskSpec) —
the reference compiles its spec into protobuf; here the wire form stays
a plain dict (pickled by the RPC layer), and THIS module is the single
place that says which keys exist, who writes them, and what they mean.
`validate_task_spec` runs unconditionally at submission so schema drift
fails loudly at the producer, not as a KeyError deep inside a worker
(the check is set arithmetic over <=17 keys — cheap enough to always
pay; set RAY_TPU_VALIDATE_SPECS=0 only to bisect the validator itself).
"""
from __future__ import annotations

import os
from typing import Any, TypedDict


class TaskSpec(TypedDict, total=False):
    """A normal-task submission (producer: CoreWorker.submit_task)."""

    task_id: bytes               # 16-byte unique id
    func_hash: bytes             # function-table key (GCS ns=functions)
    args: bytes                  # ser.serialize((args, kwargs))
    return_ids: list             # [16-byte object id, ...]
    owner_addr: tuple            # (host, port) of the owning worker
    retries_left: int            # worker-death retry budget
    reconstructions_left: int    # lineage re-execution budget
    task_desc: str               # human-readable ("task f()")
    job_id: int
    runtime_env: dict            # normalized (content keys, not paths)
    inline_exec: bool            # pump-safe: execute on the transport pump
    inlined: dict                # {ref_id: frame bytes} for small resolved
                                 # args (executor skips the owner round trip)
    dynamic_returns: bool        # num_returns="dynamic"/"streaming": the
                                 # task yields items, each its own object
    trace_ctx: dict              # {"trace_id", "parent_span_id"}
    # actor-call extension (producer: submit_actor_task)
    actor_id: bytes
    method_name: str
    caller_id: str               # submitting worker id (seq scoping)
    caller_epoch: int            # bumped per reconnect
    seq: int                     # per-caller submission order


# Keys every normal-task spec MUST carry (actor calls add their own).
REQUIRED_TASK_KEYS = frozenset({
    "task_id", "func_hash", "args", "return_ids", "owner_addr",
    "retries_left", "task_desc", "job_id",
})

REQUIRED_ACTOR_KEYS = frozenset({
    "task_id", "actor_id", "method_name", "args", "return_ids",
    "owner_addr", "caller_id",
})

# Prefix for driver-local bookkeeping that must NEVER cross the wire
# (CoreWorker._strip_spec removes these before pushing).
LOCAL_KEY_PREFIX = "_"

# Precomputed so the per-submission validator doesn't rebuild the allowed
# set from TypedDict.__annotations__ on every task (hot path).
_DECLARED_KEYS = frozenset(TaskSpec.__annotations__)


def _validation_enabled() -> bool:
    return os.environ.get("RAY_TPU_VALIDATE_SPECS", "1") != "0"


def validate_task_spec(spec: dict[str, Any], *, actor: bool = False):
    """Schema check at the PRODUCER (always on; see module docstring).
    Raises ValueError naming exactly what drifted."""
    if not _validation_enabled():
        return
    required = REQUIRED_ACTOR_KEYS if actor else REQUIRED_TASK_KEYS
    missing = required - spec.keys()
    if missing:
        raise ValueError(
            f"task spec missing required keys {sorted(missing)} "
            f"(schema: _private/task_spec.py)")
    # set-difference FIRST: the per-key startswith loop only runs over
    # leftovers, which are empty for every well-formed spec (hot path)
    unknown = spec.keys() - _DECLARED_KEYS
    if unknown:
        unknown = {k for k in unknown
                   if not k.startswith(LOCAL_KEY_PREFIX)}
    if unknown:
        raise ValueError(
            f"task spec carries undeclared keys {sorted(unknown)} — "
            f"declare them in _private/task_spec.py (the schema is the "
            f"contract both ends compile against)")
    if len(spec.get("task_id", b"")) != 16:
        raise ValueError("task_id must be 16 bytes")
    for rid in spec.get("return_ids", ()):
        if len(rid) != 16:
            raise ValueError("return ids must be 16 bytes")
