"""Per-environment pip venvs with a ref-counted URI cache.

Reference: python/ray/_private/runtime_env/pip.py (a venv per pip-spec
hash, created on first use by the node's agent) + uri_cache.py (cached
envs are ref-counted by the workers using them; unreferenced envs are
evicted LRU when the cache exceeds its budget).

TPU-native simplifications, documented as design deltas:
- an "env" is a ``pip install --target`` tree, not a full venv:
  activation is sys.path injection of that tree (plus py_modules
  paths). Same interpreter, so pure-Python and C-extension wheels both
  import, the baked-in stack (jax, numpy, ...) stays visible
  underneath, and a worker can switch envs without a process swap.
  (A real venv is also wrong here mechanically: the image's
  interpreter is itself a venv, and a nested ``python -m venv
  --system-site-packages`` resolves "system" past it, losing
  setuptools et al.)
- installs run with --no-index by default unless the spec names
  requirement URLs: this image has no network egress, and hermetic
  installs from local wheels/sdists are the supported path.
"""
from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import threading
import time

DEFAULT_CACHE_ROOT = "/tmp/ray_tpu/runtime_envs"
_MARKER = "RAY_TPU_ENV_OK"


def env_hash(pip: list[str] | None, py_modules: list[str] | None) -> str:
    """Content hash identifying one environment (the cache URI)."""
    spec = {"pip": sorted(pip or []),
            "py_modules": sorted(os.path.abspath(p)
                                 for p in (py_modules or []))}
    return "pipenv-" + hashlib.sha256(
        json.dumps(spec, sort_keys=True).encode()).hexdigest()[:20]


class PipEnvCache:
    """Node-local venv cache. One instance per process; the directory
    layout is shared across processes (creation is marker-file guarded,
    losers of a concurrent-create race reuse the winner's env)."""

    def __init__(self, root: str = DEFAULT_CACHE_ROOT,
                 max_cached: int = 8):
        self.root = root
        self.max_cached = max_cached
        self._refs: dict[str, int] = {}
        self._lock = threading.Lock()
        self.creations = 0        # diagnostics: cache-miss installs
        os.makedirs(root, exist_ok=True)

    # ------------------------------------------------------------ lifecycle
    def get_or_create(self, pip: list[str] | None = None,
                      py_modules: list[str] | None = None,
                      timeout_s: float = 300.0) -> dict:
        """Ensure the env exists; returns
        {"uri", "site_dirs": [paths to prepend to sys.path]}."""
        uri = env_hash(pip, py_modules)
        env_dir = os.path.join(self.root, uri)
        marker = os.path.join(env_dir, _MARKER)
        if not os.path.exists(marker):
            self._create(env_dir, marker, pip or [], py_modules or [],
                         timeout_s)
        site_dirs = []
        venv_site = self._site_dir(env_dir)
        if venv_site and os.path.isdir(venv_site):
            site_dirs.append(venv_site)
        mod_root = os.path.join(env_dir, "py_modules")
        if os.path.isdir(mod_root):
            site_dirs.append(mod_root)
        return {"uri": uri, "site_dirs": site_dirs}

    def _create(self, env_dir: str, marker: str, pip: list[str],
                py_modules: list[str], timeout_s: float):
        lock_dir = env_dir + ".lock"
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                os.makedirs(lock_dir)
                break               # we are the creator
            except FileExistsError:
                if os.path.exists(marker):
                    return          # another process finished it
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"runtime env creation stuck: {lock_dir}")
                time.sleep(0.2)
        try:
            if os.path.exists(marker):
                return
            self.creations += 1
            import shutil

            shutil.rmtree(env_dir, ignore_errors=True)  # half-built prior
            os.makedirs(env_dir, exist_ok=True)
            if pip:
                cmd = [sys.executable, "-m", "pip", "install",
                       "--no-build-isolation", "--target",
                       os.path.join(env_dir, "site")]
                if not any(r.startswith(("http://", "https://"))
                           for r in pip):
                    cmd.append("--no-index")
                p = subprocess.run(cmd + list(pip), capture_output=True,
                                   timeout=timeout_s, text=True)
                if p.returncode != 0:
                    from ray_tpu.exceptions import RuntimeEnvSetupError

                    raise RuntimeEnvSetupError(
                        f"pip install failed for {pip}:\n{p.stderr[-2000:]}")
            if py_modules:
                mod_root = os.path.join(env_dir, "py_modules")
                os.makedirs(mod_root, exist_ok=True)
                import shutil

                for src in py_modules:
                    src = os.path.abspath(src)
                    dst = os.path.join(mod_root, os.path.basename(src))
                    if os.path.isdir(src):
                        shutil.copytree(src, dst, dirs_exist_ok=True)
                    else:
                        shutil.copy2(src, dst)
            with open(marker, "w") as f:
                f.write(str(time.time()))
        finally:
            try:
                os.rmdir(lock_dir)
            except OSError:
                pass

    def _site_dir(self, env_dir: str) -> str | None:
        cand = os.path.join(env_dir, "site")
        return cand if os.path.isdir(cand) else None

    # ----------------------------------------------------------- refcounts
    def acquire(self, uri: str):
        with self._lock:
            self._refs[uri] = self._refs.get(uri, 0) + 1

    def release(self, uri: str):
        with self._lock:
            n = self._refs.get(uri, 0) - 1
            if n <= 0:
                self._refs.pop(uri, None)
            else:
                self._refs[uri] = n
        self._maybe_evict()

    def _maybe_evict(self):
        """LRU-evict unreferenced envs beyond max_cached (uri_cache.py's
        do-not-evict-while-referenced rule)."""
        try:
            entries = []
            for name in os.listdir(self.root):
                if not name.startswith("pipenv-"):
                    continue
                marker = os.path.join(self.root, name, _MARKER)
                if not os.path.exists(marker):
                    continue
                entries.append((os.path.getmtime(marker), name))
        except OSError:
            return
        if len(entries) <= self.max_cached:
            return
        import shutil

        entries.sort()              # oldest first
        with self._lock:
            referenced = set(self._refs)
        for _, name in entries[:len(entries) - self.max_cached]:
            if name in referenced:
                continue
            shutil.rmtree(os.path.join(self.root, name),
                          ignore_errors=True)


_node_cache: PipEnvCache | None = None
_node_cache_lock = threading.Lock()


def node_env_cache() -> PipEnvCache:
    """Process-wide cache instance (one per worker/raylet process)."""
    from ray_tpu._private.config import get_config

    global _node_cache
    with _node_cache_lock:
        if _node_cache is None:
            _node_cache = PipEnvCache(
                str(get_config("runtime_env_dir")),
                max_cached=int(get_config("runtime_env_cache_max")))
        return _node_cache
