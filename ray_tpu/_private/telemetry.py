"""Internal runtime metric catalog — every core metric declared in one place.

The user-facing primitives live in ray_tpu/util/metrics.py (Counter /
Gauge / Histogram, aggregated by `metrics_summary()` and rendered at the
dashboard's /metrics). This module is the RUNTIME'S OWN use of them:
transports, scheduler, object store, retry/fault plane. Reference tier:
Ray's core "system metrics" (ray_grpc_server_*, ray_scheduler_*,
ray_object_store_*) emitted by core components into the same Prometheus
pipeline user metrics ride.

Contract (enforced by the catalog lint in tests/test_telemetry_metrics.py):

- every internal metric name is declared HERE, in ``CATALOG``;
- names are ``ray_tpu_``-prefixed and end in a unit suffix from
  ``ALLOWED_SUFFIXES`` (Prometheus naming conventions);
- call sites reference metrics through ``counter_inc`` / ``gauge_set`` /
  ``observe`` by catalog name — an undeclared name raises KeyError at
  the call site, so instrumentation can't drift from the catalog.

Overhead: the disabled path (``RAY_TPU_INTERNAL_TELEMETRY=0``) is one
module-global bool check per call site. Enabled, a recording is one
dict lookup + the util/metrics lock'd update (~1-2µs) — noise against
the RPC/store operation it measures; nothing extra happens when no
scraper reads /metrics (recording cost is the whole cost).
"""
from __future__ import annotations

import os
import threading

ENABLED = os.environ.get("RAY_TPU_INTERNAL_TELEMETRY", "1") != "0"

# Prometheus-convention unit suffixes internal metric names must end in
# (counters additionally use `_total` per convention; `_tasks` /
# `_messages` are the "unit is the thing counted" form for gauges;
# `_ratio` is the Prometheus-convention dimensionless 0..1 form).
ALLOWED_SUFFIXES = ("_total", "_seconds", "_bytes", "_tasks", "_messages",
                    "_ratio", "_blocks", "_objects")

_RPC_BOUNDARIES = [0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0]

# name -> spec. `kind` is the util/metrics class name; `tags` the label
# keys call sites pass (bounded cardinality: method names, roles,
# node ids — never task/object ids).
CATALOG: dict[str, dict] = {
    # --- transports (protocol.py / native_rpc.py) ---
    "ray_tpu_rpc_latency_seconds": {
        "kind": "Histogram", "tags": ("method", "role"),
        "boundaries": _RPC_BOUNDARIES,
        "description": "Client-observed latency of synchronous "
                       "control-plane RPC calls",
    },
    "ray_tpu_rpc_errors_total": {
        "kind": "Counter", "tags": ("method", "role", "kind"),
        "description": "Synchronous RPC calls that failed "
                       "(kind=timeout|connection_lost)",
    },
    # --- unified retry policy (retry.py) ---
    "ray_tpu_retry_attempts_total": {
        "kind": "Counter", "tags": ("method",),
        "description": "Actual retries executed under the control-plane "
                       "retry policy (first attempts are not counted)",
    },
    "ray_tpu_retry_budget_exhausted_total": {
        "kind": "Counter", "tags": (),
        "description": "Retries refused because the process-wide retry "
                       "budget was drained",
    },
    # --- fault injection (fault_injection.py) ---
    "ray_tpu_faults_injected_total": {
        "kind": "Counter", "tags": ("action", "method"),
        "description": "Fault-injection rules fired, by action "
                       "(drop/delay/dup/disconnect/slow_reply) and method",
    },
    # --- scheduler (raylet.py) ---
    "ray_tpu_scheduler_queue_tasks": {
        "kind": "Gauge", "tags": ("node_id",),
        "description": "Lease/actor-creation requests queued on this "
                       "raylet waiting for resources",
    },
    "ray_tpu_lease_grant_latency_seconds": {
        "kind": "Histogram", "tags": ("node_id",),
        "boundaries": _RPC_BOUNDARIES,
        "description": "Time from lease request arrival to local grant "
                       "(spillbacks excluded)",
    },
    # --- object store (store_client.py) ---
    "ray_tpu_object_store_put_bytes_total": {
        "kind": "Counter", "tags": (),
        "description": "Bytes written into the local shared-memory "
                       "object store (including spilled puts)",
    },
    "ray_tpu_object_store_get_total": {
        "kind": "Counter", "tags": ("result",),
        "description": "Local object-store lookups (result=hit|miss)",
    },
    # --- memory anatomy (memory_anatomy.py provenance ledger) ---
    "ray_tpu_store_bytes": {
        "kind": "Gauge", "tags": ("category", "state"),
        "description": "Live object-store bytes by provenance category "
                       "(task_arg/task_return/collective_segment/"
                       "serve_weights/data_staging/checkpoint/other), "
                       "state=live",
    },
    "ray_tpu_store_objects": {
        "kind": "Gauge", "tags": ("category",),
        "description": "Live object-store object count by provenance "
                       "category",
    },
    "ray_tpu_store_orphan_bytes": {
        "kind": "Gauge", "tags": ("category", "reason"),
        "description": "Bytes the leak sweep classified as orphaned "
                       "(reason=owner_dead|group_destroyed|epoch_stale; "
                       "category=all,reason=all carries the sum)",
    },
    "ray_tpu_store_frees_dropped_total": {
        "kind": "Counter", "tags": ("stage",),
        "description": "Deletes lost on the one-way owner→GCS→raylet "
                       "free pipeline "
                       "(stage=owner_push|gcs_fanout|raylet_delete)",
    },
    "ray_tpu_store_free_resends_total": {
        "kind": "Counter", "tags": (),
        "description": "Bounded best-effort re-sends of free fan-outs "
                       "whose first push found no raylet connection "
                       "(config store_free_resend)",
    },
    # --- train-state accounting (ddp.py / train_step.py) ---
    "ray_tpu_train_state_bytes": {
        "kind": "Gauge", "tags": ("kind", "rank"),
        "description": "Exact per-rank train-state bytes from the "
                       "deterministic flatten "
                       "(kind=params|grads|opt_state|bucket_inflight) — "
                       "the gauge the ZeRO arc diffs before/after "
                       "sharding",
    },
    # --- durable GCS store (gcs_store.py) ---
    "ray_tpu_gcs_store_ops_total": {
        "kind": "Counter", "tags": ("backend", "op"),
        "description": "Durable GCS store operations, by backend "
                       "(sqlite/log/memory) and op (put/get/delete)",
    },
    # --- pubsub (pubsub.py) ---
    "ray_tpu_pubsub_backlog_messages": {
        "kind": "Gauge", "tags": (),
        "description": "Messages parked in long-poll subscriber "
                       "mailboxes after the latest publish",
    },
    "ray_tpu_pubsub_dropped_total": {
        "kind": "Counter", "tags": (),
        "description": "Messages dropped by mailbox overflow "
                       "(slow long-poll consumers)",
    },
    "ray_tpu_pubsub_resyncs_total": {
        "kind": "Counter", "tags": (),
        "description": "Snapshot-resyncs performed by long-poll "
                       "subscribers after a feed gap (mailbox overflow "
                       "or publisher-side GC)",
    },
    # --- GCS control plane at scale (gcs.py, cluster soak) ---
    "ray_tpu_gcs_death_fanout_seconds": {
        "kind": "Histogram", "tags": (),
        "boundaries": _RPC_BOUNDARIES,
        "description": "Wall time of the off-lock death-feed broadcast "
                       "per swept node-death batch (coalesced or "
                       "single)",
    },
    "ray_tpu_gcs_register_throttled_total": {
        "kind": "Counter", "tags": (),
        "description": "register_node calls that queued on the bounded "
                       "admission gate during a registration burst",
    },
    # --- multi-tenant scheduling (gcs.py job registry) ---
    # job names are operator-chosen and bounded (one per tenant /
    # workload), the same cardinality class as Serve deployment names
    "ray_tpu_preemptions_total": {
        "kind": "Counter", "tags": ("job",),
        "description": "Placement groups preempted (bundles reclaimed "
                       "after the grace window) per victim job — the "
                       "priority plane's graceful-degradation counter",
    },
    "ray_tpu_quota_rejections_total": {
        "kind": "Counter", "tags": ("job",),
        "description": "Admissions refused because they would push a "
                       "job over its resource quota: placement groups "
                       "held PENDING at the GCS (counted once per "
                       "transition into the blocked state) and leases "
                       "throttled at raylet grant",
    },
    "ray_tpu_job_dominant_share_ratio": {
        "kind": "Gauge", "tags": ("job",),
        "description": "Each job's dominant resource share — max over "
                       "resources of usage / (quota if set, else "
                       "cluster total) — the weight the fair-share "
                       "scheduler orders pending bundles by",
    },
    # --- event log (events.py) ---
    "ray_tpu_events_dropped_total": {
        "kind": "Counter", "tags": (),
        "description": "Structured events dropped from the bounded "
                       "per-process event ring",
    },
    # --- collective data plane (util/collective/telemetry.py) ---
    # group names are operator-chosen but bounded (one per worker gang /
    # Tune trial family), same cardinality class as method names
    "ray_tpu_collective_latency_seconds": {
        "kind": "Histogram", "tags": ("op", "backend", "group"),
        "boundaries": [0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                       5.0, 30.0],
        "description": "Caller-observed wall time of one collective op "
                       "on one rank (allreduce/broadcast/.../barrier, "
                       "host and xla backends)",
    },
    "ray_tpu_collective_bytes_total": {
        "kind": "Counter", "tags": ("op", "backend", "group"),
        "description": "Per-rank payload bytes moved through collective "
                       "ops (payload, not wire bytes — algorithm-"
                       "independent)",
    },
    "ray_tpu_collective_stragglers_total": {
        "kind": "Counter", "tags": ("group", "op"),
        "description": "Ranks flagged by the straggler detector (arrival "
                       "lag > configured multiple of the group median)",
    },
    "ray_tpu_collective_segments_total": {
        "kind": "Counter", "tags": ("op", "group"),
        "description": "Ring segments sent by the pipelined host "
                       "collective data path (one-way zero-copy frames; "
                       "0 when RAY_TPU_COLLECTIVE_PIPELINE=0)",
    },
    "ray_tpu_collective_wire_bytes_total": {
        "kind": "Counter", "tags": ("op", "group", "format"),
        "description": "Actual ring-segment bytes this rank put on the "
                       "wire (socket or shm), by wire format "
                       "(format=off|bf16|int8; forwarded frames count "
                       "under the op's active format). Against "
                       "ray_tpu_collective_bytes_total's payload bytes "
                       "this is the live compression ratio",
    },
    "ray_tpu_collective_quant_error_ratio": {
        "kind": "Histogram", "tags": ("op", "format"),
        "boundaries": [1e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2e-3, 4e-3,
                       8e-3, 2e-2],
        "description": "Measured max-abs quantization error of one "
                       "sampled segment per collective op, normalized "
                       "by the segment's absmax (bf16 bound: 2^-8 ~ "
                       "0.0039 of each element; int8 bound: 1/254 ~ "
                       "0.0039 of the block absmax)",
    },
    # --- async collective plane (util/collective/async_handles.py) ---
    "ray_tpu_collective_async_inflight_tasks": {
        "kind": "Gauge", "tags": ("group",),
        "description": "Async collective ops submitted but not yet "
                       "completed on this rank (queued on the group's "
                       "issue thread + the op currently on the wire)",
    },
    # --- bucketed DDP gradient sync (train/ddp.py) ---
    "ray_tpu_train_buckets_total": {
        "kind": "Counter", "tags": ("group",),
        "description": "Gradient-sync buckets launched by "
                       "train.ddp.sync_gradients (one async allreduce "
                       "each; 0 when RAY_TPU_TRAIN_BUCKET_DDP=0)",
    },
    "ray_tpu_train_bucket_bytes": {
        "kind": "Histogram", "tags": ("group",),
        "boundaries": [65536, 262144, 1048576, 4194304, 16777216,
                       67108864, 268435456],
        "description": "Payload size of one gradient-sync bucket "
                       "(packed contiguous grads; targeted by "
                       "RAY_TPU_TRAIN_GRAD_BUCKET_BYTES)",
    },
    "ray_tpu_train_bucket_sync_seconds": {
        "kind": "Histogram", "tags": ("group",),
        "boundaries": [0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                       5.0, 30.0],
        "description": "Launch-to-completion latency of one bucket's "
                       "async allreduce (background comm; compare "
                       "against _bucket_wait_seconds — the exposed "
                       "part — for the live overlap fraction)",
    },
    "ray_tpu_train_bucket_wait_seconds": {
        "kind": "Histogram", "tags": ("group",),
        "boundaries": [0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1,
                       0.5, 1.0, 5.0],
        "description": "Wall time the train loop was actually BLOCKED "
                       "in handle.wait() per bucket at the optimizer "
                       "boundary — the comm the backward pass failed "
                       "to hide",
    },
    "ray_tpu_train_param_gather_seconds": {
        "kind": "Histogram", "tags": ("group",),
        "boundaries": [0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                       5.0, 30.0],
        "description": "Launch-to-completion latency of one bucket's "
                       "async param-shard allgather (ZeRO mode: the "
                       "updated shard returning to every rank; "
                       "background comm riding the issue thread)",
    },
    "ray_tpu_train_param_gather_wait_seconds": {
        "kind": "Histogram", "tags": ("group",),
        "boundaries": [0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1,
                       0.5, 1.0, 5.0],
        "description": "Wall time the train loop was actually BLOCKED "
                       "waiting a param-shard allgather at first use "
                       "of the new params (ZeRO mode) — the gather "
                       "comm the inter-step window failed to hide",
    },
    # --- gang fault tolerance (train/, util/collective) ---
    "ray_tpu_train_gang_restarts_total": {
        "kind": "Counter", "tags": ("group",),
        "description": "Training gang restarts driven by fit()'s "
                       "FailureConfig retry loop (teardown + rebuild + "
                       "checkpoint resume after a worker/rank failure)",
    },
    "ray_tpu_collective_groups_poisoned_total": {
        "kind": "Counter", "tags": ("group",),
        "description": "Collective groups poisoned in this process after "
                       "a member death (pending/future ops raise "
                       "CollectiveGroupError instead of hanging)",
    },
    "ray_tpu_collective_stale_epoch_total": {
        "kind": "Counter", "tags": ("group",),
        "description": "Collective frames / shm notifies rejected at "
                       "ingest because they carried a previous group "
                       "incarnation's epoch (plus dead-epoch mailbox "
                       "entries swept at group rejoin)",
    },
    # --- multi-slice MPMD pipeline training (train/pipeline/) ---
    # stage indices are bounded (pipeline depth, single digits in
    # practice); group names are the same cardinality class as
    # collective groups
    "ray_tpu_pipeline_bubble_seconds": {
        "kind": "Histogram", "tags": ("group", "stage"),
        "boundaries": [0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                       30.0],
        "description": "Per-step wall time one pipeline stage spent "
                       "parked in schedule stalls (waiting for an "
                       "upstream activation, a downstream gradient, or "
                       "an in-flight-window credit) — the measured "
                       "bubble the (P-1)/(M+P-1) schedule theory "
                       "predicts",
    },
    "ray_tpu_pipeline_microbatches_total": {
        "kind": "Counter", "tags": ("group", "stage", "phase"),
        "description": "Microbatches processed by one pipeline stage, "
                       "split by phase (forward/backward)",
    },
    "ray_tpu_pipeline_step_seconds": {
        "kind": "Histogram", "tags": ("group", "stage"),
        "boundaries": [0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0],
        "description": "Wall time of one optimizer step on one pipeline "
                       "stage (all microbatch forwards + backwards + "
                       "the intra-stage grad allreduce + the update)",
    },
    # --- streaming data plane (data/_internal/streaming/) ---
    # consumer names are bounded: "default", bench harness labels, or
    # train/<dataset>/rank<k> (one per gang member) — same cardinality
    # class as collective group names
    "ray_tpu_data_wait_seconds": {
        "kind": "Histogram", "tags": ("consumer",),
        "boundaries": [0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1,
                       0.5, 1.0, 5.0],
        "description": "Wall time a dataset consumer was blocked "
                       "waiting for its next batch (fetch + slice + "
                       "device transfer not yet overlapped) — the "
                       "input-gates-the-step signal; per-step data "
                       "wait / step time is the ingest health ratio",
    },
    "ray_tpu_data_prefetch_depth_blocks": {
        "kind": "Gauge", "tags": ("consumer",),
        "description": "Blocks currently buffered ahead of a streaming "
                       "dataset consumer (bounded by "
                       "RAY_TPU_DATA_PREFETCH_BLOCKS; pinned in the shm "
                       "store, not heap copies)",
    },
    "ray_tpu_data_blocks_total": {
        "kind": "Counter", "tags": ("consumer", "source"),
        "description": "Blocks fed to streaming dataset consumers by "
                       "origin (source=local|remote): locality-aware "
                       "pull ordering should keep remote pulls a "
                       "minority when blocks were produced on this node",
    },
    # --- pjit compile path (parallel/compile_watch.py) ---
    "ray_tpu_pjit_compile_seconds": {
        "kind": "Histogram", "tags": ("fn",),
        "boundaries": [0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 1200.0],
        "description": "Wall time of a compile-cache-miss call of an "
                       "instrumented jitted function (trace + XLA "
                       "compile + first run)",
    },
    "ray_tpu_pjit_cache_total": {
        "kind": "Counter", "tags": ("fn", "result"),
        "description": "Instrumented jitted-function calls by compile-"
                       "cache outcome (result=hit|miss) — a miss burst "
                       "mid-training means shape churn is recompiling "
                       "the step",
    },
    "ray_tpu_mesh_build_seconds": {
        "kind": "Histogram", "tags": ("kind",),
        "boundaries": [0.001, 0.01, 0.1, 0.5, 1.0, 5.0, 30.0],
        "description": "Device-mesh construction time "
                       "(kind=mesh|hybrid_mesh)",
    },
    # --- serve data plane (serve/_private/*, serve/batching.py) ---
    # deployment names are operator-chosen and bounded (one per deployed
    # model); fn names likewise — same cardinality class as RPC methods.
    # Replica ids are NOT used as tags (they contain uuids and churn).
    "ray_tpu_serve_requests_total": {
        "kind": "Counter", "tags": ("deployment", "result"),
        "description": "Serve requests completed at the handle layer "
                       "(result=ok|error)",
    },
    "ray_tpu_serve_request_latency_seconds": {
        "kind": "Histogram", "tags": ("deployment",),
        "boundaries": _RPC_BOUNDARIES,
        "description": "End-to-end handle-observed request latency "
                       "(router queueing + replica execution)",
    },
    "ray_tpu_serve_queue_depth_tasks": {
        "kind": "Gauge", "tags": ("deployment", "role"),
        "description": "Router-side demand: callers waiting for a "
                       "replica slot plus requests in flight (the "
                       "autoscaler's primary signal). The role tag "
                       "keeps the driver handle's router and the HTTP "
                       "proxy's router as separate series — the "
                       "cross-process gauge merge keeps the last value "
                       "per tag set, so without it one idle router "
                       "masks the other's backlog; sum over roles for "
                       "total demand",
    },
    "ray_tpu_serve_shed_total": {
        "kind": "Counter", "tags": ("deployment",),
        "description": "Requests shed by admission control "
                       "(ServeOverloadedError: all replicas at "
                       "max_ongoing_requests, bounded queue full)",
    },
    "ray_tpu_serve_failovers_total": {
        "kind": "Counter", "tags": ("deployment",),
        "description": "Requests re-dispatched to a surviving replica "
                       "after their assigned replica died or started "
                       "draining mid-request",
    },
    "ray_tpu_serve_replicas_tasks": {
        "kind": "Gauge", "tags": ("deployment", "state"),
        "description": "Replica FSM occupancy per deployment "
                       "(state=starting|running|stopping|target)",
    },
    "ray_tpu_serve_replica_restarts_total": {
        "kind": "Counter", "tags": ("deployment", "reason"),
        "description": "Replicas replaced by the controller "
                       "(reason=death|health|init)",
    },
    "ray_tpu_serve_autoscale_total": {
        "kind": "Counter", "tags": ("deployment", "direction"),
        "description": "Autoscale decisions applied after hysteresis "
                       "(direction=up|down)",
    },
    # --- serve tenancy (job-plane capacity: controller.py) ---
    "ray_tpu_serve_warned_replicas_tasks": {
        "kind": "Gauge", "tags": ("deployment",),
        "description": "Replicas whose capacity gang is under a "
                       "preemption warning (already-lost capacity: the "
                       "autoscaler starts replacements before the grace "
                       "window expires) — nonzero spans are preemption "
                       "storms in flight",
    },
    "ray_tpu_serve_capacity_wait_seconds": {
        "kind": "Histogram", "tags": ("deployment",),
        "boundaries": [0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0],
        "description": "Spike-to-placed latency: time from requesting a "
                       "replica's capacity gang in the job plane to its "
                       "CREATED (includes any preemption grace window "
                       "the plane had to burn to free the capacity)",
    },
    "ray_tpu_serve_preempt_drains_total": {
        "kind": "Counter", "tags": ("deployment", "reason"),
        "description": "Replica drains begun through the preemption-"
                       "warning machinery (reason=preempted for an "
                       "external/chaos warning, scale_down for the "
                       "controller's own pg_name-narrowed self-preempt)",
    },
    "ray_tpu_serve_batch_size_tasks": {
        "kind": "Histogram", "tags": ("fn",),
        "boundaries": [1, 2, 4, 8, 16, 32, 64, 128],
        "description": "Executed @serve.batch batch sizes (after "
                       "shape-bucket padding — the batch dimension the "
                       "jitted program actually compiled for)",
    },
    "ray_tpu_serve_batch_pad_waste_tasks": {
        "kind": "Histogram", "tags": ("fn",),
        "boundaries": [1, 2, 4, 8, 16, 32, 64],
        "description": "Padded slots per executed batch (bucket size "
                       "minus real requests): the compute wasted to "
                       "keep the pjit cache at a handful of shapes",
    },
    # --- sharded checkpointing (train/sharded_checkpoint.py) ---
    "ray_tpu_checkpoint_write_seconds": {
        "kind": "Histogram", "tags": ("group",),
        "boundaries": [0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                       30.0, 120.0],
        "description": "Wall time of one rank's shard write (serialize "
                       "excluded: temp-file write + fsync + rename + "
                       "dir fsync + digest) — off the step loop when "
                       "RAY_TPU_CHECKPOINT_ASYNC is on",
    },
    "ray_tpu_checkpoint_bytes": {
        "kind": "Histogram", "tags": ("group",),
        "boundaries": [65536, 262144, 1048576, 4194304, 16777216,
                       67108864, 268435456],
        "description": "Size of one rank's checkpoint shard (its ZeRO "
                       "param slices + optimizer-state slots, npz) — "
                       "O(model/world) per rank, sum over ranks for the "
                       "generation total",
    },
    "ray_tpu_checkpoint_quarantined_total": {
        "kind": "Counter", "tags": ("reason",),
        "description": "Checkpoint generations quarantined at restore "
                       "(reason=torn|digest_mismatch|size_mismatch|"
                       "shard_missing|plan_mismatch) — each one also "
                       "records a CHECKPOINT_QUARANTINED event naming "
                       "the bad shard",
    },
    "ray_tpu_checkpoint_restore_seconds": {
        "kind": "Histogram", "tags": ("group",),
        "boundaries": [0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                       30.0, 120.0],
        "description": "Wall time of one rank's sharded restore (scan + "
                       "verify digests + param reassembly + elastic "
                       "opt-state re-slice)",
    },
    # --- step anatomy + flight recorder (parallel/step_anatomy.py,
    # _private/flight_recorder.py) ---
    "ray_tpu_step_seconds": {
        "kind": "Histogram", "tags": (),
        "boundaries": [0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                       30.0, 120.0],
        "description": "Wall time of one train-loop step on one rank "
                       "(the interval between session.report calls, "
                       "stamped by the step-anatomy plane)",
    },
    "ray_tpu_step_regressions_total": {
        "kind": "Counter", "tags": (),
        "description": "STEP_REGRESSION firings: rolling p50 step time "
                       "drifted beyond step_regression_multiple x the "
                       "prior window's p50",
    },
    "ray_tpu_flight_recorder_dumps_total": {
        "kind": "Counter", "tags": ("trigger",),
        "description": "Black-box dump directories written, by trigger "
                       "(GANG_FAILED/collective_poison/actor_death/"
                       "manual/...)",
    },
    # --- telemetry ring overflow (util/tracing.py, _private/profiling.py) ---
    "ray_tpu_trace_dropped_total": {
        "kind": "Counter", "tags": (),
        "description": "Tracing spans evicted from the bounded "
                       "per-process span ring (a non-zero rate means "
                       "fused trace windows are incomplete)",
    },
    "ray_tpu_timeline_dropped_total": {
        "kind": "Counter", "tags": (),
        "description": "Chrome-timeline spans evicted from the bounded "
                       "per-process profiling ring (merged timelines "
                       "carry a drop-marker metadata row)",
    },
    # --- per-device telemetry (_private/tpu_probe.py) ---
    # node tag is load-bearing: each host's probe subprocess numbers its
    # local devices from 0 (no jax.distributed world), so without it a
    # multi-host cluster's gauges would collide and last-write-wins
    "ray_tpu_device_hbm_bytes": {
        "kind": "Gauge", "tags": ("node", "device", "platform", "stat"),
        "description": "Per-device memory from the subprocess device "
                       "probe (stat=in_use|limit; HBM on TPU, host "
                       "allocator bytes on the CPU fallback)",
    },
}

_lock = threading.Lock()
_metrics: dict[str, object] = {}


def _get(name: str):
    """The live metric instance for a CATALOG name. KeyError for an
    undeclared name — drift from the catalog must fail loudly at the
    instrumented call site, not silently record an unlintable metric."""
    metric = _metrics.get(name)
    if metric is not None:
        return metric
    spec = CATALOG[name]
    from ray_tpu.util import metrics as um

    cls = getattr(um, spec["kind"])
    with _lock:
        metric = _metrics.get(name)
        if metric is None:
            if spec["kind"] == "Histogram":
                metric = cls(name, description=spec["description"],
                             boundaries=spec["boundaries"],
                             tag_keys=spec["tags"])
            else:
                metric = cls(name, description=spec["description"],
                             tag_keys=spec["tags"])
            _metrics[name] = metric
    return metric


def counter_inc(name: str, value: float = 1.0, tags: dict | None = None):
    if not ENABLED:
        return
    metric = _get(name)
    try:
        metric.inc(value, tags=tags)
    except Exception:
        pass   # telemetry must never take down the operation it measures


def gauge_set(name: str, value: float, tags: dict | None = None):
    if not ENABLED:
        return
    metric = _get(name)
    try:
        metric.set(value, tags=tags)
    except Exception:
        pass


def observe(name: str, value: float, tags: dict | None = None):
    if not ENABLED:
        return
    metric = _get(name)
    try:
        metric.observe(value, tags=tags)
    except Exception:
        pass


def role() -> str:
    """This process's cluster role for the {role} label — the single
    shared resolver lives in events.py so the metric label can never
    diverge from the event `role` field for the same process."""
    from ray_tpu._private.events import _role

    return _role()
