"""Per-process profiling spans → chrome://tracing timeline.

Reference: src/ray/core_worker/profiling.h (events pushed to GCS, dumped by
`ray timeline`, scripts.py:1757). Here every worker/driver process keeps a
bounded ring of completed spans; `ray_tpu.timeline()` fans out over
raylets → workers, merges, and emits the chrome trace-event JSON format.
"""
from __future__ import annotations

import collections
import contextlib
import os
import threading
import time

_MAX_EVENTS = 10_000

_lock = threading.Lock()
_events: collections.deque = collections.deque(maxlen=_MAX_EVENTS)
_dropped = 0

# pids collide across hosts: a merged multi-node timeline needs the
# producing host on every event (tracing spans already carry `node`)
_NODE = os.uname().nodename
# cached: worker processes are spawned (never forked), and getpid is a
# real syscall on this container runtime (~0.3ms — profiled on the
# collective span hot path)
_PID = os.getpid()

# Collection defaults ON (ray_tpu.timeline() works out of the box, like
# the reference's profiling events); RAY_TPU_TIMELINE=0 removes the
# per-task dict+lock cost on latency-critical deployments.
_ENABLED = os.environ.get("RAY_TPU_TIMELINE", "1") != "0"


def _append_event(category, name, start_s, dur_s, extra):
    """Single definition of the chrome-event shape — the live context
    manager and the after-the-fact recorder must never drift apart.
    Appends into a full ring evict the oldest span, COUNTED (metric +
    stats + a drop-marker metadata row in timeline merges) so a fused
    window can flag itself incomplete instead of mis-attributing."""
    global _dropped
    with _lock:
        dropped = len(_events) == _events.maxlen
        if dropped:
            _dropped += 1
        _events.append({
            "cat": category,
            "name": name,
            "pid": _PID,
            "node": _NODE,
            "tid": threading.get_ident() % 2**31,
            "ts": int(start_s * 1e6),   # µs, chrome format
            "dur": int(dur_s * 1e6),
            "ph": "X",
            "args": extra or {},
        })
    if dropped:
        try:
            from ray_tpu._private import telemetry as _tm

            _tm.counter_inc("ray_tpu_timeline_dropped_total")
        except Exception:
            pass


class _SpanCM:
    """Hand-rolled context manager: ~3µs cheaper per task than the
    generator-based contextlib version, and this runs TWICE per task
    on the execute hot path."""

    __slots__ = ("cat", "name", "extra", "start")

    def __init__(self, category, name, extra):
        self.cat = category
        self.name = name
        self.extra = extra

    def __enter__(self):
        self.start = time.time()
        return None

    def __exit__(self, *exc):
        _append_event(self.cat, self.name, self.start,
                      time.time() - self.start, self.extra)
        return False


_NULL_CM = contextlib.nullcontext()


def record_span(category: str, name: str, extra: dict | None = None):
    if not _ENABLED:
        return _NULL_CM
    return _SpanCM(category, name, extra)


def record_completed_span(category: str, name: str, start_s: float,
                          dur_s: float, extra: dict | None = None):
    """Append an already-timed span (observers that only learn a span
    happened after the fact — e.g. a compile-cache miss detected by
    cache-size delta). Same event shape as the live context manager."""
    if not _ENABLED:
        return
    _append_event(category, name, start_s, dur_s, extra)


def snapshot(with_drop_marker: bool = False) -> list[dict]:
    """This process's events. ``with_drop_marker=True`` (the RPC /
    timeline-merge path) appends one chrome *metadata* row (``ph: M``)
    carrying the ring's drop count — chrome/Perfetto ignore unknown
    metadata names, and merged timelines surface the loss instead of
    presenting an evicted window as complete."""
    with _lock:
        out = list(_events)
        dropped = _dropped
    if with_drop_marker and dropped:
        out.append({"ph": "M", "name": "ray_tpu_timeline_dropped",
                    "pid": _PID, "node": _NODE, "ts": 0,
                    "args": {"dropped": dropped}})
    return out


def stats() -> dict:
    with _lock:
        return {"buffered": len(_events), "dropped": _dropped,
                "capacity": _events.maxlen}


def clear():
    global _dropped
    with _lock:
        _events.clear()
        _dropped = 0


def to_chrome_trace(events: list[dict]) -> list[dict]:
    """Already chrome-shaped; kept as a seam for format evolution.
    Metadata rows (drop markers) sort first — ``ts`` 0."""
    return sorted(events, key=lambda e: e["ts"])
