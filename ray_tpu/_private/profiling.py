"""Per-process profiling spans → chrome://tracing timeline.

Reference: src/ray/core_worker/profiling.h (events pushed to GCS, dumped by
`ray timeline`, scripts.py:1757). Here every worker/driver process keeps a
bounded ring of completed spans; `ray_tpu.timeline()` fans out over
raylets → workers, merges, and emits the chrome trace-event JSON format.
"""
from __future__ import annotations

import collections
import contextlib
import os
import threading
import time

_MAX_EVENTS = 10_000

_lock = threading.Lock()
_events: collections.deque = collections.deque(maxlen=_MAX_EVENTS)


@contextlib.contextmanager
def record_span(category: str, name: str, extra: dict | None = None):
    start = time.time()
    try:
        yield
    finally:
        end = time.time()
        with _lock:
            _events.append({
                "cat": category,
                "name": name,
                "pid": os.getpid(),
                "tid": threading.get_ident() % 2**31,
                "ts": int(start * 1e6),     # microseconds, chrome format
                "dur": int((end - start) * 1e6),
                "ph": "X",
                "args": extra or {},
            })


def snapshot() -> list[dict]:
    with _lock:
        return list(_events)


def clear():
    with _lock:
        _events.clear()


def to_chrome_trace(events: list[dict]) -> list[dict]:
    """Already chrome-shaped; kept as a seam for format evolution."""
    return sorted(events, key=lambda e: e["ts"])
