"""Crash-consistent file writes — the sanctioned durability idiom.

Every byte the runtime persists with the intent of reading it back after
a crash (checkpoint shards, generation manifests, compacted GCS tables)
must go through :func:`atomic_write`: write to a temp file IN THE SAME
DIRECTORY, flush + fsync the file, ``os.rename`` onto the final name
(atomic on POSIX within one filesystem), then fsync the directory so the
rename itself is durable. A reader therefore observes either the old
bytes or the complete new bytes — never a torn prefix.

The ``durability`` static-analysis pass (RTD5xx,
``ray_tpu/_private/analysis/durability.py``) flags bare
``open(path, "w"/"wb")`` writes in persistence modules; routing them
here is the sanctioned fix.

Chaos: the write consults the fault plane's DISK primitives
(``torn_write:`` / ``corrupt_file:`` rules, see
``_private/fault_injection.py``) keyed by a caller-supplied ``tag`` +
logical ``name`` — a fired ``torn_write`` leaves a truncated temp file
and raises (exactly what a crash mid-write leaves behind: the final
path never appears), a fired ``corrupt_file`` flips one byte before the
otherwise-clean commit (what a latent media/DMA error leaves behind:
the file exists, the digest does not match).

``RAY_TPU_CHECKPOINT_FSYNC=0`` (config ``checkpoint_fsync``) skips the
fsync calls — a TEST-ONLY kill switch so tmpfs-heavy suites don't pay
thousands of no-op syncs; production durability requires it on.
"""
from __future__ import annotations

import os
import tempfile


class TornWriteError(OSError):
    """An injected ``torn_write`` fault: the write "crashed" mid-file.

    The temp file holds a truncated prefix and the final path was never
    created/replaced — the on-disk state a real power loss or process
    kill between write and rename leaves behind."""


def _fsync_enabled() -> bool:
    try:
        from ray_tpu._private.config import get_config

        return bool(get_config("checkpoint_fsync"))
    except Exception:
        return True


def fsync_dir(path: str):
    """fsync a DIRECTORY so a rename/creation inside it is durable."""
    if not _fsync_enabled():
        return
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write(path: str, data: bytes, tag: str = "ckpt",
                 name: str | None = None) -> str:
    """Durably replace ``path`` with ``data``; returns ``path``.

    temp file (same dir) → write → flush+fsync → rename → dir fsync.
    ``tag``/``name`` scope the fault plane's disk-rule consult (``name``
    defaults to the file's basename)."""
    path = os.fspath(path)
    dirname = os.path.dirname(path) or "."
    logical = name if name is not None else os.path.basename(path)

    torn = False
    from ray_tpu._private import fault_injection as _fi

    if _fi.ACTIVE is not None:
        for action, _param in _fi.ACTIVE.on_disk(tag, logical):
            if action == "torn_write":
                torn = True
            elif action == "corrupt_file" and data:
                # flip one byte mid-payload: the commit completes
                # cleanly but the digest can never match
                mid = len(data) // 2
                data = data[:mid] + bytes([data[mid] ^ 0xFF]) \
                    + data[mid + 1:]

    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".tmp.",
                               dir=dirname)
    if torn:
        # a crash mid-write: half the payload reaches the temp file, the
        # rename never happens, and the truncated temp stays behind —
        # exactly the wreckage restore-side verification must survive
        with os.fdopen(fd, "wb") as f:
            f.write(data[:max(1, len(data) // 2)])
            f.flush()
        raise TornWriteError(
            f"[fault-injection] torn_write of {path!r} ({tag}.{logical})")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            if _fsync_enabled():
                os.fsync(f.fileno())
        os.rename(tmp, path)
        fsync_dir(dirname)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path
