"""Worker process entry point — forked by the raylet's worker pool.

Analog of the reference's default_worker.py
(/root/reference/python/ray/_private/workers/default_worker.py): connect the
core worker to this node's raylet/GCS/store, then serve the task execution
loop until the raylet (or an actor kill) terminates us.
"""
from __future__ import annotations

import os
import signal
import sys
import time


def main():
    import faulthandler

    faulthandler.register(signal.SIGUSR1, all_threads=True)  # `ray stack`
    faulthandler.enable()   # SIGSEGV/SIGABRT dump to stderr (worker logs)
    from ray_tpu._private import fault_injection

    fault_injection.set_role("worker")
    gcs_host, gcs_port = os.environ["RAY_TPU_GCS_ADDR"].split(":")
    raylet_host, raylet_port = os.environ["RAY_TPU_RAYLET_ADDR"].split(":")

    from ray_tpu._private.protocol import ConnectionLost
    from ray_tpu._private.worker_runtime import CoreWorker, set_current_worker

    try:
        worker = CoreWorker(
            gcs_addr=(gcs_host, int(gcs_port)),
            raylet_addr=(raylet_host, int(raylet_port)),
            mode="worker",
            store_name=os.environ.get("RAY_TPU_STORE_NAME"),
            spill_dir=os.environ.get("RAY_TPU_SPILL_DIR"),
            worker_id=os.environ.get("RAY_TPU_WORKER_ID"),
            job_id=0,
        )
    except ConnectionLost:
        # Cluster shut down while we were starting (e.g. a prestarted worker
        # racing teardown) — exit quietly.
        return 0
    set_current_worker(worker)

    profile_dir = os.environ.get("RAY_TPU_WORKER_PROFILE")
    prof = None
    if profile_dir:
        import cProfile

        prof = cProfile.Profile()

    def _dump_profile():
        if prof is not None:
            try:
                os.makedirs(profile_dir, exist_ok=True)
                prof.dump_stats(os.path.join(
                    profile_dir, f"worker-{os.getpid()}.prof"))
            except Exception:
                pass

    def _term(signum, frame):
        worker.stopped = True
        _dump_profile()
        os._exit(0)

    signal.signal(signal.SIGTERM, _term)

    # Liveness watchdog: the main thread may be stuck inside a hung task
    # when the raylet dies — this thread preserves the old guarantee that
    # a dead node's workers exit within ~0.5s regardless.
    import threading

    def _watchdog():
        while True:
            time.sleep(0.5)
            if worker.raylet.closed:
                print("[worker] raylet connection closed; exiting",
                      file=sys.stderr, flush=True)
                os._exit(1)

    threading.Thread(target=_watchdog, daemon=True,
                     name="raylet-watchdog").start()

    # Serve normal-task execution on THIS (main) thread — the reference's
    # RunTaskExecutionLoop (core_worker.cc:2188). Some native libraries
    # (pyarrow submodule init) are unreliable on short-lived dispatch
    # threads; the main thread is always safe. Returns when the raylet
    # connection drops — the node is gone.
    if prof is not None:
        # Perf diagnosis aid (RAY_TPU_WORKER_PROFILE=dir): cProfile the
        # main task loop — where normal-task execution happens — and dump
        # per-pid stats at exit (including SIGTERM, see _term).
        try:
            prof.runcall(worker.serve_task_loop)
        finally:
            _dump_profile()
        os._exit(1)
    worker.serve_task_loop()
    os._exit(1)


if __name__ == "__main__":
    sys.exit(main())
