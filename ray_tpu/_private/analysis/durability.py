"""Durability pass (the ``RTD5xx`` family).

Crash consistency is a discipline, not a property a test can fully
prove: a bare ``open(path, "w"/"wb")`` + ``write`` in a persistence
module works in every test and loses data on the one power cut that
matters. The sharded-checkpointing arc made
``_private/atomic_write.atomic_write`` (temp file → write → fsync →
rename → dir fsync) the sanctioned spelling; this pass keeps new
persistence code from regressing to bare writes:

- **RTD501 — bare write in a persistence module.** A write-mode
  ``open()`` / ``os.fdopen()`` inside one of the modules whose files
  are read back after a crash (checkpoint modules, the durable GCS
  store/snapshot, object-store spill, workflow storage). Route the
  write through ``atomic_write`` — or, for streaming writers the
  bytes-payload helper doesn't fit, hand-roll the full idiom and
  document the site in the baseline.
- **RTD502 — rename commit without fsync.** An ``os.rename`` /
  ``os.replace`` commit in a persistence-module function that never
  fsyncs: atomic against a crashed WRITER, but after power loss the
  rename (or the data it points at) may not have hit the platter —
  the "atomic but not durable" half-idiom.

Like every raylint family: precision comes from inline suppression and
the justified baseline, not from deeper analysis. The helper module
itself is exempt (it IS the idiom).
"""
from __future__ import annotations

import ast

from ray_tpu._private.analysis.core import (AnalysisContext, Finding,
                                            call_name, register)

# Modules persisting state that is read back after a crash. Any module
# with "checkpoint" in its path is in scope by construction; the rest
# are named explicitly — breadth here is a policy decision, not a
# heuristic (tune loggers, tracing dumps etc. are diagnostics, not
# durable state, and stay out).
_PERSIST_SUBSTRINGS = ("checkpoint",)
_PERSIST_FILES = frozenset({
    "ray_tpu/_private/gcs_store.py",
    "ray_tpu/_private/gcs.py",
    "ray_tpu/_private/store_client.py",
    "ray_tpu/workflow/storage.py",
})
_EXEMPT_FILES = frozenset({
    "ray_tpu/_private/atomic_write.py",     # the idiom itself
})

_WRITE_MODES = frozenset({"w", "wb", "a", "ab", "w+", "wb+", "a+"})
_OPEN_CALLS = frozenset({"open", "os.fdopen"})
_RENAME_CALLS = frozenset({"os.rename", "os.replace"})
_FSYNC_CALLS = frozenset({"os.fsync", "fsync_dir"})


def _is_persist_module(path: str) -> bool:
    if path in _EXEMPT_FILES:
        return False
    if path in _PERSIST_FILES:
        return True
    base = path.rsplit("/", 1)[-1]
    return any(s in base for s in _PERSIST_SUBSTRINGS)


def _write_mode(node: ast.Call) -> bool:
    """True when the call's mode argument is a literal write mode."""
    mode = None
    if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
        mode = node.args[1].value
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    return isinstance(mode, str) and mode in _WRITE_MODES


def _collect(tree: ast.Module):
    """(qualname, [Call...]) for every function, plus "<module>"."""
    out: dict[str, list[ast.Call]] = {}

    def rec(node, qual: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                sub = f"{qual}.{child.name}" if qual else child.name
                out.setdefault(sub, [])
                rec(child, sub)
            elif isinstance(child, ast.ClassDef):
                sub = f"{qual}.{child.name}" if qual else child.name
                rec(child, sub)
            else:
                if isinstance(child, ast.Call):
                    out.setdefault(qual or "<module>", []).append(child)
                rec(child, qual)

    rec(tree, "")
    return list(out.items())


@register("durability")
def durability_pass(ctx: AnalysisContext):
    for mod in ctx.package_modules("ray_tpu"):
        if not _is_persist_module(mod.path):
            continue
        for qual, calls in _collect(mod.tree):
            fsyncs = any(call_name(c) in _FSYNC_CALLS
                         or call_name(c).endswith(".fsync")
                         for c in calls)
            for c in calls:
                name = call_name(c)
                if name in _OPEN_CALLS and _write_mode(c):
                    yield Finding(
                        "RTD501", mod.path, c.lineno, qual or "<module>",
                        "bare write-mode open() in a persistence module "
                        "— route the write through _private/"
                        "atomic_write.atomic_write (temp + fsync + "
                        "rename + dir fsync), or baseline a justified "
                        "hand-rolled site")
                elif name in _RENAME_CALLS and not fsyncs:
                    yield Finding(
                        "RTD502", mod.path, c.lineno, qual or "<module>",
                        "rename commit without any fsync in this "
                        "function — atomic against a crashed writer "
                        "but not durable across power loss; use "
                        "atomic_write or add fsync(file)+fsync(dir)")
