"""Lock-discipline passes (the ``RTL1xx`` family).

The defect classes that burned review rounds across PRs 4-6, made
mechanically checkable:

- **RTL101 — blocking call under a lock.** Socket/file IO,
  ``time.sleep``, RPC round trips, ``ray.get`` and timeout-less
  ``.get()/.join()/.result()/.wait()`` executed while a ``threading``
  lock is held stall every other thread contending for that lock (the
  PR 6 ``shared_weights``-held-across-``loader()`` class).
- **RTL102 — timeout-less blocking poll.** A zero-arg ``.get()``/
  ``.join()``/``.result()``/``.wait()`` anywhere, or a timeout-less
  ``ray_tpu.get``/``.wait`` inside an internal plane (``_private``
  subtrees — daemon threads and control loops where a hang is a
  silent stall), turns a lost wakeup into a hang instead of a named
  failure. Public API surfaces deliberately keep the reference's
  blocking-``get`` semantics and are out of scope.
- **RTL103 — user callback invoked under a lock.** Calling a function
  that arrived as a parameter (``loader()``, ``cb()``) while holding a
  lock hands YOUR lock to arbitrary user code — the composed-loader
  deadlock class.
- **RTL104 — lock-order cycle.** Two locks acquired in both nesting
  orders across a class's methods (directly or one ``self.method()``
  hop away) can deadlock under concurrency.
- **RTL105 — guarded attribute written outside its lock.** An
  attribute both read and written under a class's lock somewhere, but
  assigned lock-free in another method (the PR 5/6 unlocked
  double-checked-init / poison-check race class).
- **RTL107 — condition used without holding it.** ``.notify()`` /
  ``.notify_all()`` / ``.wait()`` / ``.wait_for()`` on a known
  condition/lock token while that lock is NOT held. Notifying an
  unheld ``threading.Condition`` raises ``RuntimeError`` at runtime,
  and a wait outside the lock races its own predicate (lost wakeup).
  Added with the async-collective issue thread (handle completion
  state flips under the group condition; waiters park in ``wait_for``
  under it) so that discipline is mechanically checked.
- **RTL106 — unbounded per-id growth in a control-plane class.** A
  dict/list/set attribute of a class in one of the CONTROL-PLANE
  modules (``_CONTROL_PLANE_FILES``: gcs / raylet / pubsub /
  sim_cluster) that some method grows (subscript-assign, ``append``,
  ``add``, ``setdefault``...) but NO method ever shrinks (``pop``,
  ``del``, ``remove``, ``discard``, ``clear``, or a reset
  reassignment). Entries keyed by node/subscriber/worker id with no
  removal on the death path leak across churn — the class the
  100-node soak otherwise finds one field at a time. Ring buffers
  built as ``deque(maxlen=...)`` are exempt (bounded by
  construction); document genuinely-by-design survivors in the
  baseline.

Heuristics are deliberately shallow (single file, one ``self.method()``
propagation hop, name-based lock identity) — precision comes from the
inline-suppression and baseline mechanisms, not from a points-to
analysis this codebase doesn't need.
"""
from __future__ import annotations

import ast
import dataclasses

from ray_tpu._private.analysis.core import (AnalysisContext, Finding,
                                            dotted, register)

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition",
               "Lock", "RLock", "Condition"}
_LOCK_NAME_HINT = ("lock", "cond", "mutex")

# attribute tails that block regardless of receiver
_BLOCKING_ATTRS = {"sendall", "recv", "recv_into", "accept", "makefile",
                   "get_actor", "getaddrinfo"}
# exact dotted names that block
_BLOCKING_EXACT = {"time.sleep", "socket.create_connection",
                   "_time.sleep", "open"}
_SUBPROCESS = {"run", "call", "check_call", "check_output", "Popen",
               "communicate"}
# module-ish receivers whose .get/.wait are the cluster blocking APIs
_RAY_MODULES = {"ray", "ray_tpu"}
# zero-arg calls of these attrs park the thread with no deadline
_PARK_ATTRS = {"get", "join", "result", "wait"}


def _has_kw(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


def _is_lockish(token: str | None) -> bool:
    return token is not None and any(h in token.rsplit(".", 1)[-1].lower()
                                     for h in _LOCK_NAME_HINT)


@dataclasses.dataclass
class _Block:
    """One blocking call observed in a function."""
    node: ast.Call
    desc: str
    held: tuple[str, ...]   # canonical lock tokens held at the call


@dataclasses.dataclass
class _FnReport:
    name: str
    qual: str
    blocks: list = dataclasses.field(default_factory=list)
    cond_misuse: list = dataclasses.field(default_factory=list)  # (node, meth, tok)
    callbacks: list = dataclasses.field(default_factory=list)  # (node, pname, held)
    edges: list = dataclasses.field(default_factory=list)      # (A, B, node)
    acquired: set = dataclasses.field(default_factory=set)
    self_calls: list = dataclasses.field(default_factory=list)  # (method, held, node)
    attr_reads: list = dataclasses.field(default_factory=list)  # (attr, held)
    attr_writes: list = dataclasses.field(default_factory=list)  # (attr, held, node)


class _Scope:
    """Lock universe for one class (or the module pseudo-scope)."""

    def __init__(self):
        self.locks: set[str] = set()       # canonical tokens
        self.aliases: dict[str, str] = {}  # cond token -> wrapped lock
        self.ctxvars: set[str] = set()     # ContextVar names: .get() is
        #                                    a lookup, not a park

    def canon(self, token: str) -> str:
        return self.aliases.get(token, token)

    def register_assign(self, target_token: str, value: ast.AST):
        if not isinstance(value, ast.Call):
            return
        ctor = dotted(value.func)
        if ctor in _LOCK_CTORS:
            self.locks.add(target_token)
            if ctor.endswith("Condition") and value.args:
                wrapped = dotted(value.args[0])
                if wrapped:
                    self.aliases[target_token] = wrapped
                    self.locks.add(wrapped)
        elif ctor in ("contextvars.ContextVar", "ContextVar"):
            self.ctxvars.add(target_token)

    def lock_token(self, expr: ast.AST) -> str | None:
        """Canonical token when ``expr`` names a lock of this scope
        (declared, or named like one)."""
        tok = dotted(expr)
        if not tok:
            return None
        if tok in self.locks or tok in self.aliases:
            return self.canon(tok)
        if _is_lockish(tok) and (tok.startswith("self.")
                                 or "." not in tok):
            return self.canon(tok)
        return None


class _FnWalker:
    """Walks one function's statements in order, tracking held locks."""

    def __init__(self, scope: _Scope, fn: ast.AST, qual: str,
                 is_async: bool = False):
        params = []
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)):
            a = fn.args
            params = [p.arg for p in (a.posonlyargs + a.args
                                      + a.kwonlyargs)]
            if a.vararg:
                params.append(a.vararg.arg)
        self.scope = scope
        self.params = {p for p in params if p not in ("self", "cls")}
        self.is_async = is_async
        self.held: list[str] = []
        self.rep = _FnReport(getattr(fn, "name", "<lambda>"), qual)
        self.nested: list[tuple[ast.AST, bool]] = []

    # ------------------------------------------------------------ driving
    def run(self, body: list[ast.stmt]) -> _FnReport:
        self._stmts(body)
        return self.rep

    def _stmts(self, stmts: list[ast.stmt]):
        for s in stmts:
            self._stmt(s)

    def _stmt(self, s: ast.stmt):
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.nested.append((s, isinstance(s, ast.AsyncFunctionDef)))
            return
        if isinstance(s, (ast.With, ast.AsyncWith)):
            tokens = []
            for item in s.items:
                self._exprs(item.context_expr)
                tok = self._with_lock_token(item.context_expr)
                if tok is not None:
                    self._acquire(tok, item.context_expr)
                    tokens.append(tok)
            self._stmts(s.body)
            for tok in reversed(tokens):
                self._release(tok)
            return
        if isinstance(s, (ast.If,)):
            self._exprs(s.test)
            self._stmts(s.body)
            self._stmts(s.orelse)
            return
        if isinstance(s, (ast.For, ast.AsyncFor)):
            self._exprs(s.iter)
            self._assign_target(s.target)
            self._stmts(s.body)
            self._stmts(s.orelse)
            return
        if isinstance(s, ast.While):
            self._exprs(s.test)
            self._stmts(s.body)
            self._stmts(s.orelse)
            return
        if isinstance(s, ast.Try):
            self._stmts(s.body)
            for h in s.handlers:
                self._stmts(h.body)
            self._stmts(s.orelse)
            self._stmts(s.finalbody)
            return
        if isinstance(s, ast.Expr) and isinstance(s.value, ast.Call):
            name = dotted(s.value.func)
            if name.endswith(".acquire"):
                tok = self.scope.lock_token(s.value.func.value)
                if tok is not None:
                    self._acquire(tok, s.value)
                    self._exprs_of_call_args(s.value)
                    return
            if name.endswith(".release"):
                tok = self.scope.lock_token(s.value.func.value)
                if tok is not None:
                    self._release(tok)
                    return
        if isinstance(s, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = getattr(s, "value", None)
            if value is not None:
                self._exprs(value)
            targets = (s.targets if isinstance(s, ast.Assign)
                       else [s.target])
            for t in targets:
                self._assign_target(t)
            return
        # any other simple statement: scan its expressions
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self._exprs(child)

    # ------------------------------------------------------------- pieces
    def _with_lock_token(self, expr: ast.AST) -> str | None:
        return self.scope.lock_token(expr)

    def _acquire(self, tok: str, node: ast.AST):
        if self.held:
            self.rep.edges.append((self.held[-1], tok, node))
        self.held.append(tok)
        self.rep.acquired.add(tok)

    def _release(self, tok: str):
        if tok in self.held:
            self.held.reverse()
            self.held.remove(tok)
            self.held.reverse()

    def _assign_target(self, t: ast.AST):
        if isinstance(t, ast.Attribute) and dotted(t.value) == "self":
            self.rep.attr_writes.append((t.attr, tuple(self.held), t))
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._assign_target(e)
        elif isinstance(t, ast.Subscript):
            self._exprs(t)

    def _exprs_of_call_args(self, call: ast.Call):
        for a in call.args:
            self._exprs(a)
        for kw in call.keywords:
            self._exprs(kw.value)

    def _exprs(self, expr: ast.AST):
        """Scan one expression tree for calls / attr access, PRUNING
        lambda bodies (they run later, lock-free — a plain ast.walk
        would still descend into them and report their calls as made
        under the current lock)."""
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                continue   # prune: don't push its children
            if isinstance(node, ast.Call):
                self._call(node)
            elif isinstance(node, ast.Attribute) and \
                    dotted(node.value) == "self" and \
                    isinstance(node.ctx, ast.Load):
                self.rep.attr_reads.append((node.attr, tuple(self.held)))
            stack.extend(ast.iter_child_nodes(node))

    def _call(self, call: ast.Call):
        name = dotted(call.func)
        held = tuple(self.held)
        # user-callback: a bare parameter name invoked directly
        if isinstance(call.func, ast.Name) and \
                call.func.id in self.params and held:
            self.rep.callbacks.append((call, call.func.id, held))
        # RTL107: condition primitives invoked while the condition's
        # lock is NOT held. Skipped inside *_locked methods (the
        # caller holds SOME lock; name-based identity can't tell which)
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr in ("notify", "notify_all", "wait",
                                   "wait_for") and \
                "<caller's lock>" not in self.held:
            tok = self.scope.lock_token(call.func.value)
            if tok is not None and tok not in self.held:
                self.rep.cond_misuse.append((call, call.func.attr, tok))
        desc = self._blocking_reason(call, name)
        if desc is not None:
            self.rep.blocks.append(_Block(call, desc, held))
        if name.startswith("self.") and name.count(".") == 1:
            self.rep.self_calls.append((name.split(".")[1], held, call))

    def _blocking_reason(self, call: ast.Call, name: str) -> str | None:
        if self.is_async:
            return None   # event-loop code has its own discipline
        tail = name.rsplit(".", 1)[-1]
        recv = name.rsplit(".", 1)[0] if "." in name else ""
        if name in _BLOCKING_EXACT:
            return f"{name}()"
        if recv == "subprocess" and tail in _SUBPROCESS:
            return f"{name}()"
        if tail in _BLOCKING_ATTRS:
            return f".{tail}()"
        if recv in _RAY_MODULES and tail == "get" \
                and not _has_kw(call, "timeout"):
            return f"{name}() without timeout"
        if recv in _RAY_MODULES and tail == "wait" \
                and not _has_kw(call, "timeout"):
            return f"{name}() without timeout"
        if tail in _PARK_ATTRS and not call.args and not call.keywords \
                and isinstance(call.func, ast.Attribute):
            if tail == "wait" and self.scope.lock_token(
                    call.func.value) in self.held:
                return None   # Condition.wait releases the lock
            if tail == "get" and dotted(call.func.value) in \
                    self.scope.ctxvars:
                return None   # ContextVar.get() is a lookup
            return f".{tail}() with no timeout"
        return None


# --------------------------------------------------------------- analysis


def _scope_for_class(cls: ast.ClassDef) -> _Scope:
    scope = _Scope()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Attribute) and dotted(t.value) == "self":
                scope.register_assign(dotted(t), node.value)
                # ctor-param aliasing: ``self.x = cond`` stores a lock
                # RECEIVED from the caller (the async-handle pattern —
                # a completion Condition handed to every handle of an
                # issue queue). The attribute name may carry no lock
                # hint, so propagate lock identity from the aliased
                # NAME instead; notify/wait on it then lints like any
                # declared lock (RTL107 coverage for handle-completion
                # conditions on the reducescatter/allgather path).
                if isinstance(node.value, ast.Name) and \
                        _is_lockish(node.value.id):
                    scope.locks.add(dotted(t))
    return scope


def _scope_for_module(tree: ast.Module) -> _Scope:
    scope = _Scope()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            scope.register_assign(node.targets[0].id, node.value)
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and \
                node.value is not None:
            scope.register_assign(node.target.id, node.value)
    return scope


def _walk_functions(scope: _Scope, fns, qual_prefix: str):
    """Run the walker over each function AND the nested defs it finds
    (nested defs start with an empty held stack — they run later)."""
    reports = {}
    for fn in fns:
        pending = [(fn, isinstance(fn, ast.AsyncFunctionDef),
                    f"{qual_prefix}{fn.name}")]
        collected = []
        while pending:
            node, is_async, qual = pending.pop()
            w = _FnWalker(scope, node, qual, is_async=is_async)
            if node.name.endswith("_locked"):
                # convention: *_locked methods run with the caller's
                # lock held — their writes are guarded (RTL105) and
                # blocking calls inside them are under a lock (RTL101)
                w.held.append("<caller's lock>")
            rep = w.run(node.body)
            collected.append(rep)
            for nested, nested_async in w.nested:
                pending.append(
                    (nested, nested_async, f"{qual}.{nested.name}"))
        reports[fn.name] = collected
    return reports


def _findings_for_scope(path: str, scope: _Scope, reports: dict,
                        class_name: str | None):
    findings = []
    flat = [rep for reps in reports.values() for rep in reps]

    # ---- per-method summaries for one-hop propagation
    blocking_summary = {}
    for name, reps in reports.items():
        lockfree = [b for rep in reps for b in rep.blocks if not b.held]
        if lockfree:
            blocking_summary[name] = lockfree

    def emit(code, node, qual, msg):
        findings.append(Finding(code, path, node.lineno, qual, msg))

    for rep in flat:
        for b in rep.blocks:
            if b.held:
                emit("RTL101", b.node, rep.qual,
                     f"blocking {b.desc} while holding "
                     f"{', '.join(b.held)}")
            elif "no timeout" in b.desc or "without timeout" in b.desc:
                # ray.get-style blocking without timeout is the
                # DOCUMENTED public-API semantic (data/rllib/util
                # mirror the reference); only internal planes — where
                # a hang is a silent daemon stall, not a user's
                # foreground call — are held to the deadline rule
                if "without timeout" in b.desc \
                        and "/_private/" not in path:
                    continue
                emit("RTL102", b.node, rep.qual,
                     f"{b.desc}: a lost wakeup hangs this thread "
                     f"forever instead of failing")
        for node, meth, tok in rep.cond_misuse:
            emit("RTL107", node, rep.qual,
                 f".{meth}() on condition {tok} without holding it — "
                 f"notify on an unheld Condition raises RuntimeError, "
                 f"and a wait outside the lock races its own predicate")
        for node, pname, held in rep.callbacks:
            emit("RTL103", node, rep.qual,
                 f"user callback {pname}() invoked while holding "
                 f"{', '.join(held)}")
        # one-hop: self.m() under a lock where m blocks lock-free
        for method, held, node in rep.self_calls:
            if held and method in blocking_summary:
                b = blocking_summary[method][0]
                emit("RTL101", node, rep.qual,
                     f"calls self.{method}() while holding "
                     f"{', '.join(held)}; it performs blocking "
                     f"{b.desc} (line {b.node.lineno})")

    # ---- RTL104 lock-order cycles over the class's edge set
    edges = {}
    for rep in flat:
        for a, b, node in rep.edges:
            if a != b:
                edges.setdefault((a, b), (node, rep.qual))
        for method, held, node in rep.self_calls:
            for other in reports.get(method, []):
                for tok in other.acquired:
                    for h in held:
                        if tok != h and (h, tok) not in edges:
                            edges[(h, tok)] = (node, rep.qual)
    graph = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    seen_cycles = set()
    for start in graph:
        stack = [(start, [start])]
        while stack:
            cur, trail = stack.pop()
            for nxt in graph.get(cur, ()):
                if nxt == start and len(trail) > 1:
                    cyc = frozenset(trail)
                    if cyc not in seen_cycles:
                        seen_cycles.add(cyc)
                        node, qual = edges[(trail[0], trail[1])]
                        emit("RTL104", node, qual,
                             "lock-order cycle: "
                             + " -> ".join(trail + [start]))
                elif nxt not in trail:
                    stack.append((nxt, trail + [nxt]))

    # ---- RTL105 guarded attribute written lock-free elsewhere
    if class_name is not None:
        guarded_writes = set()
        guarded_reads = set()
        for rep in flat:
            for attr, held, _node in rep.attr_writes:
                if held:
                    guarded_writes.add(attr)
            for attr, held in rep.attr_reads:
                if held:
                    guarded_reads.add(attr)
        guarded = guarded_writes & guarded_reads
        for rep in flat:
            if rep.name in ("__init__", "__new__", "__setstate__",
                            "__getstate__", "__reduce__", "__del__"):
                continue
            for attr, held, node in rep.attr_writes:
                if not held and attr in guarded \
                        and not _is_lockish(attr):
                    emit("RTL105", node, rep.qual,
                         f"self.{attr} is read AND written under a "
                         f"lock elsewhere in {class_name} but assigned "
                         f"here with no lock held")
    return findings


# ------------------------------------------------- RTL106: unbounded growth

# Modules whose classes hold per-node/per-subscriber/per-worker tables —
# the control plane. Growth discipline applies HERE (a driver-side cache
# has an owner watching it; a GCS table outlives every client).
_CONTROL_PLANE_FILES = (
    "ray_tpu/_private/gcs.py",
    "ray_tpu/_private/raylet.py",
    "ray_tpu/_private/pubsub.py",
    "ray_tpu/_private/sim_cluster.py",
)

# method calls that add entries / that remove them
_GROW_METHODS = {"setdefault", "append", "add", "extend", "insert"}
_SHRINK_METHODS = {"pop", "popitem", "remove", "discard", "clear",
                   "popleft"}


def _self_attrs_in(expr: ast.AST):
    """Attribute names ``self.X`` appearing anywhere inside ``expr``
    (receiver chains like ``self.kv.get(ns, {}).pop(...)`` count as
    touching ``kv``)."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and dotted(node.value) == "self":
            yield node.attr


def _growth_findings_for_class(path: str, cls: ast.ClassDef):
    grows: dict[str, ast.AST] = {}     # attr -> first grow site
    shrinks: set[str] = set()
    bounded: set[str] = set()          # deque(maxlen=...) etc.
    for fn in [n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        is_init = fn.name in ("__init__", "__new__", "__setstate__")
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    for sub_t in (t.elts if isinstance(
                            t, (ast.Tuple, ast.List)) else (t,)):
                        if isinstance(sub_t, ast.Subscript):
                            # self.X[k] = v  (also self.X[k1][k2] = v).
                            # A CONSTANT key is a fixed vocabulary (a
                            # stats dict), not per-id growth.
                            if isinstance(sub_t.slice, ast.Constant):
                                continue
                            for attr in _self_attrs_in(sub_t.value):
                                grows.setdefault(attr, node)
                        elif isinstance(sub_t, ast.Attribute) and \
                                dotted(sub_t.value) == "self":
                            if is_init:
                                # bounded-by-construction rings
                                v = node.value
                                if isinstance(v, ast.Call) and \
                                        dotted(v.func).endswith("deque") \
                                        and any(kw.arg == "maxlen"
                                                for kw in v.keywords):
                                    bounded.add(sub_t.attr)
                            else:
                                # re-binding outside init resets/bounds
                                # the container (swap-and-flush pattern)
                                shrinks.add(sub_t.attr)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    for attr in _self_attrs_in(t):
                        shrinks.add(attr)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                m = node.func.attr
                if m in _GROW_METHODS:
                    for attr in _self_attrs_in(node.func.value):
                        grows.setdefault(attr, node)
                elif m in _SHRINK_METHODS:
                    for attr in _self_attrs_in(node.func.value):
                        shrinks.add(attr)
    out = []
    for attr, node in sorted(grows.items()):
        if attr in shrinks or attr in bounded:
            continue
        out.append(Finding(
            "RTL106", path, node.lineno, f"{cls.name}.{attr}",
            f"control-plane container self.{attr} grows (per-id entries "
            f"added) but no method of {cls.name} ever removes entries — "
            f"it leaks across node/subscriber churn; remove on the death "
            f"path, bound it, or document it in the baseline"))
    return out


def analyze_growth_source(source: str, path: str,
                          tree: ast.Module | None = None):
    """RTL106 over one source text (fixture-test entry point). Only
    control-plane paths are analyzed; other paths return []."""
    if path not in _CONTROL_PLANE_FILES:
        return []
    if tree is None:
        tree = ast.parse(source)
    findings = []
    for cls in [n for n in tree.body if isinstance(n, ast.ClassDef)]:
        findings += _growth_findings_for_class(path, cls)
    return findings


def analyze_module_source(source: str, path: str = "<string>",
                          tree: ast.Module | None = None):
    """Run the lock-discipline analysis over one source text — the unit
    the fixture tests drive directly. Pass ``tree`` when the caller
    already parsed the file (the repo-wide pass reuses the context's
    cached ASTs instead of re-parsing the whole package)."""
    if tree is None:
        tree = ast.parse(source)
    findings = []
    mod_scope = _scope_for_module(tree)
    mod_fns = [n for n in tree.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    reports = _walk_functions(mod_scope, mod_fns, "")
    findings += _findings_for_scope(path, mod_scope, reports, None)
    for cls in [n for n in tree.body if isinstance(n, ast.ClassDef)]:
        scope = _scope_for_class(cls)
        scope.locks |= mod_scope.locks
        scope.aliases.update(mod_scope.aliases)
        fns = [n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        reports = _walk_functions(scope, fns, f"{cls.name}.")
        findings += _findings_for_scope(path, scope, reports, cls.name)
    return findings


@register("lock-discipline")
def lock_discipline_pass(ctx: AnalysisContext):
    for mod in ctx.package_modules():
        yield from analyze_module_source(mod.source, mod.path,
                                         tree=mod.tree)
        yield from analyze_growth_source(mod.source, mod.path,
                                         tree=mod.tree)
