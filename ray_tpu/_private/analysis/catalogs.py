"""Catalog-consistency passes (the ``RTC4xx`` family).

Unifies the metric-catalog lint that previously lived inside
``tests/test_telemetry_metrics.py`` (the test now calls this pass) and
adds the analogous event-name lint against ``_private/events.py``'s
docstring catalog:

- **RTC401 — undeclared metric literal.** Any ``ray_tpu_*<unit>``
  string in the tree must be declared in ``telemetry.CATALOG``.
- **RTC402 — malformed catalog entry.** Catalog names need the
  ``ray_tpu_`` prefix, a unit suffix, a known kind; counters must end
  ``_total``.
- **RTC403 — grafana panel charts a phantom metric.** Dashboard
  exprs may only reference cataloged names.
- **RTC404 — unregistered event kind.** ``events.record("<kind>")``
  with a kind the events.py module docstring doesn't document.
- **RTC405 — dead event catalog entry.** A documented kind nothing
  records any more.
"""
from __future__ import annotations

import ast
import re

from ray_tpu._private.analysis.core import (AnalysisContext, Finding,
                                            dotted, register)

EVENTS_PY = "ray_tpu/_private/events.py"
TELEMETRY_PY = "ray_tpu/_private/telemetry.py"

_EVENT_SECTION_START = "Event kinds recorded by the runtime:"
_EVENT_ENTRY_RE = re.compile(r"``([A-Za-z_]+)``")


# ------------------------------------------------------------ event kinds

def documented_event_kinds(ctx: AnalysisContext) -> set[str] | None:
    """Kinds cataloged in events.py's module docstring (the ``- ``x````
    entries under "Event kinds recorded by the runtime:"). None when the
    docstring/section is missing entirely."""
    mod = ctx.module(EVENTS_PY)
    if mod is None:
        return None
    doc = ast.get_docstring(mod.tree) or ""
    if _EVENT_SECTION_START not in doc:
        return None
    section = doc.split(_EVENT_SECTION_START, 1)[1]
    kinds: set[str] = set()
    for line in section.splitlines():
        if line.strip().startswith("- ``"):
            head = line.split("—", 1)[0]
            kinds.update(_EVENT_ENTRY_RE.findall(head))
    return kinds


def recorded_event_kinds(ctx: AnalysisContext):
    """Yield (kind, path, node) for every literal-kind record() call."""
    for mod in ctx.package_modules():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name == "record" and mod.path == EVENTS_PY:
                pass   # events.py's own helpers call record() bare
            elif not name.endswith(".record"):
                continue
            else:
                recv = name.rsplit(".", 1)[0].rsplit(".", 1)[-1]
                if recv not in ("events", "_events"):
                    continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                yield node.args[0].value, mod.path, node


@register("event-catalog")
def event_catalog_pass(ctx: AnalysisContext):
    documented = documented_event_kinds(ctx)
    if documented is None:
        yield Finding(
            "RTC404", EVENTS_PY, 1, "<docstring>",
            "events.py module docstring lost its \"Event kinds recorded "
            "by the runtime:\" catalog section")
        return
    recorded: set[str] = set()
    for kind, path, node in recorded_event_kinds(ctx):
        recorded.add(kind)
        if kind not in documented:
            yield Finding(
                "RTC404", path, node.lineno, kind,
                f"event kind {kind!r} is recorded but not documented in "
                f"events.py's docstring catalog — consumers discover "
                f"kinds there (and `ray-tpu events --kind`)")
    for kind in sorted(documented - recorded):
        yield Finding(
            "RTC405", EVENTS_PY, 1, kind,
            f"event kind {kind!r} is documented in the catalog but "
            f"nothing records it — dead entry, or its producer was "
            f"dropped by mistake")


# ---------------------------------------------------------------- metrics

@register("metric-catalog")
def metric_catalog_pass(ctx: AnalysisContext):
    from ray_tpu._private.telemetry import ALLOWED_SUFFIXES, CATALOG

    for name, spec in CATALOG.items():
        problems = []
        if not name.startswith("ray_tpu_"):
            problems.append("missing the ray_tpu_ prefix")
        if not name.endswith(ALLOWED_SUFFIXES):
            problems.append(f"lacks a unit suffix {ALLOWED_SUFFIXES}")
        if spec.get("kind") not in ("Counter", "Gauge", "Histogram"):
            problems.append(f"unknown kind {spec.get('kind')!r}")
        elif spec["kind"] == "Counter" and not name.endswith("_total"):
            problems.append("counters must end in _total")
        if problems:
            yield Finding("RTC402", TELEMETRY_PY, 1, name,
                          f"catalog entry {name}: " + "; ".join(problems))

    suffix_re = "|".join(s.lstrip("_") for s in ALLOWED_SUFFIXES)
    pat = re.compile(r"""["'](ray_tpu_[a-z0-9_]+_(?:%s))["']"""
                     % suffix_re)
    # memory-anatomy families are additionally linted BY PREFIX: a
    # ``ray_tpu_store_*`` / ``ray_tpu_train_state_*`` literal must be
    # cataloged even when it lacks a recognized unit suffix — a typo'd
    # suffix on these names must fail loudly, not slip past the lint
    prefix_pat = re.compile(
        r"""["'](ray_tpu_(?:store|train_state)_[a-z0-9_]+)["']""")
    for mod in ctx.package_modules():
        if mod.path == TELEMETRY_PY:
            continue
        for i, line in enumerate(mod.source.splitlines(), start=1):
            hits = {m.group(1) for m in pat.finditer(line)}
            hits.update(m.group(1) for m in prefix_pat.finditer(line))
            for name in sorted(hits):
                if name not in CATALOG:
                    yield Finding(
                        "RTC401", mod.path, i, name,
                        f"internal metric {name!r} is not "
                        f"declared in _private/telemetry.py CATALOG")

    # grafana: the default dashboard may only chart cataloged metrics
    try:
        from ray_tpu.dashboard.grafana import generate_default_dashboard

        dash = generate_default_dashboard()
    except Exception as e:   # import/runtime break = a finding, not a skip
        yield Finding("RTC403", "ray_tpu/dashboard/grafana.py", 1,
                      "generate_default_dashboard",
                      f"default dashboard generation failed: {e!r}")
        return
    if not dash.get("panels"):
        yield Finding("RTC403", "ray_tpu/dashboard/grafana.py", 1,
                      "generate_default_dashboard",
                      "default dashboard lost its panels")
    for panel in dash.get("panels", []):
        for target in panel.get("targets", []):
            for name in re.findall(r"ray_tpu_[a-z0-9_]+",
                                   target.get("expr", "")):
                base = re.sub(r"_(?:bucket|sum|count)$", "", name)
                if base not in CATALOG and name not in CATALOG:
                    yield Finding(
                        "RTC403", "ray_tpu/dashboard/grafana.py", 1,
                        f"{panel.get('title', '?')}:{name}",
                        f"grafana panel {panel.get('title')!r} charts "
                        f"{name!r}, which the runtime never emits")
