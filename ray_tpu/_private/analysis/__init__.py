"""raylint: repo-wide invariant lint + lock-discipline analysis plane.

Five pass families over ``ray_tpu/`` (and the native sources they must
stay consistent with):

- ``lock-discipline`` (RTL1xx) — blocking calls / user callbacks under
  locks, timeout-less polls, lock-order cycles, lock-free writes to
  guarded attributes;
- ``knob-registry`` (RTK2xx) — every ``RAY_TPU_*`` env read declared in
  ``_private/knobs.KNOBS``, catalog/README drift both directions;
- ``wire-format`` (RTW3xx) — PROTOCOL_VERSION / frame kinds / shm oid
  layout consistent across ``protocol.py`` and ``src/rpc/rpc_core.cc``;
- ``metric-catalog`` + ``event-catalog`` (RTC4xx) — metric and event
  names declared in their single-source-of-truth catalogs;
- ``durability`` (RTD5xx) — persistence modules (checkpoints, GCS
  store/snapshot, spill, workflow storage) write through the
  temp+fsync+rename idiom (``_private/atomic_write.py``), never a bare
  write-mode ``open()`` or an fsync-less rename commit.

Run it: ``ray-tpu lint`` (or ``python -m ray_tpu.scripts.cli lint``).
Gate suite: ``tests/test_zz_lint.py``. Suppress one line with
``# raylint: disable=<CODE>``; document a by-design finding in
``baseline.txt`` (with a justification comment).
"""
from ray_tpu._private.analysis.core import (AnalysisContext, Finding,
                                            format_baseline, load_baseline,
                                            partition, run_all)

__all__ = ["AnalysisContext", "Finding", "format_baseline",
           "load_baseline", "partition", "run_all"]
