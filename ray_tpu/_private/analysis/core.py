"""raylint core: AST pass registry, findings, suppressions, baseline.

The repo-wide invariant checks (metric catalog, event catalog, knob
registry, lock discipline, wire-format consistency) started life as
ad-hoc asserts inside test files; this package makes them a subsystem
with one contract, the shape of the reference's sanitizer-tagged test
configs (python/ray/tests/BUILD asan tags) applied to *static*
invariants:

- every check is a registered **pass** producing typed ``Finding``s
  (stable code + file:line + a stable context key);
- a finding is silenced either **inline** (``# raylint: disable=CODE``
  on the offending line or the line above) or via the checked-in
  **baseline** (``baseline.txt`` next to this file — one line per
  documented-by-design finding, each with a justification comment);
- anything not silenced fails ``ray-tpu lint`` and the late-alphabet
  gate suite ``tests/test_zz_lint.py``.

Passes are pure functions over an ``AnalysisContext`` (parsed-once ASTs
plus raw text access with override hooks so tests can tamper with a
file's content without touching disk).
"""
from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Callable, Iterable

SUPPRESS_RE = re.compile(r"#\s*raylint:\s*disable=([A-Z0-9,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    code: str       # e.g. "RTL101" — stable, documented in README
    path: str       # repo-relative posix path
    line: int       # 1-indexed; NOT part of the baseline key
    context: str    # stable anchor, e.g. "Router._update_replicas"
    message: str

    @property
    def key(self) -> str:
        """Baseline identity: line numbers drift with unrelated edits,
        so the key is (code, file, enclosing def/class) instead."""
        return f"{self.code} {self.path} {self.context}"

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}: {self.code} [{self.context}] "
                f"{self.message}")


class Module:
    """One parsed source file."""

    __slots__ = ("path", "source", "tree", "_suppressions")

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source)
        self._suppressions: dict[int, set[str]] | None = None

    @property
    def suppressions(self) -> dict[int, set[str]]:
        """{lineno: {codes}} for every ``# raylint: disable=...``."""
        if self._suppressions is None:
            sup: dict[int, set[str]] = {}
            for i, line in enumerate(self.source.splitlines(), start=1):
                m = SUPPRESS_RE.search(line)
                if m:
                    sup[i] = {c.strip() for c in m.group(1).split(",")
                              if c.strip()}
            self._suppressions = sup
        return self._suppressions

    def suppressed(self, finding: Finding) -> bool:
        """The comment silences the reported line; the line above also
        counts, for expressions too long to share a line with it."""
        for ln in (finding.line, finding.line - 1):
            if finding.code in self.suppressions.get(ln, set()):
                return True
        return False


class AnalysisContext:
    """Lazily loads and caches the repo's sources for the passes.

    ``overrides`` maps repo-relative paths to replacement text (or None
    to simulate a deleted file) — the tamper hook the wire-format tests
    use to prove that e.g. a dropped PROTOCOL_VERSION line fails the
    lint without editing the real file.
    """

    def __init__(self, root: str | Path | None = None,
                 overrides: dict[str, str | None] | None = None):
        if root is None:
            import ray_tpu

            root = Path(ray_tpu.__file__).resolve().parent.parent
        self.root = Path(root)
        self.overrides = dict(overrides or {})
        self._modules: dict[str, Module | None] = {}

    # ----------------------------------------------------------- file io
    def read_text(self, relpath: str) -> str | None:
        """Raw text of a repo file (None when absent/overridden away)."""
        if relpath in self.overrides:
            return self.overrides[relpath]
        p = self.root / relpath
        try:
            return p.read_text()
        except OSError:
            return None

    def module(self, relpath: str) -> Module | None:
        """Parsed module for one .py file (None when missing or
        syntactically broken — the latter surfaces loudly elsewhere)."""
        if relpath not in self._modules:
            src = self.read_text(relpath)
            try:
                self._modules[relpath] = (Module(relpath, src)
                                          if src is not None else None)
            except SyntaxError:
                self._modules[relpath] = None
        return self._modules[relpath]

    def package_files(self, package: str = "ray_tpu") -> list[str]:
        names = set()
        for p in sorted((self.root / package).rglob("*.py")):
            names.add(p.relative_to(self.root).as_posix())
        for rel in self.overrides:
            if rel.startswith(package + "/") and rel.endswith(".py") \
                    and self.overrides[rel] is not None:
                names.add(rel)
        return sorted(n for n in names
                      if self.overrides.get(n, "") is not None)

    def package_modules(self, package: str = "ray_tpu"):
        for rel in self.package_files(package):
            mod = self.module(rel)
            if mod is not None:
                yield mod


# --------------------------------------------------------------- registry

PassFn = Callable[[AnalysisContext], Iterable[Finding]]
PASSES: dict[str, PassFn] = {}


def register(name: str):
    def deco(fn: PassFn) -> PassFn:
        PASSES[name] = fn
        return fn
    return deco


def _load_passes():
    """Import the pass modules (registration is import-time)."""
    from ray_tpu._private.analysis import (  # noqa: F401
        catalogs, durability, knobs_pass, lock_discipline, wire_format)


def run_all(ctx: AnalysisContext | None = None,
            passes: Iterable[str] | None = None) -> list[Finding]:
    """Run the requested passes (default: all) and return every finding
    that is NOT inline-suppressed. Baseline filtering is the caller's
    (``partition``) — callers usually want to see both sets."""
    _load_passes()
    if ctx is None:
        ctx = AnalysisContext()
    names = list(passes) if passes is not None else sorted(PASSES)
    unknown = [n for n in names if n not in PASSES]
    if unknown:
        raise ValueError(
            f"unknown pass name(s) {unknown}; valid passes: "
            f"{sorted(PASSES)}")
    findings: list[Finding] = []
    for name in names:
        for f in PASSES[name](ctx):
            mod = ctx.module(f.path) if f.path.endswith(".py") else None
            if mod is not None and mod.suppressed(f):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


# --------------------------------------------------------------- baseline

BASELINE_PATH = Path(__file__).with_name("baseline.txt")


def load_baseline(path: str | Path | None = None) -> dict[str, str]:
    """{finding key: justification}. Format, one finding per line::

        CODE path context  # why this is by-design

    Blank lines and full-line comments are ignored. The justification
    comment is REQUIRED by the gate suite — an unexplained baseline
    entry is itself a finding of the process, not the code."""
    p = Path(path) if path is not None else BASELINE_PATH
    entries: dict[str, str] = {}
    try:
        text = p.read_text()
    except OSError:
        return entries
    for line in text.splitlines():
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        body, _, comment = stripped.partition("#")
        parts = body.split()
        if len(parts) >= 3:
            entries[" ".join(parts[:3])] = comment.strip()
    return entries


# finding-code prefixes each pass family owns — staleness judgements
# only apply to families that actually ran (a `--passes wire-format`
# run must not condemn the lock-discipline baseline as stale)
PASS_CODES = {
    "lock-discipline": ("RTL",),
    "knob-registry": ("RTK",),
    "wire-format": ("RTW",),
    "metric-catalog": ("RTC401", "RTC402", "RTC403"),
    "event-catalog": ("RTC404", "RTC405"),
    "durability": ("RTD",),
}


def partition(findings: Iterable[Finding],
              baseline: dict[str, str] | None = None,
              passes: Iterable[str] | None = None):
    """(new, baselined, stale_keys): findings not covered by the
    baseline, findings the baseline documents, and baseline keys no
    pass produced any more (candidates for deletion). ``passes``
    restricts the staleness check to those families' codes (default:
    all)."""
    if baseline is None:
        baseline = load_baseline()
    prefixes = None
    if passes is not None:
        prefixes = tuple(p for name in passes
                         for p in PASS_CODES.get(name, ()))
    new, known = [], []
    seen = set()
    for f in findings:
        seen.add(f.key)
        (known if f.key in baseline else new).append(f)
    stale = sorted(
        k for k in baseline if k not in seen
        and (prefixes is None or k.startswith(prefixes)))
    return new, known, stale


def format_baseline(findings: Iterable[Finding]) -> str:
    """Render findings as baseline lines (justifications left TODO —
    the gate suite requires a human to fill them in)."""
    lines = []
    for f in sorted(set(f.key for f in findings)):
        lines.append(f"{f}  # TODO: justify or fix")
    return "\n".join(lines) + ("\n" if lines else "")


# ------------------------------------------------------------ AST helpers
# shared by the pass modules


def qualname_of(stack: list[ast.AST]) -> str:
    """Stable context key from the enclosing class/function stack."""
    names = [n.name for n in stack
             if isinstance(n, (ast.ClassDef, ast.FunctionDef,
                               ast.AsyncFunctionDef))]
    return ".".join(names) if names else "<module>"


def call_name(node: ast.Call) -> str:
    """Dotted best-effort name of a call target: ``time.sleep``,
    ``self._lock.acquire``, ``loader``..."""
    return dotted(node.func)


def dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""
