"""Knob-registry passes (the ``RTK2xx`` family).

- **RTK201 — undeclared knob read.** Every explicit
  ``os.environ``/``getenv`` read of a ``RAY_TPU_*`` name inside
  ``ray_tpu/`` must be declared in ``_private/knobs.KNOBS`` (or be a
  config-table-derived ``RAY_TPU_<CONFIG_KEY>``). A typo'd read
  otherwise silently returns the default forever.
- **RTK202 — knob missing from README.** Every cataloged knob must
  appear in README (its tables are generated from the catalog, so this
  only fires when someone adds a knob and forgets to regenerate).
- **RTK203 — dead catalog entry.** A cataloged knob no source file
  reads any more: delete it (or the code that should read it got
  dropped by mistake).
"""
from __future__ import annotations

import ast
import re

from ray_tpu._private.analysis.core import (AnalysisContext, Finding,
                                            dotted, register)

_ENV_CALLS = {"os.environ.get", "environ.get", "os.getenv", "getenv",
              "os.environ.setdefault", "environ.setdefault",
              "os.environ.pop", "environ.pop"}
_KNOB_RE = re.compile(r"^RAY_TPU_[A-Z0-9_]+$")


def _env_reads(tree: ast.Module):
    """Yield (name, node) for every RAY_TPU_* env access by literal."""
    for node in ast.walk(tree):
        literal = None
        if isinstance(node, ast.Call) and dotted(node.func) in _ENV_CALLS:
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                literal = node.args[0].value
        elif isinstance(node, ast.Subscript) and \
                dotted(node.value) in ("os.environ", "environ"):
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                literal = sl.value
        if literal is not None and _KNOB_RE.match(literal):
            yield literal, node


def _undeclared_read_findings(reads, path: str):
    from ray_tpu._private.knobs import is_declared

    out = []
    for name, node in reads:
        if not is_declared(name):
            out.append(Finding(
                "RTK201", path, node.lineno, name,
                f"env read of undeclared knob {name} — declare it in "
                f"_private/knobs.KNOBS (default/type/doc) and "
                f"regenerate the README table"))
    return out


def analyze_module_source(source: str, path: str = "<string>",
                          tree: ast.Module | None = None):
    """RTK201 over one source text (fixture-test entry point; the
    repo-wide pass hands in the context's cached ``tree``)."""
    if tree is None:
        tree = ast.parse(source)
    return _undeclared_read_findings(_env_reads(tree), path)


def _literal_knob_names(tree: ast.Module):
    """Every RAY_TPU_* string constant ASSIGNED in the module — knobs
    read through a named constant (``_MARKER = "RAY_TPU_ENV_OK"``)
    count as live even though the env access itself is dynamic."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)) and \
                isinstance(getattr(node, "value", None), ast.Constant) \
                and isinstance(node.value.value, str) \
                and _KNOB_RE.match(node.value.value):
            yield node.value.value


@register("knob-registry")
def knob_registry_pass(ctx: AnalysisContext):
    from ray_tpu._private.knobs import KNOBS, config_knob_names

    # knobs read through the config table (RAY_TPU_<CONFIG_KEY>) never
    # appear as env-access literals — they are live by construction
    used: set[str] = set(config_knob_names())
    for mod in ctx.package_modules():
        used.update(_literal_knob_names(mod.tree))
        reads = list(_env_reads(mod.tree))
        used.update(name for name, _node in reads)
        yield from _undeclared_read_findings(reads, mod.path)
    # liveness (RTK203) also counts harness/bench readers outside the
    # package — undeclared-read enforcement (RTK201) stays ray_tpu/-only
    for extra_pkg in ("tests", "benchmarks", "scripts"):
        for mod in ctx.package_modules(extra_pkg):
            for name, _node in _env_reads(mod.tree):
                used.add(name)

    readme = ctx.read_text("README.md") or ""
    for name, knob in sorted(KNOBS.items()):
        if name not in readme:
            yield Finding(
                "RTK202", "README.md", 1, name,
                f"cataloged knob {name} is not mentioned in README — "
                f"regenerate the knob table "
                f"(`ray-tpu lint --knob-table`)")
        if name not in used:
            yield Finding(
                "RTK203", "ray_tpu/_private/knobs.py", 1, name,
                f"cataloged knob {name} has no explicit env read left "
                f"in ray_tpu/ — dead entry, or its consumer was "
                f"dropped by mistake")
