"""Cross-language wire-format consistency (the ``RTW3xx`` family).

The frame protocol has two implementations (``_private/protocol.py`` and
``src/rpc/rpc_core.cc``) and the collective shm object id is laid out in
two files (``worker_runtime.py`` mints the prefix/epoch tags,
``host_backend.py`` appends rank + counter). PR 4 and PR 5 each nearly
shipped with the sides desynced (the "silent v3-peer desync" class); this
pass makes that unshippable:

- **RTW301 — constant missing.** ``PROTOCOL_VERSION`` /
  ``kProtocolVersion`` / a frame-kind constant vanished from either
  side; deleting the line now fails the lint instead of shipping.
- **RTW302 — protocol version mismatch** between Python and C++.
- **RTW303 — frame-kind constant mismatch** (REQUEST/REPLY/PUSH/
  PUSH_OOB vs kReq/kReply/kPush/kPushOob).
- **RTW304 — oid layout broken.** group-prefix + epoch + rank +
  counter widths must sum to the store's ``kIdSize`` exactly (PR 5's
  20-byte oid silently disabled the whole shm fast path).
- **RTW305 — collective wire-dtype tag missing/colliding.** The
  quantized-segment header tags (``WIRE_OFF``/``WIRE_BF16``/
  ``WIRE_INT8`` in ``util/collective/wire.py``) must all exist, be
  distinct, and each selectable format must be wired into
  ``WIRE_FORMATS`` — every group member parses peers' segment headers
  by these values, so losing or renumbering one silently turns
  quantized frames into garbage payloads on the receive side.
"""
from __future__ import annotations

import ast
import re

from ray_tpu._private.analysis.core import (AnalysisContext, Finding,
                                            dotted, register)

PROTOCOL_PY = "ray_tpu/_private/protocol.py"
RPC_CC = "src/rpc/rpc_core.cc"
STORE_CC = "src/store/store.cc"
WORKER_PY = "ray_tpu/_private/worker_runtime.py"
HOSTBK_PY = "ray_tpu/util/collective/host_backend.py"
WIRE_PY = "ray_tpu/util/collective/wire.py"

# quantized-segment header tags every group member must agree on
WIRE_TAG_NAMES = ("WIRE_OFF", "WIRE_BF16", "WIRE_INT8")

_CC_CONST_RE = re.compile(
    r"constexpr\s+(?:unsigned\s+)?(?:int|uint32_t|int32_t)\s+"
    r"(k[A-Za-z0-9_]+)\s*=\s*(-?\d+)\s*;")

# python name -> C++ name for the kinds that cross the wire
KIND_PAIRS = [("REQUEST", "kReq"), ("REPLY", "kReply"),
              ("PUSH", "kPush"), ("PUSH_OOB", "kPushOob")]


def _py_int_constants(tree: ast.Module) -> dict[str, int]:
    """Top-level int assignments, incl. tuple unpacking
    (``REQUEST, REPLY, PUSH = 0, 1, 2``)."""
    out: dict[str, int] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if isinstance(target, ast.Name) and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, int):
                out[target.id] = node.value.value
            elif isinstance(target, ast.Tuple) and \
                    isinstance(node.value, ast.Tuple) and \
                    len(target.elts) == len(node.value.elts):
                for t, v in zip(target.elts, node.value.elts):
                    if isinstance(t, ast.Name) and \
                            isinstance(v, ast.Constant) and \
                            isinstance(v.value, int):
                        out[t.id] = v.value
    return out


def _cc_constants(text: str) -> dict[str, int]:
    return {m.group(1): int(m.group(2))
            for m in _CC_CONST_RE.finditer(text)}


def _find_fn(tree: ast.Module, name: str):
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


def _oid_widths(worker_tree: ast.Module, host_tree: ast.Module) -> dict:
    """Byte widths of each collective shm oid component, read from the
    code that mints them (None for a component that can't be found —
    the check treats that as a layout break, not a skip)."""
    widths = {"prefix": None, "epoch": None, "rank": None,
              "counter": None}

    fn = _find_fn(worker_tree, "col_oid_prefix")
    if fn is not None:
        const_bytes = 0
        digest = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, bytes):
                const_bytes += len(node.value)
            if isinstance(node, ast.keyword) and \
                    node.arg == "digest_size" and \
                    isinstance(node.value, ast.Constant):
                digest = int(node.value.value)
        if digest is not None:
            widths["prefix"] = const_bytes + digest

    fn = _find_fn(worker_tree, "col_epoch_tag")
    if fn is not None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "to_bytes" and \
                    node.args and isinstance(node.args[0], ast.Constant):
                widths["epoch"] = int(node.args[0].value)

    for node in ast.walk(host_tree):
        if isinstance(node, ast.Call) and \
                dotted(node.func) == "self.rank.to_bytes" and \
                node.args and isinstance(node.args[0], ast.Constant):
            widths["rank"] = int(node.args[0].value)
        if isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Call) and \
                dotted(node.value.func).endswith("._new_id") and \
                isinstance(node.slice, ast.Slice) and \
                node.slice.upper is None and \
                isinstance(node.slice.lower, ast.Constant):
            # _new_id() mints a full store-id-sized value; the slice
            # keeps its low (kIdSize - lower) counter bytes
            widths["counter"] = ("tail", int(node.slice.lower.value))
    return widths


def _wire_formats_map(tree: ast.Module) -> dict[str, str]:
    """The ``WIRE_FORMATS`` literal: config value -> tag constant name
    (``{"bf16": WIRE_BF16, ...}``)."""
    out: dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "WIRE_FORMATS"
                for t in node.targets) and isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str) and \
                        isinstance(v, ast.Name):
                    out[k.value] = v.id
    return out


def parse_layout(ctx: AnalysisContext | None = None) -> dict:
    """The parsed cross-language constants, for tests to pin:
    {py: {...}, cc: {...}, id_size, oid_widths, wire_tags,
    wire_formats}. Missing files/constants appear as absent keys / None
    values."""
    if ctx is None:
        ctx = AnalysisContext()
    out: dict = {"py": {}, "cc": {}, "id_size": None, "oid_widths": {},
                 "wire_tags": {}, "wire_formats": {}}
    mod = ctx.module(PROTOCOL_PY)
    if mod is not None:
        out["py"] = _py_int_constants(mod.tree)
    cc = ctx.read_text(RPC_CC)
    if cc is not None:
        out["cc"] = _cc_constants(cc)
    store = ctx.read_text(STORE_CC)
    if store is not None:
        m = re.search(r"kIdSize\s*=\s*(\d+)", store)
        if m:
            out["id_size"] = int(m.group(1))
    worker = ctx.module(WORKER_PY)
    host = ctx.module(HOSTBK_PY)
    if worker is not None and host is not None:
        out["oid_widths"] = _oid_widths(worker.tree, host.tree)
    wiremod = ctx.module(WIRE_PY)
    if wiremod is not None:
        consts = _py_int_constants(wiremod.tree)
        out["wire_tags"] = {n: consts.get(n) for n in WIRE_TAG_NAMES}
        out["wire_formats"] = _wire_formats_map(wiremod.tree)
    return out


@register("wire-format")
def wire_format_pass(ctx: AnalysisContext):
    layout = parse_layout(ctx)
    py, cc = layout["py"], layout["cc"]

    if "PROTOCOL_VERSION" not in py:
        yield Finding("RTW301", PROTOCOL_PY, 1, "PROTOCOL_VERSION",
                      "PROTOCOL_VERSION constant missing from "
                      "protocol.py — the Python side no longer pins a "
                      "wire revision")
    if "kProtocolVersion" not in cc:
        yield Finding("RTW301", RPC_CC, 1, "kProtocolVersion",
                      "kProtocolVersion constant missing from "
                      "rpc_core.cc — the native side no longer pins a "
                      "wire revision")
    if "PROTOCOL_VERSION" in py and "kProtocolVersion" in cc and \
            py["PROTOCOL_VERSION"] != cc["kProtocolVersion"]:
        yield Finding(
            "RTW302", PROTOCOL_PY, 1, "PROTOCOL_VERSION",
            f"protocol version desync: protocol.py speaks "
            f"v{py['PROTOCOL_VERSION']} but rpc_core.cc speaks "
            f"v{cc['kProtocolVersion']} — a mixed build would reject "
            f"every frame (or worse, misparse)")

    for py_name, cc_name in KIND_PAIRS:
        if py_name not in py:
            yield Finding("RTW301", PROTOCOL_PY, 1, py_name,
                          f"frame-kind constant {py_name} missing from "
                          f"protocol.py")
            continue
        if cc_name not in cc:
            yield Finding("RTW301", RPC_CC, 1, cc_name,
                          f"frame-kind constant {cc_name} missing from "
                          f"rpc_core.cc")
            continue
        if py[py_name] != cc[cc_name]:
            yield Finding(
                "RTW303", PROTOCOL_PY, 1, py_name,
                f"frame-kind desync: {py_name}={py[py_name]} in "
                f"protocol.py but {cc_name}={cc[cc_name]} in "
                f"rpc_core.cc")

    id_size = layout["id_size"]
    widths = layout["oid_widths"]
    if id_size is None:
        yield Finding("RTW304", STORE_CC, 1, "kIdSize",
                      "store id size (kIdSize) not found in store.cc")
    elif widths:
        missing = [k for k, v in widths.items() if v is None]
        if missing:
            yield Finding(
                "RTW304", WORKER_PY, 1, "col_oid_layout",
                f"collective shm oid layout: could not locate the "
                f"{'/'.join(missing)} component width(s) in the "
                f"minting code — layout check cannot hold")
        else:
            counter = widths["counter"]
            counter_w = (id_size - counter[1]
                         if isinstance(counter, tuple) else counter)
            total = (widths["prefix"] + widths["epoch"]
                     + widths["rank"] + counter_w)
            if total != id_size:
                yield Finding(
                    "RTW304", HOSTBK_PY, 1, "col_oid_layout",
                    f"collective shm oid layout is {total} bytes "
                    f"(prefix {widths['prefix']} + epoch "
                    f"{widths['epoch']} + rank {widths['rank']} + "
                    f"counter {counter_w}) but the store id is "
                    f"{id_size} bytes — a mismatched oid silently "
                    f"disables the whole shm fast path (the PR 5 bug)")

    tags = layout["wire_tags"]
    if not tags:
        yield Finding(
            "RTW305", WIRE_PY, 1, "wire_tags",
            "util/collective/wire.py is missing or unparseable — the "
            "quantized-segment wire tags can no longer be pinned")
    else:
        for name in WIRE_TAG_NAMES:
            if tags.get(name) is None:
                yield Finding(
                    "RTW305", WIRE_PY, 1, name,
                    f"wire-dtype tag {name} missing from wire.py — "
                    f"receivers can no longer identify that segment "
                    f"header, so a peer still sending it delivers "
                    f"garbage payloads")
        values = [v for v in tags.values() if v is not None]
        if len(set(values)) != len(values):
            yield Finding(
                "RTW305", WIRE_PY, 1, "wire_tag_collision",
                f"wire-dtype tags collide: {tags} — two formats would "
                f"parse each other's segment headers")
        fmts = layout["wire_formats"]
        for fmt, tag_name in sorted(fmts.items()):
            if tags.get(tag_name) is None:
                yield Finding(
                    "RTW305", WIRE_PY, 1, f"WIRE_FORMATS[{fmt}]",
                    f"WIRE_FORMATS maps {fmt!r} to {tag_name}, which is "
                    f"not a pinned wire tag")
