"""Control-plane RPC: length-prefixed pickle frames over TCP.

The reference's control plane is gRPC (/root/reference/src/ray/rpc/ —
GrpcServer, ClientCall); ours has the same shape: persistent
bidirectional connections, request/reply correlation ids, and one-way
pushes. Pickle is safe here because every endpoint belongs to the same
trust domain (one cluster, one user), exactly like the reference's
cloudpickled task specs.

Wire format (shared with the native C++ core, src/rpc/rpc_core.cc):
``[len: u64 BE] [ver<<4 | kind: u8] [seq: i64 BE] [payload: len-9 bytes]``
where payload is an opaque pickle. kind (low nibble) is
REQUEST/REPLY/PUSH; the high nibble carries PROTOCOL_VERSION so a peer
speaking a different frame layout is rejected with a named error instead
of a misparse (the reference versions its protobuf schema the same way).

Two interoperable implementations: the native C++ core (framing,
correlation and queueing off-GIL — the default; see native_rpc.py) and
the pure-Python classes below (fallback, and the semantic reference).
``RAY_TPU_NATIVE_RPC=0`` forces pure Python.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import sys
import threading
import time
import traceback
import uuid

from ray_tpu._private import fault_injection as _fi
from ray_tpu._private import telemetry as _tm

# Chaos plane: RAY_TPU_FAULT_SCHEDULE activates the injector for every
# transport in this process (and, via env inheritance, every spawned
# cluster process). Disabled cost per call: one global load + None check.
_fi.maybe_init_from_env()

REQUEST, REPLY, PUSH = 0, 1, 2
# One-way frame carrying an out-of-band payload: the wire payload is
# [u32 head_len][pickle (method, kwargs, pool_hint)][raw body bytes...]
# instead of one monolithic pickle, so tensor segments travel as raw
# buffers (scatter-gather written, received straight into a reusable
# buffer) and the receiver hands the handler a zero-copy OobFrame.
# Kinds ride the frame header's low nibble end-to-end through the native
# C core untouched (rpc_core.cc passes `kind` opaquely), so this needs
# no C change; PROTOCOL_VERSION gates cross-build mixes as usual.
PUSH_OOB = 3

# Bump on any incompatible frame-layout/semantics change. Must match
# kProtocolVersion in src/rpc/rpc_core.cc.
# Detection is receive-side: a v(N) receiver names a v(M!=N) sender's rev
# in the error. The inverse direction against a PRE-versioning build (which
# reads the whole byte as `kind`) surfaces as silently dropped frames →
# call timeout, not a named error; v1 is the first versioned rev, so that
# legacy pairing disappears once every node runs any versioned build.
# v2: owner-based object directory (free_objects locations kwarg,
# register_worker node snapshot, task-reply stored_sizes/node keys).
# v3: PUSH_OOB frames (kind 3 carries an out-of-band payload layout a
# v2 receiver would misparse as a pickle — the data-plane collective
# frames, worker_runtime rpc_col_push_frame).
# v4: collective incarnation epochs (col frame keys gain an epoch slot —
# seq_pos 2→3 — and shm oids re-lay as group(6)+epoch(4)+rank(2)+ctr(4));
# a v3 peer's frames would never match a v4 receiver's mailbox keys and
# every op would ride out the full collective timeout instead of failing
# fast here.
PROTOCOL_VERSION = 4

_HDR = struct.Struct(">QBq")   # total-after-len, ver<<4|kind, seq
_U32 = struct.Struct(">I")     # PUSH_OOB head length prefix

# Sentinel a handler returns to suppress the automatic reply; it must
# then answer later via conn.reply(seq, result) (deferred replies let
# e.g. the worker main loop answer task pushes without parking a
# dispatch thread per in-flight task).
NO_REPLY = object()


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class ProtocolMismatch(RpcError):
    """Peer speaks a different frame-protocol version; the connection is
    unusable and gets dropped (both ends must run the same wire rev)."""


# Receive-buffer pool for PUSH_OOB bodies. The consumer side
# (worker_runtime's collective mailbox) registers an object with
# acquire(pool_key, nbytes) -> writable buffer and
# release(pool_key, buf); with one registered, steady-state segment
# receives recycle the same buffers instead of allocating per message.
_OOB_POOL = None


def set_oob_buffer_pool(pool):
    global _OOB_POOL
    _OOB_POOL = pool


class OobFrame:
    """A received PUSH_OOB body: a zero-copy view plus its (possibly
    pooled) backing buffer. The HANDLER owns it — call release() once
    the bytes are consumed so a pooled buffer returns to the pool.
    release() is idempotent; frames over non-pooled memory no-op."""

    __slots__ = ("view", "_buf", "_pool_key")

    def __init__(self, buf, view, pool_key=None):
        self._buf = buf
        self.view = view
        self._pool_key = pool_key

    @property
    def nbytes(self) -> int:
        return self.view.nbytes

    def release(self):
        buf, self._buf, self.view = self._buf, None, None
        if buf is not None and self._pool_key is not None \
                and _OOB_POOL is not None:
            _OOB_POOL.release(self._pool_key, buf)


def _send_frame(sock: socket.socket, kind: int, seq: int, payload,
                lock: threading.Lock):
    data = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    hdr = _HDR.pack(len(data) + 9, (PROTOCOL_VERSION << 4) | kind, seq)
    with lock:
        # the write lock EXISTS to serialize socket writes — holding it
        # across sendall is its entire job, not a lock-discipline bug
        sock.sendall(hdr + data)  # raylint: disable=RTL101


def _send_frame_parts(sock: socket.socket, head: bytes, parts,
                      lock: threading.Lock):
    """Write one PUSH_OOB frame scatter-gather: header + head pickle,
    then each body part straight from its source buffer (numpy segment
    memory, a forwarded frame view) — no assembled intermediate."""
    body = sum(memoryview(p).nbytes for p in parts)
    hdr = _HDR.pack(9 + 4 + len(head) + body,
                    (PROTOCOL_VERSION << 4) | PUSH_OOB, 0)
    with lock:
        # as in _send_frame: the per-connection write lock's purpose is
        # to keep scatter-gather frame writes contiguous on the socket
        sock.sendall(hdr + _U32.pack(len(head)) + head)  # raylint: disable=RTL101
        for p in parts:
            sock.sendall(p)  # raylint: disable=RTL101


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionLost("peer closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _recv_exact_into(sock: socket.socket, view: memoryview):
    n = view.nbytes
    off = 0
    while off < n:
        r = sock.recv_into(view[off:], min(n - off, 1 << 20))
        if not r:
            raise ConnectionLost("peer closed")
        off += r


def _recv_frame(sock: socket.socket):
    length, kind_byte, seq = _HDR.unpack(_recv_exact(sock, 17))
    ver = kind_byte >> 4
    if ver != PROTOCOL_VERSION:
        raise ProtocolMismatch(
            f"rpc protocol version mismatch: peer sent v{ver}, this "
            f"process speaks v{PROTOCOL_VERSION} — both ends of a cluster "
            f"must run the same ray-tpu wire revision")
    kind = kind_byte & 0x0F
    if kind == PUSH_OOB:
        (head_len,) = _U32.unpack(_recv_exact(sock, 4))
        method, kwargs, pool_hint = pickle.loads(_recv_exact(sock, head_len))
        body_len = length - 9 - 4 - head_len
        pool = _OOB_POOL
        buf = None
        pool_key = None
        if pool is not None and pool_hint is not None:
            pool_key = (pool_hint, body_len)
            buf = pool.acquire(pool_key, body_len)
        if buf is None:
            buf, pool_key = bytearray(body_len), None
        try:
            _recv_exact_into(sock, memoryview(buf))
        except BaseException:
            # connection died mid-body: hand the buffer back so the
            # pool's recycled capacity survives reconnect cycles
            if pool_key is not None and pool is not None:
                pool.release(pool_key, buf)
            raise
        return kind, seq, (method, kwargs,
                           OobFrame(buf, memoryview(buf), pool_key))
    return kind, seq, pickle.loads(_recv_exact(sock, length - 9))


# (The native transport's already-contiguous PUSH_OOB payloads are
# parsed by native_rpc._NativeOobFrame.parse_head — same
# [u32 head_len][pickle head][body] layout as the incremental socket
# read above; keep the two in sync on any layout change.)


class _RemoteError:
    """Marker wrapper: the handler raised; re-raise at the caller."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class PyRpcClient:
    """A persistent connection to one RpcServer. Thread-safe; many in-flight
    calls multiplex on the connection (like the reference's ClientCallManager,
    rpc/client_call.h)."""

    def __init__(self, addr: tuple[str, int], timeout: float = 30.0,
                 on_push=None, retry: int = 3, on_close=None):
        from ray_tpu._private.retry import RetryPolicy

        self.addr = tuple(addr)
        self._timeout = timeout
        self._on_push = on_push
        # Fired (once, from the reader thread) when the connection is
        # LOST — peer died, reset, protocol mismatch — but NOT on a
        # deliberate local close(). Liveness consumers (the collective
        # data plane's peer-death detector) key off exactly that
        # asymmetry: our own teardown is not a peer failure.
        self._on_close = on_close
        self._deliberate_close = False
        policy = RetryPolicy(max_attempts=retry, deadline_s=None)
        last = None
        for attempt in range(retry):
            try:
                self._sock = socket.create_connection(self.addr, timeout=timeout)
                break
            except OSError as e:
                last = e
                if attempt + 1 < retry:
                    time.sleep(policy.backoff(attempt + 1))
        else:
            raise ConnectionLost(f"cannot connect to {self.addr}: {last}")
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)
        self._wlock = threading.Lock()
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._pending: dict[int, _Future] = {}
        self._closed = False
        self._mismatch: ProtocolMismatch | None = None
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name=f"rpc-client-{self.addr}")
        self._reader.start()

    def _next_seq(self) -> int:
        with self._seq_lock:
            self._seq += 1
            return self._seq

    def _read_loop(self):
        mismatch = None
        try:
            while True:
                kind, seq, payload = _recv_frame(self._sock)
                if kind == REPLY:
                    fut = self._pending.pop(seq, None)
                    if fut is not None:
                        fut.set(payload)
                elif kind == PUSH and self._on_push is not None:
                    try:
                        self._on_push(payload)
                    except Exception:
                        pass
                elif kind == PUSH_OOB:
                    # servers never OOB-push to clients today; reclaim
                    # the buffer instead of leaking it from the pool
                    payload[2].release()
        except ProtocolMismatch as e:
            mismatch = self._mismatch = e
            print(f"ray-tpu rpc: {e} (peer {self.addr})",
                  file=sys.stderr, flush=True)
        except (ConnectionLost, OSError, EOFError, pickle.UnpicklingError):
            if os.environ.get("RAY_TPU_RPC_DEBUG"):
                import traceback
                print(f"[rpc-debug pid={os.getpid()}] client read_loop to "
                      f"{self.addr} died:", flush=True)
                traceback.print_exc()
        finally:
            self._closed = True
            # On a version mismatch the TCP connection is still healthy —
            # drop it or the fd (and the peer's sends) leak. shutdown, NOT
            # close: a writer thread may be inside sendall on this socket,
            # and close() would free the fd number for reuse mid-write
            # (same reasoning as rpc_core.cc reader_loop).
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            err = _RemoteError(
                mismatch
                or ConnectionLost(f"connection to {self.addr} lost"))
            for fut in list(self._pending.values()):
                fut.set(err)
            self._pending.clear()
            if self._on_close is not None and not self._deliberate_close:
                try:
                    self._on_close()
                except Exception:
                    traceback.print_exc()

    def call(self, method: str, timeout: float | None = None, **kwargs):
        """Synchronous request/reply."""
        start = time.monotonic() if _tm.ENABLED else 0.0
        try:
            fut = self.call_async(method, **kwargs)
        except ConnectionLost:
            # send-side failure (dead socket, injected disconnect)
            _tm.counter_inc("ray_tpu_rpc_errors_total", tags={
                "method": method, "role": _tm.role(),
                "kind": "connection_lost"})
            raise
        try:
            result = fut.result(
                timeout if timeout is not None else self._timeout)
        except TimeoutError:
            # Nobody will ever consume this future — reap its _pending
            # slot now instead of carrying it for the connection's
            # lifetime (a late reply finds the slot empty and is
            # dropped; injected drops would otherwise leak one slot per
            # fault over a long chaos soak).
            self._pending.pop(fut.seq, None)
            _tm.counter_inc("ray_tpu_rpc_errors_total", tags={
                "method": method, "role": _tm.role(), "kind": "timeout"})
            raise
        except ConnectionLost:
            _tm.counter_inc("ray_tpu_rpc_errors_total", tags={
                "method": method, "role": _tm.role(),
                "kind": "connection_lost"})
            raise
        if _tm.ENABLED:
            _tm.observe("ray_tpu_rpc_latency_seconds",
                        time.monotonic() - start,
                        tags={"method": method, "role": _tm.role()})
        return result

    def call_async(self, method: str, **kwargs) -> "_Future":
        if self._closed:
            raise self._mismatch or ConnectionLost(
                f"connection to {self.addr} closed")
        inj = _fi.ACTIVE
        plan = inj.on_send(method) if inj is not None else None
        if plan is not None:
            _fi.apply_send_plan(plan, self.close, method)
        seq = self._next_seq()
        fut = _Future()
        fut.seq = seq   # lets the sync path reap _pending on timeout
        self._pending[seq] = fut
        # Re-check after registering: the reader may have drained _pending on
        # connection loss between the check above and the insert, which would
        # leave this future unresolvable.
        if self._closed:
            self._pending.pop(seq, None)
            raise self._mismatch or ConnectionLost(
                f"connection to {self.addr} closed")
        if plan is not None and plan.drop:
            return fut   # injected message loss: registered, never sent
        try:
            _send_frame(self._sock, REQUEST, seq, (method, kwargs), self._wlock)
            if plan is not None and plan.dup:
                # same seq twice: the duplicate reply is discarded by the
                # _pending pop; the SERVER sees (and must tolerate) both
                _send_frame(self._sock, REQUEST, seq, (method, kwargs),
                            self._wlock)
        except OSError as e:
            self._pending.pop(seq, None)
            self._closed = True
            raise ConnectionLost(str(e)) from e
        return fut

    def push(self, method: str, **kwargs):
        """One-way message; no reply expected."""
        if self._closed:
            raise self._mismatch or ConnectionLost(
                f"connection to {self.addr} closed")
        inj = _fi.ACTIVE
        plan = inj.on_send(method) if inj is not None else None
        if plan is not None:
            _fi.apply_send_plan(plan, self.close, method)
            if plan.drop:
                return   # injected loss: one-way messages vanish silently
        try:
            _send_frame(self._sock, PUSH, 0, (method, kwargs), self._wlock)
            if plan is not None and plan.dup:
                _send_frame(self._sock, PUSH, 0, (method, kwargs),
                            self._wlock)
        except OSError as e:
            self._closed = True
            raise ConnectionLost(str(e)) from e

    def push_parts(self, method: str, kwargs: dict, parts,
                   pool: str | None = None):
        """One-way out-of-band send: `parts` (a serialize_parts frame or
        any buffer sequence) is written scatter-gather after a small
        pickled head — no monolithic payload pickle, no reply. The
        receiver's handler gets the body as a zero-copy OobFrame kwarg
        ``frame``; `pool` names the receive-buffer pool the peer should
        draw from (and return to, via frame.release()). Completion is
        detected by the CONSUMER (e.g. the collective op timeout), so an
        injected drop surfaces there, exactly like real one-way loss."""
        if self._closed:
            raise self._mismatch or ConnectionLost(
                f"connection to {self.addr} closed")
        inj = _fi.ACTIVE
        plan = inj.on_send(method) if inj is not None else None
        if plan is not None:
            _fi.apply_send_plan(plan, self.close, method)
            if plan.drop:
                return   # injected loss: one-way messages vanish silently
        head = pickle.dumps((method, kwargs, pool),
                            protocol=pickle.HIGHEST_PROTOCOL)
        try:
            _send_frame_parts(self._sock, head, parts, self._wlock)
            if plan is not None and plan.dup:
                _send_frame_parts(self._sock, head, parts, self._wlock)
        except OSError as e:
            self._closed = True
            raise ConnectionLost(str(e)) from e

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self):
        self._deliberate_close = True   # before the shutdown wakes the
        self._closed = True             # reader into its finally block
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class _Future:
    def __init__(self):
        self._ev = threading.Event()
        self._value = None
        self._cb = None
        self._cb_lock = threading.Lock()

    def set(self, value):
        self._value = value
        # the lock makes the set-flag/claim-callback pair atomic against
        # add_done_callback — without it the two sides can BOTH observe
        # "flag set, callback present" and fire cb twice (double
        # _task_done corrupts in_flight accounting)
        with self._cb_lock:
            self._ev.set()
            cb, self._cb = self._cb, None
        if cb is not None:
            try:
                cb(value)
            except Exception:
                # a reply-path callback failure would otherwise hang the
                # caller's get() with zero diagnostics (the old
                # thread-per-reply pattern at least hit threading.excepthook)
                traceback.print_exc()

    def done(self) -> bool:
        return self._ev.is_set()

    def add_done_callback(self, cb):
        """Run ``cb(raw_value)`` when the reply lands — on the transport's
        reader/pump thread, so cb MUST NOT block and MUST NOT issue a sync
        call over the same connection (the thread that would deliver that
        reply is the one running cb). A _RemoteError value arrives
        UNWRAPPED; callers unwrap instead of raising. Replaces the
        thread-per-in-flight-call reply pattern on the task hot path."""
        with self._cb_lock:
            if not self._ev.is_set():
                self._cb = cb      # set() will run it
                return
        try:
            cb(self._value)
        except Exception:
            traceback.print_exc()

    def result(self, timeout: float | None = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("rpc call timed out")
        if isinstance(self._value, _RemoteError):
            raise self._value.exc
        return self._value


class Connection:
    """Server-side view of one accepted client connection."""

    def __init__(self, sock: socket.socket, addr):
        self.sock = sock
        self.peer = addr
        self.wlock = threading.Lock()
        self.id = uuid.uuid4().hex
        self.meta: dict = {}
        self.alive = True

    def push(self, method: str, **kwargs):
        try:
            _send_frame(self.sock, PUSH, 0, (method, kwargs), self.wlock)
        except OSError:
            self.alive = False

    def reply(self, seq: int, result):
        """Send a deferred reply (pairs with a handler returning NO_REPLY)."""
        try:
            _send_frame(self.sock, REPLY, seq, result, self.wlock)
        except OSError:
            self.alive = False


class PyRpcServer:
    """Threaded RPC server. A handler object exposes `rpc_<method>` callables;
    each gets (conn, **kwargs). Raising inside a handler propagates the
    exception to the caller. A handler may also expose `on_connect(conn)` /
    `on_disconnect(conn)` for liveness tracking (the reference tracks client
    death via socket EOF the same way, common/client_connection.h), an
    ``INLINE_RPC`` set naming non-blocking methods dispatched inline on the
    connection's reader thread, and handlers may return NO_REPLY to answer
    later via conn.reply."""

    def __init__(self, handler, host: str = "127.0.0.1", port: int = 0):
        self._handler = handler
        self._inline = getattr(handler, "INLINE_RPC", frozenset())
        # methods that take (conn, seq, **kwargs) so they can answer
        # later via conn.reply(seq, ...) after returning NO_REPLY
        self._deferred = getattr(handler, "DEFERRED_RPC", frozenset())
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(512)
        self.addr = self._listener.getsockname()
        self._stopped = False
        self._conns: dict[str, Connection] = {}
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name=f"rpc-accept-{self.addr[1]}")

    def start(self):
        self._accept_thread.start()
        return self

    def _accept_loop(self):
        while not self._stopped:
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return
            if self._stopped:
                # stop() raced the accept (stop() joins us before releasing
                # the listener fd, so this conn is genuinely ours): the
                # server is going down — close instead of serving.
                try:
                    sock.close()
                except OSError:
                    pass
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = Connection(sock, addr)
            self._conns[conn.id] = conn
            if os.environ.get("RAY_TPU_RPC_DEBUG"):
                print(f"[rpc-debug pid={os.getpid()}] "
                      f"{type(self._handler).__name__}@{self.addr} accepted "
                      f"conn from {addr}", flush=True)
            threading.Thread(target=self._serve_conn, args=(conn,), daemon=True,
                             name=f"rpc-conn-{addr}").start()

    def _serve_conn(self, conn: Connection):
        on_connect = getattr(self._handler, "on_connect", None)
        if on_connect is not None:
            on_connect(conn)
        try:
            while not self._stopped:
                kind, seq, payload = _recv_frame(conn.sock)
                if kind == PUSH_OOB:
                    # inline on the reader thread, like PUSH: OOB
                    # handlers (mailbox stores) must not block
                    method, kwargs, frame = payload
                    try:
                        self._lookup(method)(conn, frame=frame, **kwargs)
                    except Exception:
                        frame.release()
                    continue
                method, kwargs = payload
                if kind == REQUEST:
                    if method in self._inline:
                        self._dispatch(conn, seq, method, kwargs)
                    else:
                        threading.Thread(
                            target=self._dispatch,
                            args=(conn, seq, method, kwargs),
                            daemon=True).start()
                elif kind == PUSH:
                    try:
                        self._lookup(method)(conn, **kwargs)
                    except Exception:
                        pass
        except ProtocolMismatch as e:
            # Drop the connection loudly: we cannot even parse the peer's
            # frames, so an in-band error reply is impossible.
            print(f"ray-tpu rpc: {e} (client {conn.peer}); dropping "
                  f"connection", file=sys.stderr, flush=True)
        except (ConnectionLost, OSError, EOFError, pickle.UnpicklingError) as e:
            if os.environ.get("RAY_TPU_RPC_DEBUG"):
                print(f"[rpc-debug pid={os.getpid()}] "
                      f"{type(self._handler).__name__}@{self.addr} conn from "
                      f"{conn.peer} died: {type(e).__name__}: {e} "
                      f"(stopped={self._stopped})", flush=True)
        finally:
            conn.alive = False
            self._conns.pop(conn.id, None)
            on_disconnect = getattr(self._handler, "on_disconnect", None)
            if on_disconnect is not None:
                try:
                    on_disconnect(conn)
                except Exception:
                    pass
            try:
                conn.sock.close()
            except OSError:
                pass

    def _lookup(self, method: str):
        fn = getattr(self._handler, f"rpc_{method}", None)
        if fn is None:
            raise RpcError(f"no such rpc method: {method}")
        return fn

    def _dispatch(self, conn: Connection, seq: int, method: str, kwargs):
        try:
            if method in self._deferred:
                result = self._lookup(method)(conn, seq, **kwargs)
            else:
                result = self._lookup(method)(conn, **kwargs)
        except BaseException as e:  # noqa: BLE001 — ship handler errors back
            result = _RemoteError(e)
        if result is NO_REPLY:
            return
        inj = _fi.ACTIVE
        if inj is not None:
            stall = inj.on_reply(method)
            if stall:
                time.sleep(stall)   # injected slow peer (GC pause analog)
        try:
            _send_frame(conn.sock, REPLY, seq, result, conn.wlock)
        except OSError:
            conn.alive = False

    def connections(self):
        return list(self._conns.values())

    def stop(self):
        self._stopped = True
        if os.environ.get("RAY_TPU_RPC_DEBUG"):
            print(f"[rpc-debug pid={os.getpid()}] "
                  f"{type(self._handler).__name__}@{self.addr} stop(): closing "
                  f"{len(self._conns)} conns", flush=True)
        # Wake the accept thread BEFORE releasing the listener fd: a thread
        # blocked in accept() does not notice close(), and once the fd number
        # is reused by a new listener in this process the stale thread would
        # steal (and instantly close) the new server's connections. shutdown()
        # interrupts the blocked accept; join guarantees the thread is gone
        # before close() frees the fd for reuse.
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        if self._accept_thread.is_alive() and \
                threading.current_thread() is not self._accept_thread:
            self._accept_thread.join(timeout=5.0)
        try:
            self._listener.close()
        except OSError:
            pass
        for conn in list(self._conns.values()):
            # shutdown BEFORE close: a plain close() while a _serve_conn
            # thread is blocked in recv on the same socket is deferred by
            # CPython's fd guard — no FIN is sent and remote clients
            # (e.g. a lease request to this dying raylet) hang until their
            # own timeout instead of failing over immediately.
            try:
                conn.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.sock.close()
            except OSError:
                pass


# --------------------------------------------------------------- selection

_native_state: list = []   # [] = undecided, [True/False] = decided


def _use_native() -> bool:
    if not _native_state:
        use = os.environ.get("RAY_TPU_NATIVE_RPC", "1") == "1"
        if use:
            try:
                from ray_tpu._private.native_rpc import load_lib

                load_lib()
            except Exception:
                use = False   # toolchain missing: pure Python still works
        _native_state.append(use)
    return _native_state[0]


def RpcClient(addr, timeout: float = 30.0, on_push=None, retry: int = 3):
    """Factory: native C++ transport when available, else pure Python."""
    if _use_native():
        from ray_tpu._private.native_rpc import NativeRpcClient

        return NativeRpcClient(addr, timeout=timeout, on_push=on_push,
                               retry=retry)
    return PyRpcClient(addr, timeout=timeout, on_push=on_push, retry=retry)


def RpcServer(handler, host: str = "127.0.0.1", port: int = 0):
    """Factory: native C++ transport when available, else pure Python."""
    if _use_native():
        from ray_tpu._private.native_rpc import NativeRpcServer

        return NativeRpcServer(handler, host=host, port=port)
    return PyRpcServer(handler, host=host, port=port)


class _ReconnectFailed(Exception):
    """Internal sentinel: the heal attempt found the endpoint DEAD (its
    own connect failed). Deliberately NOT a ConnectionLost subclass so
    the retry policy's retry_on can't catch it — the caller unwraps
    `.cause` back into the original ConnectionLost."""

    def __init__(self, cause: BaseException):
        self.cause = cause
        super().__init__(str(cause))


class ReconnectingRpcClient:
    """Self-healing client for control-plane endpoints that may RESTART
    (the GCS in fault-tolerant mode). On ConnectionLost the call
    reconnects once and retries; an `on_reconnect(raw_client)` hook lets
    the owner replay its registration state (reference:
    gcs_rpc_client.h reconnection + node_manager.cc:1179
    HandleNotifyGCSRestart re-registration).

    Retry semantics ride the unified control-plane policy
    (_private/retry.py): per-method idempotency decides whether a call
    that MAY have been applied is re-sent at all (non-retry-safe
    methods fail fast — actor_failed double-charges the restart budget
    on replay), retries back off with full jitter under a wall-clock
    deadline that also shrinks each attempt's RPC timeout, and a
    process-wide budget bounds retry amplification during an outage.
    Message shapes of the top control RPCs are validated HERE, at the
    producer boundary (task_spec.validate_control_rpc), so a typo'd
    field fails in the calling process, not as a KeyError in the GCS.

    GCS table ops are retry-safe (register_* overwrite by id, kv_put
    overwrites, actor_started re-announces). A new non-idempotent op
    must either be listed in retry.NON_RETRY_SAFE_RPCS or be deduped
    server-side (the ray:// client pairs every submit/put with a
    session req_id the proxy caches).
    """

    def __init__(self, addr, timeout: float = 30.0, on_push=None,
                 on_reconnect=None):
        self.addr = tuple(addr)
        self._timeout = timeout
        self._on_push = on_push
        self._on_reconnect = on_reconnect
        self._lock = threading.Lock()
        self._client = RpcClient(self.addr, timeout=timeout,
                                 on_push=on_push)
        self._shutdown = False
        self._policy = None   # default-timeout RetryPolicy, built lazily

    def _reconnect(self):
        # Herd damping (cluster soak, PR 12): when the endpoint
        # RESTARTS, every client in the cluster observes ConnectionLost
        # in the same instant — 100 nodes dialing + replaying
        # registration simultaneously is the thundering herd the
        # registration-admission gate then has to absorb. A full-jitter
        # pause decorrelates the arrivals. The sleep happens OUTSIDE
        # the heal lock (holding it would serialize, not decorrelate,
        # and park every caller behind one sleeper) and is skipped when
        # another thread already healed the channel.
        if self._client.closed and not self._shutdown:
            from ray_tpu._private.config import get_config
            from ray_tpu._private.retry import full_jitter

            pause = full_jitter(float(get_config("gcs_reconnect_jitter_s")))
            if pause > 0 and self._client.closed:
                time.sleep(pause)
        with self._lock:
            if self._shutdown:
                raise ConnectionLost("client shut down")
            if not self._client.closed:
                return self._client   # another thread already healed it
            fresh = RpcClient(self.addr, timeout=self._timeout,
                              on_push=self._on_push)
            if self._on_reconnect is not None:
                # replay registration through the RAW client (the wrapper
                # lock is held; recursing through call() would deadlock)
                try:
                    self._on_reconnect(fresh)
                except Exception:
                    fresh.close()
                    raise
            self._client = fresh
            return fresh

    def call(self, method: str, timeout: float | None = None, **kwargs):
        from ray_tpu._private.retry import RetryPolicy, is_retry_safe
        from ray_tpu._private.task_spec import validate_control_rpc

        validate_control_rpc(method, kwargs)
        if not is_retry_safe(method):
            # fail fast: a replay of e.g. actor_failed after an
            # applied-then-died server would double-apply
            return self._client.call(method, timeout=timeout, **kwargs)
        if timeout is None:
            # default-timeout calls ride the full policy (config attempt
            # timeout, config deadline — timeouts retried); cached, the
            # config is static for the client's lifetime
            policy = self._policy
            if policy is None:
                from ray_tpu._private.config import get_config

                policy = RetryPolicy.from_config(
                    attempt_timeout_s=float(
                        get_config("gcs_rpc_timeout_s")))
                self._policy = policy
        else:
            # an EXPLICIT timeout is the caller's liveness bound: honor
            # it as the overall deadline (one full-length attempt; only
            # ConnectionLost retries fit inside the remainder) instead
            # of multiplying it per attempt
            policy = RetryPolicy.from_config(attempt_timeout_s=timeout,
                                             deadline_s=timeout)

        def attempt(attempt_timeout):
            try:
                return self._client.call(method, timeout=attempt_timeout,
                                         **kwargs)
            except ConnectionLost:
                if self._shutdown:
                    raise
                # Heal the channel, then charge this as one failed
                # attempt (the policy sleeps + re-enters attempt()).
                # If the reconnect ITSELF fails the server is down, not
                # flaky — fail after this one reconnect attempt instead
                # of burning the retry budget against a dead endpoint
                # (teardown paths hit this on every post-shutdown call;
                # pre-policy semantics). The sentinel wrapper keeps the
                # policy's retry_on from catching the reconnect failure
                # (a ConnectionLost subclass would still match).
                try:
                    self._reconnect()
                except ConnectionLost as dead:
                    raise _ReconnectFailed(dead) from dead
                raise

        try:
            return policy.run(attempt, method=method,
                              retry_on=(ConnectionLost, TimeoutError))
        except _ReconnectFailed as rf:
            raise rf.cause

    def call_once(self, method: str, timeout: float | None = None,
                  **kwargs):
        """Single attempt, NO retry — for ops that are not idempotent
        (e.g. actor_failed consumes restart budget: a retry after the
        server applied-then-died would double-charge it)."""
        from ray_tpu._private.task_spec import validate_control_rpc

        validate_control_rpc(method, kwargs)
        return self._client.call(method, timeout=timeout, **kwargs)

    def call_async(self, method: str, **kwargs):
        """Async submit; the retry covers only a dead connection at
        SUBMIT time — a future that later fails with ConnectionLost is
        the caller's to handle (retrying it here could double-apply)."""
        from ray_tpu._private.task_spec import validate_control_rpc

        validate_control_rpc(method, kwargs)
        try:
            return self._client.call_async(method, **kwargs)
        except ConnectionLost:
            return self._reconnect().call_async(method, **kwargs)

    def push(self, method: str, **kwargs):
        from ray_tpu._private.task_spec import validate_control_rpc

        validate_control_rpc(method, kwargs)
        try:
            self._client.push(method, **kwargs)
        except ConnectionLost:
            self._reconnect().push(method, **kwargs)

    @property
    def closed(self) -> bool:
        return self._shutdown

    def close(self):
        self._shutdown = True
        self._client.close()
