"""Runtime environments: env_vars + working_dir packaging.

Reference: python/ray/_private/runtime_env/ (working_dir.py, packaging.py —
directories zipped into the GCS KV, unpacked next to the worker) scoped to
the two capabilities jobs need most: environment variables and a packaged
working directory. The package rides the GCS KV (ns="packages") keyed by
content hash, so resubmitting the same tree uploads nothing.
"""
from __future__ import annotations

import hashlib
import io
import os
import zipfile

_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
MAX_PACKAGE_BYTES = 100 * 1024 * 1024


def package_working_dir(path: str) -> tuple[str, bytes]:
    """Zip a directory tree → (content-hash key, zip bytes)."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"working_dir {path!r} is not a directory")
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
            for name in sorted(files):
                full = os.path.join(root, name)
                z.write(full, os.path.relpath(full, path))
    blob = buf.getvalue()
    if len(blob) > MAX_PACKAGE_BYTES:
        raise ValueError(
            f"working_dir package is {len(blob)} bytes "
            f"(limit {MAX_PACKAGE_BYTES}); exclude large data files")
    key = "pkg-" + hashlib.sha256(blob).hexdigest()[:24]
    return key, blob


def upload_working_dir(gcs_call, path: str) -> str:
    """Idempotent upload; returns the package key (URI analog)."""
    key, blob = package_working_dir(path)
    if gcs_call("kv_get", ns="packages", key=key.encode()) is None:
        gcs_call("kv_put", ns="packages", key=key.encode(), value=blob)
    return key


def materialize_working_dir(gcs_call, key: str, dest_root: str) -> str:
    """Download + extract a package; returns the directory path. Cached per
    key under dest_root (the per-node URI cache analog, uri_cache.py).
    Concurrency-safe: extraction happens in a private temp dir and the
    rename loser simply uses the winner's copy (content-addressed keys
    make both copies identical)."""
    import shutil
    import tempfile

    dest = os.path.join(dest_root, key)
    if os.path.isdir(dest):
        return dest
    blob = gcs_call("kv_get", ns="packages", key=key.encode())
    if blob is None:
        raise ValueError(f"package {key!r} not found in GCS")
    tmp = tempfile.mkdtemp(dir=dest_root, prefix=f".{key}-")
    try:
        with zipfile.ZipFile(io.BytesIO(blob)) as z:
            z.extractall(tmp)
        os.rename(tmp, dest)
    except OSError:
        if not os.path.isdir(dest):   # lost a race we didn't win either
            raise
        shutil.rmtree(tmp, ignore_errors=True)
    return dest


def apply_runtime_env(runtime_env: dict | None, gcs_call,
                      dest_root: str) -> dict:
    """Resolve a runtime_env spec into concrete subprocess settings:
    {"env": merged os.environ overlay, "cwd": working dir or None}."""
    runtime_env = runtime_env or {}
    env = dict(os.environ)
    env.update({str(k): str(v)
                for k, v in (runtime_env.get("env_vars") or {}).items()})
    cwd = None
    wd = runtime_env.get("working_dir")
    if wd:
        if wd.startswith("pkg-"):
            cwd = materialize_working_dir(gcs_call, wd, dest_root)
        else:
            cwd = os.path.abspath(wd)
        env["PYTHONPATH"] = cwd + os.pathsep + env.get("PYTHONPATH", "")
    return {"env": env, "cwd": cwd}
