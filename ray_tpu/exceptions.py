"""Public exception types.

Mirrors the reference's error taxonomy (python/ray/exceptions.py in the
reference tree): user-code errors wrap the original traceback, system
errors describe which component died.
"""
from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at `get()` with the remote
    traceback attached. If the original exception pickled cleanly it is
    available as `.cause` (and raised `from` it)."""

    def __init__(self, cause_cls_name: str, traceback_str: str,
                 cause: BaseException | None = None, task_desc: str = ""):
        self.cause_cls_name = cause_cls_name
        self.traceback_str = traceback_str
        self.cause = cause
        self.task_desc = task_desc
        where = f" in {task_desc}" if task_desc else ""
        super().__init__(
            f"{cause_cls_name} raised{where}:\n{traceback_str}")
        if cause is not None:
            self.__cause__ = cause

    def __reduce__(self):
        try:
            import pickle

            pickle.dumps(self.cause)
            cause = self.cause
        except Exception:
            cause = None
        return (type(self), (self.cause_cls_name, self.traceback_str,
                             cause, self.task_desc))


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died unexpectedly (analog of the
    reference's WORKER_DIED error type, common.proto ErrorType)."""


class ActorDiedError(RayTpuError):
    def __init__(self, actor_id_hex: str = "", reason: str = ""):
        self.actor_id_hex = actor_id_hex
        self.reason = reason
        super().__init__(f"Actor {actor_id_hex} died: {reason or 'unknown cause'}")


class ActorUnavailableError(RayTpuError):
    """Actor is restarting; the call may be retried."""


class ObjectLostError(RayTpuError):
    """All copies of an object were lost and reconstruction failed/disabled
    (reference: object_recovery_manager.h)."""

    def __init__(self, object_id_hex: str):
        self.object_id_hex = object_id_hex
        super().__init__(f"Object {object_id_hex} lost and could not be reconstructed")


class ObjectStoreFullError(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    """Raised when the memory monitor kills a task to protect the node
    (reference: memory_monitor.h:88, worker_killing_policy.h:30)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class TaskCancelledError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupUnschedulableError(RayTpuError):
    pass


class CrossLanguageError(RayTpuError):
    pass


class CollectiveSeqMismatchError(RayTpuError):
    """A collective recv found a message for the same (group, phase,
    step, peer) channel carrying a DIFFERENT op sequence number than
    expected: the group's op ordering has desynchronized (e.g. a rank
    restarted and reset its counters, or ranks issued collectives in
    different orders). Raised instead of the old behavior — hanging
    until the op timeout or silently pairing the wrong payloads."""


class CollectiveGroupError(RayTpuError):
    """The collective group was poisoned: a member rank died (or the
    group was torn down) while ops were pending. Raised by pending and
    future collective calls on every surviving rank — naming the dead
    rank(s) — well under the collective op timeout, instead of letting
    each rank hang until its own watchdog fires. The group is unusable;
    recovery is a gang restart (destroy + re-create the group, which
    mints a new incarnation epoch so stale traffic is fenced off)."""

    def __init__(self, group: str, dead_ranks=(), reason: str = ""):
        self.group = group
        self.dead_ranks = tuple(sorted(set(int(r) for r in dead_ranks)))
        self.reason = reason
        ranks = (f" (dead ranks: {list(self.dead_ranks)})"
                 if self.dead_ranks else "")
        super().__init__(
            f"collective group {group!r} poisoned{ranks}: "
            f"{reason or 'member death'}")

    def __reduce__(self):
        return (type(self), (self.group, self.dead_ranks, self.reason))


class TrainWorkerGroupError(RayTpuError):
    """One or more workers of a training gang failed. ``errors`` maps
    world rank -> the exception that rank's call raised; ``dead_ranks``
    names the ranks whose worker actor died (as opposed to raising a
    user-code error). Raised by ``WorkerGroup.execute`` so one dead
    worker's failure is attributed per rank instead of poisoning the
    whole gang result with a generic timeout."""

    def __init__(self, errors: dict | None = None, dead_ranks=(),
                 message: str = ""):
        self.errors = dict(errors or {})
        self.dead_ranks = tuple(sorted(set(int(r) for r in dead_ranks)))
        summary = ", ".join(
            f"rank {r}: {type(e).__name__}: {e}" if not isinstance(e, str)
            else f"rank {r}: {e}"
            for r, e in sorted(self.errors.items()))
        super().__init__(
            message or f"training worker group failure "
                       f"(dead ranks: {list(self.dead_ranks)}) — {summary}")

    def __reduce__(self):
        # per-rank causes may not pickle; degrade them to strings
        errs = {}
        import pickle

        for r, e in self.errors.items():
            try:
                pickle.dumps(e)
                errs[r] = e
            except Exception:
                errs[r] = f"{type(e).__name__}: {e}"
        return (type(self), (errs, self.dead_ranks, str(self)))


class JobQuotaError(RayTpuError, ValueError):
    """A job-registry operation carried an invalid quota/priority shape
    (negative amounts, non-numeric values, unknown job on update). Raised
    at the GCS admission boundary so a mis-specified tenant fails at
    registration, not as a silently never-scheduling placement group."""


class TrainPreemptedError(TrainWorkerGroupError):
    """The training gang's placement group was preempted by a
    higher-priority job (multi-tenant control plane). This is graceful
    degradation, not a failure: the victim received a PREEMPTION warning
    with a grace window to cut a checkpoint, the GCS reclaimed its
    bundles, and ``fit()`` tears the gang down through the elastic-FT
    path and re-queues it — WITHOUT charging a
    ``FailureConfig.max_failures`` token — to resume from the latest
    checkpoint when capacity returns."""


class ServeConfigError(RayTpuError, ValueError):
    """A Serve DeploymentConfig / AutoscalingConfig carried an invalid
    value (num_replicas <= 0, min_replicas > max_replicas, negative
    timeouts/periods, ...). Raised at CONSTRUCTION — a bad config must
    fail where the operator wrote it, not as a deep runtime failure
    three actors later. Subclasses ValueError so generic config-
    validation handlers keep working."""


class ServeOverloadedError(RayTpuError):
    """Admission control shed this request: every replica of the
    deployment is at ``max_ongoing_requests`` and the router's bounded
    queue (``max_queued_requests`` per replica) is full. The request was
    REJECTED, not queued — callers should back off ``retry_after_s``
    and retry; the HTTP proxy maps this to 503 + a Retry-After header.
    Shedding with a typed error is the production-serve contract: an
    unbounded queue converts overload into unbounded latency for every
    caller instead of fast feedback for the marginal one.

    ``draining`` distinguishes a capacity storm from a load blip: True
    means replicas are preemption-warned / drain-scheduled and
    ``retry_after_s`` hints the grace window remaining (back off past
    the storm), not the static queue-depth heuristic."""

    def __init__(self, deployment_id: str = "", queued: int = 0,
                 retry_after_s: float = 1.0, draining: bool = False):
        self.deployment_id = deployment_id
        self.queued = queued
        self.retry_after_s = retry_after_s
        self.draining = draining
        super().__init__(
            f"deployment {deployment_id!r} is overloaded: all replicas at "
            f"max_ongoing_requests and {queued} requests already queued"
            + (" (replicas draining under preemption warning)"
               if draining else "")
            + f"; retry after {retry_after_s:.2f}s")

    def __reduce__(self):
        return (type(self), (self.deployment_id, self.queued,
                             self.retry_after_s, self.draining))


class ReplicaDrainingError(RayTpuError):
    """A Serve replica refused a request because it is draining (the
    controller told it to shut down gracefully). Raised replica-side and
    caught by the handle layer, which transparently re-dispatches the
    request to a surviving replica — a scale-down or rolling update must
    not lose accepted requests that raced the routing-table update."""

    def __init__(self, replica_id: str = ""):
        self.replica_id = replica_id
        super().__init__(f"replica {replica_id!r} is draining; "
                         f"re-dispatch to another replica")

    def __reduce__(self):
        return (type(self), (self.replica_id,))


class RaySystemError(RayTpuError):
    """An internal framework component failed (narrow subclass — catching it
    must NOT swallow user-code TaskErrors, matching reference semantics)."""


# Reference-API-compatible aliases (python/ray/exceptions.py names) so users
# migrating from the reference find the names they expect.
RayError = RayTpuError
RayTaskError = TaskError
RayActorError = ActorDiedError
