"""Public exception types.

Mirrors the reference's error taxonomy (python/ray/exceptions.py in the
reference tree): user-code errors wrap the original traceback, system
errors describe which component died.
"""
from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at `get()` with the remote
    traceback attached. If the original exception pickled cleanly it is
    available as `.cause` (and raised `from` it)."""

    def __init__(self, cause_cls_name: str, traceback_str: str,
                 cause: BaseException | None = None, task_desc: str = ""):
        self.cause_cls_name = cause_cls_name
        self.traceback_str = traceback_str
        self.cause = cause
        self.task_desc = task_desc
        where = f" in {task_desc}" if task_desc else ""
        super().__init__(
            f"{cause_cls_name} raised{where}:\n{traceback_str}")
        if cause is not None:
            self.__cause__ = cause

    def __reduce__(self):
        try:
            import pickle

            pickle.dumps(self.cause)
            cause = self.cause
        except Exception:
            cause = None
        return (type(self), (self.cause_cls_name, self.traceback_str,
                             cause, self.task_desc))


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died unexpectedly (analog of the
    reference's WORKER_DIED error type, common.proto ErrorType)."""


class ActorDiedError(RayTpuError):
    def __init__(self, actor_id_hex: str = "", reason: str = ""):
        self.actor_id_hex = actor_id_hex
        self.reason = reason
        super().__init__(f"Actor {actor_id_hex} died: {reason or 'unknown cause'}")


class ActorUnavailableError(RayTpuError):
    """Actor is restarting; the call may be retried."""


class ObjectLostError(RayTpuError):
    """All copies of an object were lost and reconstruction failed/disabled
    (reference: object_recovery_manager.h)."""

    def __init__(self, object_id_hex: str):
        self.object_id_hex = object_id_hex
        super().__init__(f"Object {object_id_hex} lost and could not be reconstructed")


class ObjectStoreFullError(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    """Raised when the memory monitor kills a task to protect the node
    (reference: memory_monitor.h:88, worker_killing_policy.h:30)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class TaskCancelledError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupUnschedulableError(RayTpuError):
    pass


class CrossLanguageError(RayTpuError):
    pass


class CollectiveSeqMismatchError(RayTpuError):
    """A collective recv found a message for the same (group, phase,
    step, peer) channel carrying a DIFFERENT op sequence number than
    expected: the group's op ordering has desynchronized (e.g. a rank
    restarted and reset its counters, or ranks issued collectives in
    different orders). Raised instead of the old behavior — hanging
    until the op timeout or silently pairing the wrong payloads."""


class RaySystemError(RayTpuError):
    """An internal framework component failed (narrow subclass — catching it
    must NOT swallow user-code TaskErrors, matching reference semantics)."""


# Reference-API-compatible aliases (python/ray/exceptions.py names) so users
# migrating from the reference find the names they expect.
RayError = RayTpuError
RayTaskError = TaskError
RayActorError = ActorDiedError
