"""Standalone node process: `python -m ray_tpu.scripts.node`.

Reference role: the `raylet` / `gcs_server` binaries plus
python/ray/_private/node.py:41 (Node process supervisor). The CLI spawns
this detached; it hosts GCS + raylet (head) or raylet-only (worker),
writes its address/PID bookkeeping under the session dir, and exits
cleanly on SIGTERM (draining the node from GCS first).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time

SESSION_ROOT = "/tmp/ray_tpu"
CLUSTER_FILE = os.path.join(SESSION_ROOT, "ray_current_cluster")


def main(argv=None):
    p = argparse.ArgumentParser(prog="ray_tpu.scripts.node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", default=None, help="existing GCS host:port")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0, help="GCS port (head)")
    p.add_argument("--num-cpus", type=int, default=None)
    p.add_argument("--num-tpus", type=int, default=None)
    p.add_argument("--resources", default=None, help="JSON dict")
    p.add_argument("--object-store-memory", type=int,
                   default=256 * 1024 * 1024)
    p.add_argument("--ready-file", default=None)
    p.add_argument("--gcs-store", default=None,
                   help="durable GCS store: sqlite:<path> | log:<path> "
                        "(head only; zero-window fault tolerance)")
    args = p.parse_args(argv)

    from ray_tpu._private import fault_injection
    from ray_tpu._private.gcs import GcsServer
    from ray_tpu._private.raylet import Raylet, detect_resources

    # role tag for role-scoped fault schedules; a head node hosts GCS +
    # raylet in one process, so the finer "gcs" tag applies only to the
    # dedicated gcs.main entrypoint
    fault_injection.set_role("gcs" if args.head else "raylet", weak=True)
    os.makedirs(SESSION_ROOT, exist_ok=True)
    extra = json.loads(args.resources) if args.resources else None

    gcs = None
    if args.head:
        gcs = GcsServer(host=args.host, port=args.port,
                        store=args.gcs_store).start()
        gcs_addr = gcs.addr
    else:
        if not args.address:
            p.error("worker nodes need --address host:port")
        host, port = args.address.rsplit(":", 1)
        gcs_addr = (host, int(port))

    raylet = Raylet(
        gcs_addr,
        resources=detect_resources(args.num_cpus, args.num_tpus,
                                   resources=extra),
        store_size=args.object_store_memory,
    )

    info = {
        "gcs_address": f"{gcs_addr[0]}:{gcs_addr[1]}",
        "node_id": raylet.node_id,
        "pid": os.getpid(),
        "head": bool(args.head),
    }
    if args.head:
        with open(CLUSTER_FILE, "w") as f:
            json.dump(info, f)
    if args.ready_file:
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(info, f)
        os.replace(tmp, args.ready_file)

    stop = threading.Event()

    def _term(_sig, _frm):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    while not stop.is_set():
        time.sleep(0.2)
    # graceful: drain this node, then tear down
    try:
        raylet.stop(kill_workers=True)
    except Exception:
        pass
    if gcs is not None:
        try:
            gcs.stop()
        except Exception:
            pass
        try:
            os.unlink(CLUSTER_FILE)
        except OSError:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
