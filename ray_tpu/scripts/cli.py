"""ray-tpu CLI: `python -m ray_tpu.scripts.cli <command>`.

Reference: python/ray/scripts/scripts.py — start :532, stop :977,
status :1872, memory :1822, `ray list ...` (state CLI), microbenchmark
:1743. argparse instead of click (zero extra deps); each command talks to
the cluster through the same GCS RPCs the runtime uses.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

from ray_tpu.scripts.node import CLUSTER_FILE, SESSION_ROOT

PID_DIR = os.path.join(SESSION_ROOT, "node_pids")


def _spawn_node(node_args: list[str]) -> dict:
    os.makedirs(PID_DIR, exist_ok=True)
    ready = os.path.join(
        SESSION_ROOT, f"ready_{os.getpid()}_{int(time.time()*1000)}.json")
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts.node",
         "--ready-file", ready] + node_args,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True,
    )
    deadline = time.time() + 60
    while time.time() < deadline:
        if os.path.exists(ready):
            with open(ready) as f:
                info = json.load(f)
            os.unlink(ready)
            with open(os.path.join(PID_DIR, str(proc.pid)), "w") as f:
                json.dump(info, f)
            return info
        if proc.poll() is not None:
            raise RuntimeError(
                f"node process exited rc={proc.returncode} during startup")
        time.sleep(0.1)
    proc.kill()
    raise TimeoutError("node did not come up within 60s")


def cmd_start(args):
    node_args = []
    if args.head:
        node_args += ["--head", "--port", str(args.port)]
    else:
        addr = args.address or _current_cluster()["gcs_address"]
        node_args += ["--address", addr]
    if args.num_cpus is not None:
        node_args += ["--num-cpus", str(args.num_cpus)]
    if args.num_tpus is not None:
        node_args += ["--num-tpus", str(args.num_tpus)]
    if args.resources:
        node_args += ["--resources", args.resources]
    node_args += ["--object-store-memory", str(args.object_store_memory)]
    if getattr(args, "gcs_store", None):
        node_args += ["--gcs-store", args.gcs_store]
    info = _spawn_node(node_args)
    print(f"started {'head' if args.head else 'worker'} node "
          f"{info['node_id']} (pid {info['pid']})")
    print(f"GCS address: {info['gcs_address']}")
    if args.head:
        print(f"connect with: ray_tpu.init(address={info['gcs_address']!r})")
    return 0


def cmd_stop(_args):
    stopped = 0
    if os.path.isdir(PID_DIR):
        for name in os.listdir(PID_DIR):
            path = os.path.join(PID_DIR, name)
            try:
                pid = int(name)
                os.kill(pid, signal.SIGTERM)
                stopped += 1
            except (ValueError, ProcessLookupError):
                pass
            finally:
                try:
                    os.unlink(path)
                except OSError:
                    pass
    # give nodes a beat to drain, then force-kill stragglers
    deadline = time.time() + 5
    while time.time() < deadline:
        alive = [p for p in _known_pids() if _pid_alive(p)]
        if not alive:
            break
        time.sleep(0.1)
    print(f"stopped {stopped} node process(es)")
    return 0


def _known_pids():
    if not os.path.isdir(PID_DIR):
        return []
    return [int(n) for n in os.listdir(PID_DIR) if n.isdigit()]


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def _current_cluster() -> dict:
    if not os.path.exists(CLUSTER_FILE):
        raise SystemExit("no running cluster (no head started on this host); "
                         "pass --address or run `start --head` first")
    with open(CLUSTER_FILE) as f:
        return json.load(f)


def cmd_status(args):
    from ray_tpu.experimental.state.api import cluster_status

    print(cluster_status(address=args.address))
    return 0


def cmd_list(args):
    from ray_tpu.experimental.state import api as state

    fn = {
        "nodes": state.list_nodes,
        "actors": state.list_actors,
        "objects": state.list_objects,
        "placement-groups": state.list_placement_groups,
        "tasks": state.list_tasks,
        "workers": state.list_workers,
    }[args.kind]
    rows = fn(address=args.address)
    print(json.dumps(rows, indent=2, default=str))
    return 0


def cmd_memory(args):
    """Object-store summary + the memory-anatomy rollup (PR 18) — the
    CLI face of `experimental.state.api.summarize_memory`: live
    bytes/objects per provenance category, leak-sweep orphans with
    creator provenance, dropped-free counters per pipeline stage, and
    per-rank train-state bytes."""
    from ray_tpu.experimental.state.api import (
        memory_summary,
        summarize_memory,
    )

    if getattr(args, "anatomy_json", False):
        print(json.dumps(summarize_memory(address=args.address),
                         indent=2, default=str))
        return 0
    print(memory_summary(address=args.address))
    anatomy = summarize_memory(address=args.address)
    lines = ["", "======== Memory anatomy ========"]
    for cat, v in anatomy["categories"].items():
        lines.append(f"  {cat:<20} {v['bytes']:>14} bytes  "
                     f"{v['objects']:>6} objects")
    if anatomy["dropped_frees"]:
        lines.append("Dropped frees (never landed):")
        for stage, n in sorted(anatomy["dropped_frees"].items()):
            lines.append(f"  {stage:<20} {n}")
    if anatomy["orphans"]:
        lines.append(f"Orphans: {len(anatomy['orphans'])} "
                     f"({anatomy['orphan_bytes']} bytes)")
        for r in anatomy["orphans"][:10]:
            lines.append(
                f"  {(r.get('oid') or '?')[:16]:<18} "
                f"{r.get('category')}  {r.get('nbytes')} bytes  "
                f"reason={r.get('reason')} group={r.get('group')} "
                f"epoch={r.get('epoch')} rank={r.get('rank')}")
    if anatomy["train_state"]:
        lines.append("Train state (kind:rank -> bytes):")
        for key, v in anatomy["train_state"].items():
            lines.append(f"  {key:<24} {v}")
    print("\n".join(lines))
    return 0


def cmd_serve(args):
    """`ray-tpu serve run/status/shutdown` (reference: serve CLI,
    python/ray/serve/scripts.py). `run module:attr` imports the bound
    Application and deploys it; --non-blocking returns after deploy
    (deployments are detached actors — they outlive this process)."""
    import importlib

    import ray_tpu

    if args.action == "run" and not args.target:
        raise SystemExit("serve run needs a target (module:attr of a "
                         "bound Application)")
    address = args.address or _current_cluster()["gcs_address"]
    ray_tpu.init(address=address, ignore_reinit_error=True)
    from ray_tpu import serve

    if args.action == "run":
        mod_name, _, attr = args.target.partition(":")
        app = getattr(importlib.import_module(mod_name), attr or "app")
        serve.run(app, route_prefix=args.route_prefix or "/")
        print(json.dumps({"status": "deployed",
                          "target": args.target,
                          "http_port": serve.http_port()}), flush=True)
        if args.non_blocking:
            return 0
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            serve.shutdown()
        return 0
    if args.action == "status":
        try:
            print(json.dumps(serve.status(), default=str, indent=2))
        except ValueError:
            raise SystemExit("Serve is not running on this cluster")
        return 0
    if args.action == "summary":
        # serving-plane rollup: app status + request/shed/failover
        # counters, batch-size/pad-waste stats, replica lifecycle events
        from ray_tpu.experimental.state.api import summarize_serve

        print(json.dumps(summarize_serve(address=args.address),
                         default=str, indent=2))
        return 0
    serve.shutdown()
    print('{"status": "shutdown"}')
    return 0


def cmd_stack(args):
    """`ray stack` analog: dump every worker's Python thread stacks
    (faulthandler over SIGUSR1 — no py-spy needed)."""
    from ray_tpu._private.protocol import RpcClient
    from ray_tpu.experimental.state.api import _gcs

    address = args.address or _current_cluster()["gcs_address"]
    with _gcs(address) as call:
        nodes = [n for n in call("get_nodes") if n["Alive"]]
    for n in nodes:
        try:
            c = RpcClient((n["NodeManagerAddress"], n["NodeManagerPort"]),
                          timeout=10.0)
            try:
                dumps = c.call("dump_stacks", timeout=15.0)
            finally:
                c.close()
        except Exception as e:
            print(f"=== node {n['NodeID'][:8]}: unreachable ({e})")
            continue
        for worker_id, info in sorted(dumps.items()):
            print(f"=== worker {worker_id} "
                  f"(pid={info['pid']}, node={info['node_id'][:8]}) ===")
            print(info["stack"].strip() or "(no dump captured)")
            print()
    return 0


def cmd_dashboard(args):
    import time as _time

    from ray_tpu.dashboard import DashboardServer

    address = args.address or _current_cluster()["gcs_address"]
    server = DashboardServer(address, host=args.host, port=args.port).start()
    print(f"dashboard at http://{args.host}:{server.port}")
    try:
        while True:
            _time.sleep(1)
    except KeyboardInterrupt:
        server.stop()
    return 0


def cmd_job(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(
        args.address or _current_cluster()["gcs_address"])
    if args.action == "submit":
        if not args.rest:
            raise SystemExit("job submit needs an entrypoint command")
        runtime_env = {}
        if args.working_dir:
            runtime_env["working_dir"] = args.working_dir
        if args.env:
            runtime_env["env_vars"] = dict(kv.split("=", 1)
                                           for kv in args.env)
        import shlex

        # re-quote each argv token so argument boundaries survive the
        # supervisor's shell (a bare join breaks e.g. `python -c "a; b"`)
        entrypoint = (args.rest[0] if len(args.rest) == 1
                      else " ".join(shlex.quote(t) for t in args.rest))
        sid = client.submit_job(entrypoint=entrypoint,
                                runtime_env=runtime_env or None)
        print(sid)
    elif args.action == "list":
        print(json.dumps(client.list_jobs(), indent=2))
    else:
        if not args.rest:
            raise SystemExit(f"job {args.action} needs a job id")
        sid = args.rest[0]
        if args.action == "status":
            print(client.get_job_status(sid))
        elif args.action == "logs":
            print(client.get_job_logs(sid), end="")
        elif args.action == "stop":
            client.stop_job(sid)
            print("stopped")
    return 0


def cmd_events(args):
    """Structured runtime event log (task transitions, actor/node
    lifecycle, retry-budget exhaustion, injected faults) — the CLI face
    of `experimental.state.api.list_cluster_events` (reference:
    `ray list cluster-events`)."""
    from ray_tpu.experimental.state.api import list_cluster_events

    filters = [("kind", "=", args.kind)] if args.kind else None
    rows = list_cluster_events(address=args.address, filters=filters,
                               limit=args.limit)
    print(json.dumps(rows, indent=2, default=str))
    return 0


def cmd_collectives(args):
    """Data-plane summary — the CLI face of
    `experimental.state.api.summarize_collectives`: per-(group, backend,
    op) collective latency/bytes, COLLECTIVE_STRAGGLER events, pjit
    compile/cache stats, per-device HBM gauges."""
    from ray_tpu.experimental.state.api import summarize_collectives

    print(json.dumps(summarize_collectives(address=args.address),
                     indent=2, default=str))
    return 0


def cmd_data(args):
    """Streaming-data-plane summary — the CLI face of
    `experimental.state.api.summarize_data`: per-consumer batch counts,
    data-wait totals, prefetch depth, and local/remote block counts."""
    from ray_tpu.experimental.state.api import summarize_data

    print(json.dumps(summarize_data(address=args.address),
                     indent=2, default=str))
    return 0


def cmd_control(args):
    """Control-plane scale & health summary — the CLI face of
    `experimental.state.api.summarize_control_plane`: GCS table sizes,
    death-feed fanout/coalescing counters, registration-admission
    throttling, pubsub subscriber/resync state (cluster soak, r12)."""
    from ray_tpu.experimental.state.api import summarize_control_plane

    print(json.dumps(summarize_control_plane(address=args.address),
                     indent=2, default=str))
    return 0


def cmd_topology(args):
    """ICI-topology summary — the CLI face of
    `experimental.state.api.summarize_topology`: every TPU slice the
    raylets report (hosts, worker indices, coords, chips) and which
    placement groups / pipeline stages occupy each slice (the
    SPREAD_ACROSS_SLICES scheduler's operator view)."""
    from ray_tpu.experimental.state.api import summarize_topology

    print(json.dumps(summarize_topology(address=args.address),
                     indent=2, default=str))
    return 0


def cmd_jobs(args):
    """Multi-tenant job summary — the CLI face of
    `experimental.state.api.summarize_jobs`: per-job priority/quota/
    usage/dominant-share plus preemption and quota-rejection rollups
    (and the quota-violation list, which must stay empty)."""
    from ray_tpu.experimental.state.api import summarize_jobs

    print(json.dumps(summarize_jobs(address=args.address),
                     indent=2, default=str))
    return 0


def cmd_steps(args):
    """Step-anatomy summary — the CLI face of
    `experimental.state.api.summarize_steps`: per-step/per-rank
    compute/comm/data/compile breakdown, overlap fraction, the
    cross-rank critical path, and STEP_REGRESSION events."""
    from ray_tpu.experimental.state.api import summarize_steps

    print(json.dumps(summarize_steps(address=args.address,
                                     last=args.last),
                     indent=2, default=str))
    return 0


def cmd_checkpoints(args):
    """Sharded-checkpoint inventory: `ray-tpu checkpoints <root>` lists
    every generation under the root newest-first with its verify status
    (committed / torn / corrupt / quarantined), world size, shard count
    and bytes — the offline face of
    `train.sharded_checkpoint.summarize_checkpoints` (pure: verifies
    digests but never renames or deletes anything)."""
    from ray_tpu.train.sharded_checkpoint import summarize_checkpoints

    entries = summarize_checkpoints(args.root,
                                    digests=not args.no_digests)
    print(json.dumps({"root": args.root, "generations": entries},
                     indent=2, default=str))
    return 0


def cmd_blackbox(args):
    """Flight recorder: `ray-tpu blackbox dump` fans out over every
    process's black box (bounded rings of recent spans/events/steps/
    metrics) and writes one timestamped dump dir with per-process JSONL
    plus a merged chrome-timeline — the same artifact gang failures and
    collective poisoning produce automatically."""
    from ray_tpu._private import flight_recorder

    if args.action == "dump":
        path = flight_recorder.dump("manual", address=args.address,
                                    out_dir=args.out)
        if path is None:
            raise SystemExit(
                "flight recorder disabled (RAY_TPU_INTERNAL_TELEMETRY=0)")
        print(json.dumps({"status": "dumped", "path": path,
                          "timeline": os.path.join(path,
                                                   "timeline.json")}))
        return 0
    # last: where did the most recent automatic/manual dump land?
    # Scan the base dir — the in-memory last_dump_path is per-process
    # and this CLI is always a fresh process.
    print(json.dumps({"last_dump": flight_recorder.find_latest_dump(),
                      "base_dir": flight_recorder.base_dir(),
                      "window_s": flight_recorder.window_s()}))
    return 0


def cmd_lint(args):
    """raylint: the repo-wide invariant lint (ray_tpu/_private/analysis/)
    — lock discipline, knob registry, wire-format consistency, metric +
    event catalogs. Exit 0 only when every finding is inline-suppressed
    or baselined AND the baseline carries no stale entries."""
    from ray_tpu._private import analysis

    if args.knob_table:
        from ray_tpu._private.knobs import readme_knob_table

        print(readme_knob_table())
        print()
        print(readme_knob_table(internal=True))
        return 0
    passes = args.passes.split(",") if args.passes else None
    try:
        findings = analysis.run_all(passes=passes)
    except ValueError as e:
        raise SystemExit(str(e))
    new, known, stale = analysis.partition(findings, passes=passes)
    if args.json:
        print(json.dumps({
            "new": [vars(f) for f in new],
            "baselined": [vars(f) for f in known],
            "stale_baseline": stale,
        }, indent=2))
        return 1 if (new or stale) else 0
    if args.emit_baseline:
        sys.stdout.write(analysis.format_baseline(new))
        return 0
    for f in new:
        print(f)
    if stale:
        print(f"\n{len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} (fixed findings — "
              f"delete these lines from "
              f"ray_tpu/_private/analysis/baseline.txt):")
        for key in stale:
            print(f"  {key}")
    print(f"\nraylint: {len(new)} finding(s), {len(known)} baselined, "
          f"{len(stale)} stale baseline entr"
          f"{'y' if len(stale) == 1 else 'ies'}")
    return 1 if (new or stale) else 0


def cmd_microbenchmark(_args):
    from ray_tpu._private.ray_perf import main as perf_main

    perf_main()
    return 0


def cmd_summary(args):
    """Reference: `ray summary actors|tasks|objects` (state CLI)."""
    from ray_tpu.experimental.state import api as state

    fn = {"actors": state.summarize_actors,
          "tasks": state.summarize_tasks,
          "objects": state.summarize_objects}[args.resource]
    print(json.dumps(fn(address=args.address), indent=2, default=str))
    return 0


def cmd_up(args):
    """Reference: `ray up cluster.yaml` (scripts/scripts.py:1164)."""
    from ray_tpu.autoscaler.launcher import up

    state = up(args.config, no_monitor=args.no_monitor)
    print(f"cluster {state['cluster_name']!r} is up")
    print(f"GCS address: {state['gcs_address']}")
    print(f"connect with: ray_tpu.init(address={state['gcs_address']!r})")
    print(f"tear down with: ray-tpu down {args.config}")
    return 0


def cmd_down(args):
    """Reference: `ray down cluster.yaml` (scripts/scripts.py:1240)."""
    from ray_tpu.autoscaler.launcher import down

    if down(args.config):
        print("cluster stopped")
        return 0
    print("no running cluster for that config")
    return 1


def main(argv=None):
    p = argparse.ArgumentParser(prog="ray-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a head or worker node process")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", default=None)
    sp.add_argument("--port", type=int, default=0)
    sp.add_argument("--num-cpus", type=int, default=None)
    sp.add_argument("--num-tpus", type=int, default=None)
    sp.add_argument("--resources", default=None)
    sp.add_argument("--gcs-store", default=None,
                    help="head only: durable GCS store "
                         "(sqlite:<path> | log:<path>)")
    sp.add_argument("--object-store-memory", type=int,
                    default=256 * 1024 * 1024)
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop node processes on this host")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("status", help="cluster resource summary")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("list", help="list cluster state")
    sp.add_argument("kind", choices=["nodes", "actors", "objects",
                                     "placement-groups", "tasks", "workers"])
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("memory",
                        help="object store summary + memory anatomy")
    sp.add_argument("--address", default=None)
    sp.add_argument("--anatomy-json", action="store_true",
                    dest="anatomy_json",
                    help="print the raw summarize_memory() rollup as "
                         "JSON instead of the text summary")
    sp.set_defaults(fn=cmd_memory)

    sp = sub.add_parser("microbenchmark",
                        help="core task/actor/object throughput numbers")
    sp.set_defaults(fn=cmd_microbenchmark)

    sp = sub.add_parser("serve", help="deploy / inspect Serve apps")
    sp.add_argument("action",
                    choices=["run", "status", "summary", "shutdown"])
    sp.add_argument("target", nargs="?", default=None,
                    help="module:attr of a bound Application (run)")
    sp.add_argument("--address", default=None)
    sp.add_argument("--route-prefix", default=None)
    sp.add_argument("--non-blocking", action="store_true")
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("stack",
                        help="dump all workers' Python thread stacks")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_stack)

    sp = sub.add_parser("dashboard", help="serve the HTTP dashboard")
    sp.add_argument("--address", default=None)
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8265)
    sp.set_defaults(fn=cmd_dashboard)

    sp = sub.add_parser("job", help="submit / inspect cluster jobs")
    sp.add_argument("action", choices=["submit", "status", "logs", "stop",
                                       "list"])
    sp.add_argument("rest", nargs="*",
                    help="submit: entrypoint command; others: job id")
    sp.add_argument("--address", default=None)
    sp.add_argument("--working-dir", default=None)
    sp.add_argument("--env", action="append", default=[],
                    help="KEY=VALUE runtime env var (repeatable)")
    sp.set_defaults(fn=cmd_job)

    sp = sub.add_parser("events",
                        help="structured runtime event log "
                             "(task/actor/node transitions, faults)")
    sp.add_argument("--address", default=None)
    sp.add_argument("--kind", default=None,
                    help="filter: task_state | actor_state | node_state "
                         "| retry_budget_exhausted | fault_injected | "
                         "COLLECTIVE_STRAGGLER | COMPILE_BEGIN | "
                         "COMPILE_END | train_step | train_group")
    sp.add_argument("--limit", type=int, default=None)
    sp.set_defaults(fn=cmd_events)

    sp = sub.add_parser("collectives",
                        help="data-plane summary: collective op "
                             "latency/bytes, stragglers, pjit compile "
                             "stats, device HBM gauges")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_collectives)

    sp = sub.add_parser("data",
                        help="streaming data-plane summary "
                             "(per-consumer data wait / prefetch / "
                             "block locality)")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_data)

    sp = sub.add_parser("topology",
                        help="TPU slice topology + placement occupancy")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_topology)

    sp = sub.add_parser("jobs",
                        help="multi-tenant job quota/priority/preemption "
                             "summary")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_jobs)

    sp = sub.add_parser("control",
                        help="control-plane scale/health summary "
                             "(death-feed coalescing, registration "
                             "admission, pubsub resyncs)")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_control)

    sp = sub.add_parser("steps",
                        help="step-anatomy summary: per-step/per-rank "
                             "compute/comm/data breakdown, overlap "
                             "fraction, critical path, regressions")
    sp.add_argument("--address", default=None)
    sp.add_argument("--last", type=int, default=None,
                    help="only the most recent N steps")
    sp.set_defaults(fn=cmd_steps)

    sp = sub.add_parser("checkpoints",
                        help="list sharded-checkpoint generations "
                             "under a root with verify status "
                             "(committed/torn/corrupt/quarantined)")
    sp.add_argument("root", help="checkpoint generation root "
                                 "(the trainer's "
                                 "<storage_path>/<name>/sharded)")
    sp.add_argument("--no-digests", action="store_true",
                    help="skip per-shard sha256 verification (cheap "
                         "existence/size check only)")
    sp.set_defaults(fn=cmd_checkpoints)

    sp = sub.add_parser("blackbox",
                        help="flight recorder: dump / locate the "
                             "cluster black box")
    sp.add_argument("action", choices=["dump", "last"])
    sp.add_argument("--address", default=None)
    sp.add_argument("--out", default=None,
                    help="dump: parent directory to write the dump "
                         "under (default RAY_TPU_FLIGHT_RECORDER_DIR)")
    sp.set_defaults(fn=cmd_blackbox)

    sp = sub.add_parser("lint",
                        help="repo-wide invariant lint: lock "
                             "discipline, knob registry, wire-format "
                             "consistency, metric/event catalogs")
    sp.add_argument("--passes", default=None,
                    help="comma-separated pass names (default: all); "
                         "see ray_tpu/_private/analysis/")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--emit-baseline", action="store_true",
                    help="print baseline-format lines for the current "
                         "non-baselined findings (justifications left "
                         "TODO)")
    sp.add_argument("--knob-table", action="store_true",
                    help="print the generated README knob tables and "
                         "exit")
    sp.set_defaults(fn=cmd_lint)

    sp = sub.add_parser("summary",
                        help="aggregated cluster state rollups")
    sp.add_argument("resource", choices=["actors", "tasks", "objects"])
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_summary)

    sp = sub.add_parser("up", help="launch a cluster from a YAML spec")
    sp.add_argument("config", help="cluster YAML path")
    sp.add_argument("--no-monitor", action="store_true",
                    help="skip the autoscaler monitor process")
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("down", help="tear down a launched cluster")
    sp.add_argument("config", help="cluster YAML path (or cluster name)")
    sp.set_defaults(fn=cmd_down)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
