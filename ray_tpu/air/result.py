"""Training outcome (reference: python/ray/air/result.py)."""
from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Result:
    metrics: dict = field(default_factory=dict)
    checkpoint: object = None
    error: BaseException | None = None
    metrics_history: list = field(default_factory=list)
    path: str | None = None

    @property
    def best_checkpoints(self):
        return [(self.checkpoint, self.metrics)] if self.checkpoint else []
