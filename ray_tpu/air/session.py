"""Worker-facing training session API (reference: python/ray/air/session.py —
report :41, get_world_rank :220, get_dataset_shard :345).

Inside a training worker, `session.report(metrics, checkpoint=...)` streams
an intermediate result back to the trainer; rank/size accessors describe the
worker's place in the gang. The active session is process-global state set
by the train worker actor before the user function runs.
"""
from __future__ import annotations

import queue
import threading


class _Session:
    def __init__(self, world_rank: int, world_size: int, local_rank: int = 0,
                 dataset_shards: dict | None = None, trial_info=None):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.dataset_shards = dataset_shards or {}
        self.trial_info = trial_info
        self.results: queue.Queue = queue.Queue()
        self.finished = threading.Event()
        self.error: BaseException | None = None
        self.iteration = 0
        # set by TrainWorker.notify_preemption when the gang's placement
        # group receives a PREEMPTION warning: {"grace_s", "warned_at"}
        self.preempt_notice: dict | None = None

    def report(self, metrics: dict, checkpoint=None):
        self.iteration += 1
        # step-anatomy boundary: the interval between reports IS the
        # step, and the report's iteration number its monotonically
        # increasing step_id. No-op outside an instrumented train loop
        # (e.g. Tune function trainables reporting on the driver).
        try:
            from ray_tpu.parallel import step_anatomy

            step_anatomy.advance(self.iteration)
        except Exception:
            pass
        self.results.put({"metrics": dict(metrics),
                          "checkpoint": checkpoint,
                          "iteration": self.iteration,
                          "world_rank": self.world_rank})


_active: _Session | None = None
_lock = threading.Lock()


def _set_session(sess: _Session | None):
    global _active
    with _lock:
        _active = sess


def _get_session() -> _Session:
    if _active is None:
        raise RuntimeError(
            "session API used outside a training worker — these functions "
            "only work inside a train_loop_per_worker")
    return _active


def report(metrics: dict, *, checkpoint=None):
    _get_session().report(metrics, checkpoint)


def get_world_rank() -> int:
    return _get_session().world_rank


def get_world_size() -> int:
    return _get_session().world_size


def get_local_rank() -> int:
    return _get_session().local_rank


def get_dataset_shard(dataset_name: str = "train"):
    return _get_session().dataset_shards.get(dataset_name)


def get_checkpoint():
    """Starting checkpoint when resuming (Tune restore / PBT exploit)."""
    return getattr(_get_session(), "resume_checkpoint", None)


def get_checkpoint_dir() -> str | None:
    """The sharded-checkpoint generation root for this training run
    (``<storage_path>/<name>/sharded``, plumbed by the trainer), or
    ``None`` outside a trainer run. ``train.sharded_checkpoint``'s
    save/restore default their ``root`` to this, so a train loop can
    call them with no path plumbing of its own."""
    return getattr(_get_session(), "checkpoint_dir", None)


def preemption_warned() -> dict | None:
    """Non-None once this gang's placement group received a PREEMPTION
    warning from the multi-tenant scheduler: a higher-priority job will
    reclaim its bundles after the grace window. A cooperative train
    loop checks this between steps and cuts a checkpoint (via
    ``report(..., checkpoint=...)``) inside the window — the driver
    then tears the gang down gracefully and resumes it from that
    checkpoint when capacity returns. Returns
    ``{"grace_s": float, "warned_at": epoch_s}``."""
    return _get_session().preempt_notice


def get_trial_name() -> str:
    info = _get_session().trial_info
    return info.get("name", "") if info else ""
