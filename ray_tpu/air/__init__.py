from ray_tpu.air import session  # noqa: F401
from ray_tpu.air.checkpoint import Checkpoint  # noqa: F401
from ray_tpu.air.config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air.result import Result  # noqa: F401
from ray_tpu.air.preprocessors import (  # noqa: F401
    BatchMapper,
    Chain,
    Concatenator,
    LabelEncoder,
    MinMaxScaler,
    OneHotEncoder,
    OrdinalEncoder,
    Preprocessor,
    PreprocessorNotFittedError,
    SimpleImputer,
    StandardScaler,
)
