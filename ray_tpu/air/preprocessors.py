"""AIR preprocessors — fit-on-Dataset / transform-anywhere feature prep.

Reference: python/ray/data/preprocessor.py:23 (the Preprocessor
contract: fit computes distributed statistics over a Dataset, transform
applies them to Datasets or raw batches) and data/preprocessors/
(scaler.py, encoder.py, imputer.py, concatenator.py, batch_mapper.py,
chain.py). Fitting rides the Dataset's existing distributed aggregation
(per-block partials merged with Chan's algorithm — dataset.py
_numeric_partials) so no per-row Python runs on the hot path; transform
is a vectorized map_batches stage, which means it fuses with downstream
stages and feeds `iter_batches(device_put=True)` untouched.
"""
from __future__ import annotations

import numpy as np


class PreprocessorNotFittedError(RuntimeError):
    pass


class Preprocessor:
    """fit(dataset) -> self; transform(dataset) -> Dataset;
    transform_batch(dict-of-arrays) -> dict-of-arrays."""

    _fitted = False

    # -- contract ------------------------------------------------------------
    def _fit(self, dataset) -> None:          # stats computation
        raise NotImplementedError

    def _transform_batch(self, batch: dict) -> dict:
        raise NotImplementedError

    _requires_fit = True

    # -- public --------------------------------------------------------------
    def fit(self, dataset) -> "Preprocessor":
        self._fit(dataset)
        self._fitted = True
        return self

    def fit_transform(self, dataset):
        if self._requires_fit:
            # materialize ONCE: fitting walks every block; re-running
            # the lazy stages again inside transform would double the
            # cluster work (stateless preprocessors skip this and keep
            # the lazy stage fusion)
            dataset = dataset.materialize()
        return self.fit(dataset).transform(dataset)

    def transform(self, dataset):
        self._check_fitted()
        fn = self._transform_batch

        def apply(block):
            from ray_tpu.data import block as B

            cols = B.to_numpy_batch(block)
            # plain-array blocks (from_numpy/range) pass through as-is:
            # column-agnostic preprocessors (BatchMapper) handle them;
            # column-based ones fail with their own KeyError
            return fn(dict(cols) if isinstance(cols, dict) else cols)

        return dataset.map_batches(apply)

    def transform_batch(self, batch):
        self._check_fitted()
        return self._transform_batch(
            dict(batch) if isinstance(batch, dict) else batch)

    def _check_fitted(self):
        if self._requires_fit and not self._fitted:
            raise PreprocessorNotFittedError(
                f"{type(self).__name__} must be fit() before transform")

    def __repr__(self):
        state = "fitted" if self._fitted else "unfitted"
        return f"{type(self).__name__}({state})"


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference: scaler.py
    StandardScaler)."""

    def __init__(self, columns: list[str], ddof: int = 0):
        self.columns = list(columns)
        self.ddof = ddof
        self.stats_: dict[str, tuple] = {}

    def _fit(self, dataset):
        for col, p in _fit_numeric_columns(dataset, self.columns).items():
            count, _tot, _mn, _mx, mean, m2 = p
            denom = max(1, count - self.ddof)
            std = float(np.sqrt(m2 / denom))
            self.stats_[col] = (mean, std if std > 0 else 1.0)

    def _transform_batch(self, batch):
        for col in self.columns:
            mean, std = self.stats_[col]
            batch[col] = (np.asarray(batch[col], np.float64) - mean) / std
        return batch


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column (reference: scaler.py)."""

    def __init__(self, columns: list[str]):
        self.columns = list(columns)
        self.stats_: dict[str, tuple] = {}

    def _fit(self, dataset):
        for col, p in _fit_numeric_columns(dataset, self.columns).items():
            _c, _t, mn, mx, _mean, _m2 = p
            span = mx - mn
            self.stats_[col] = (mn, span if span > 0 else 1.0)

    def _transform_batch(self, batch):
        for col in self.columns:
            mn, span = self.stats_[col]
            batch[col] = (np.asarray(batch[col], np.float64) - mn) / span
        return batch


def _block_cols(block, cols) -> dict | None:
    """Columnar view of one block, or None for an EMPTY block (filter
    stages can empty individual blocks; fits must skip them, not crash
    indexing an empty ndarray with a column name)."""
    from ray_tpu.data import block as B

    data = B.to_numpy_batch(block)
    if not isinstance(data, dict) or not data:
        return None
    return data


def _block_numeric_partials(block, cols):
    """Per-column (n, sum, min, max, mean, M2) for one block — ONE task
    covers every column; M2 merges across blocks with Chan's algorithm
    (cancellation-safe, unlike sum-of-squares)."""
    data = _block_cols(block, cols)
    out = {}
    for c in cols:
        vals = (np.asarray(data[c], np.float64)
                if data is not None else np.empty(0))
        if vals.size == 0:
            out[c] = None
            continue
        mean = float(vals.mean())
        out[c] = (int(vals.size), float(vals.sum()), float(vals.min()),
                  float(vals.max()), mean,
                  float(np.square(vals - mean).sum()))
    return out


def _block_nan_mean_partials(block, cols):
    data = _block_cols(block, cols)
    out = {}
    for c in cols:
        vals = (np.asarray(data[c], np.float64)
                if data is not None else np.empty(0))
        mask = ~np.isnan(vals)
        out[c] = (float(vals[mask].sum()), int(mask.sum()))
    return out


def _block_distinct(block, cols):
    data = _block_cols(block, cols)
    if data is None:
        return {c: set() for c in cols}
    return {c: set(np.asarray(data[c]).tolist()) for c in cols}


def _merge_partials(a, b):
    """Chan's parallel merge of (n, sum, min, max, mean, M2)."""
    if a is None:
        return b
    if b is None:
        return a
    n = a[0] + b[0]
    delta = b[4] - a[4]
    mean = a[4] + delta * b[0] / n
    m2 = a[5] + b[5] + delta * delta * a[0] * b[0] / n
    return (n, a[1] + b[1], min(a[2], b[2]), max(a[3], b[3]), mean, m2)


def _fit_fanout(dataset, cols, block_fn, zero, merge) -> dict:
    """THE shared fit shape: one cached remote task per block covering
    ALL columns, per-column merge on the driver (a per-column fan-out
    would cost k_columns x n_blocks tasks plus k stage re-runs)."""
    import ray_tpu

    task = ray_tpu.remote(block_fn)
    refs = [task.remote(r, list(cols))
            for r in dataset._materialized_refs()]
    merged = {c: zero() for c in cols}
    for part in ray_tpu.get(refs, timeout=600):
        for c in cols:
            merged[c] = merge(merged[c], part[c])
    return merged


def _fit_numeric_columns(dataset, cols) -> dict:
    out = _fit_fanout(dataset, cols, _block_numeric_partials,
                      lambda: None, _merge_partials)
    empty = [c for c, p in out.items() if p is None]
    if empty:
        raise ValueError(f"cannot fit on columns with no rows: {empty}")
    return out


def _fit_distinct_columns(dataset, cols) -> dict:
    out = _fit_fanout(dataset, cols, _block_distinct,
                      set, lambda a, b: a | b)
    return {c: sorted(v) for c, v in out.items()}


class OrdinalEncoder(Preprocessor):
    """Category -> dense int id (reference: encoder.py OrdinalEncoder).
    Unseen categories map to -1."""

    def __init__(self, columns: list[str]):
        self.columns = list(columns)
        self.stats_: dict[str, dict] = {}
        self._vocab_arrays: dict[str, np.ndarray] = {}

    def _fit(self, dataset):
        self._vocab_arrays = {}
        for col, vals in _fit_distinct_columns(dataset,
                                               self.columns).items():
            self.stats_[col] = {v: i for i, v in enumerate(vals)}

    def _transform_batch(self, batch):
        for col in self.columns:
            if col not in self._vocab_arrays:   # setdefault would build
                self._vocab_arrays[col] = np.asarray(  # eagerly per batch
                    sorted(self.stats_[col]))
            vocab = self._vocab_arrays[col]
            values = np.asarray(batch[col])
            if len(vocab) == 0:
                batch[col] = np.full(len(values), -1, np.int64)
                continue
            # vectorized lookup: ids ARE searchsorted positions because
            # the fit sorted the categories — no per-row Python
            idx = np.searchsorted(vocab, values)
            idx_c = np.clip(idx, 0, len(vocab) - 1)
            valid = vocab[idx_c] == values
            batch[col] = np.where(valid, idx_c, -1).astype(np.int64)
        return batch


class LabelEncoder(OrdinalEncoder):
    """OrdinalEncoder for one label column (reference: encoder.py
    LabelEncoder keeps the same category->id semantics)."""

    def __init__(self, label_column: str):
        super().__init__([label_column])
        self.label_column = label_column

    def inverse_transform_batch(self, batch):
        self._check_fitted()
        inv = {i: v for v, i in self.stats_[self.label_column].items()}
        batch = dict(batch)
        batch[self.label_column] = np.asarray(
            [inv.get(int(i)) for i in
             np.asarray(batch[self.label_column]).tolist()])
        return batch


class OneHotEncoder(Preprocessor):
    """Category -> indicator columns `{col}_{value}` (reference:
    encoder.py OneHotEncoder); unseen categories one-hot to all-zeros."""

    def __init__(self, columns: list[str]):
        self.columns = list(columns)
        self.stats_: dict[str, list] = {}

    def _fit(self, dataset):
        self.stats_ = _fit_distinct_columns(dataset, self.columns)

    def _transform_batch(self, batch):
        for col in self.columns:
            values = np.asarray(batch.pop(col))
            for cat in self.stats_[col]:
                batch[f"{col}_{cat}"] = (values == cat).astype(np.int8)
        return batch


class SimpleImputer(Preprocessor):
    """Fill missing values (NaN) with mean/constant (reference:
    imputer.py)."""

    def __init__(self, columns: list[str], strategy: str = "mean",
                 fill_value=None):
        if strategy not in ("mean", "constant"):
            raise ValueError(f"unknown strategy {strategy!r}")
        if strategy == "constant" and fill_value is None:
            raise ValueError("strategy='constant' needs fill_value")
        self.columns = list(columns)
        self.strategy = strategy
        self.fill_value = fill_value
        self.stats_: dict[str, float] = {}
        if strategy == "constant":
            self._requires_fit = False   # the fill needs no statistics

    def _fit(self, dataset):
        if self.strategy == "constant":
            return
        agg = _fit_fanout(
            dataset, self.columns, _block_nan_mean_partials,
            lambda: (0.0, 0),
            lambda a, b: (a[0] + b[0], a[1] + b[1]))
        for c, (total, count) in agg.items():
            self.stats_[c] = total / count if count else 0.0

    def _transform_batch(self, batch):
        for col in self.columns:
            vals = np.asarray(batch[col], np.float64)
            fill = (self.fill_value if self.strategy == "constant"
                    else self.stats_[col])
            batch[col] = np.where(np.isnan(vals), fill, vals)
        return batch


class Concatenator(Preprocessor):
    """Merge numeric columns into one feature matrix column (reference:
    concatenator.py) — the model-input shape for to_tf/iter_batches."""

    _requires_fit = False
    _fitted = True

    def __init__(self, columns: list[str], output_column: str = "features",
                 dtype=np.float32):
        self.columns = list(columns)
        self.output_column = output_column
        self.dtype = dtype

    def _fit(self, dataset):
        pass

    def _transform_batch(self, batch):
        mat = np.stack([np.asarray(batch.pop(c), self.dtype)
                        for c in self.columns], axis=1)
        batch[self.output_column] = mat
        return batch


class BatchMapper(Preprocessor):
    """User fn over batches (reference: batch_mapper.py)."""

    _requires_fit = False
    _fitted = True

    def __init__(self, fn):
        self.fn = fn

    def _fit(self, dataset):
        pass

    def _transform_batch(self, batch):
        return self.fn(batch)


class Chain(Preprocessor):
    """Sequential composition; fit_transform semantics per stage
    (reference: chain.py — each stage fits on the PREVIOUS stage's
    output)."""

    def __init__(self, *stages: Preprocessor):
        self.stages = list(stages)
        # a chain of stateless stages is itself stateless (reference:
        # chain.py derives fit_status from its stages)
        if not any(st._requires_fit for st in self.stages):
            self._requires_fit = False
            self._fitted = True

    def fit(self, dataset):
        for stage in self.stages[:-1]:
            dataset = stage.fit_transform(dataset).materialize()
        if self.stages:
            self.stages[-1].fit(dataset)
        self._fitted = True
        return self

    def _transform_batch(self, batch):
        for stage in self.stages:
            batch = stage.transform_batch(batch)
        return batch

    def transform(self, dataset):
        self._check_fitted()
        for stage in self.stages:
            dataset = stage.transform(dataset)
        return dataset
