"""AIR-style structured configs (reference: python/ray/air/config.py —
ScalingConfig, RunConfig, FailureConfig, CheckpointConfig dataclasses)."""
from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class ScalingConfig:
    """How many training workers and what each needs.

    TPU-native: `use_tpu` + `chips_per_worker` replace the reference's
    use_gpu/num_gpus (one worker per TPU host, holding all its chips, is the
    canonical multi-controller JAX layout)."""

    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: int | None = None
    resources_per_worker: dict | None = None
    placement_strategy: str = "PACK"
    # per-bundle stage labels for SPREAD_ACROSS_SLICES gangs (the
    # multi-slice MPMD pipeline layout): workers sharing a label form
    # one stage sub-gang placed contiguous inside one slice, distinct
    # stages on distinct slices. Parallel to the bundle list (one
    # entry per worker); None for single-slice gangs.
    bundle_stages: list | None = None
    trainer_resources: dict | None = None
    # multi-tenant label: the gang's placement group (and therefore its
    # quota accounting, fair-share weight, and preemption priority) is
    # attributed to this named job (ray_tpu.util.jobs). None inherits
    # the process's current job.
    job: str | None = None

    @property
    def num_chips(self) -> int:
        return (self.chips_per_worker or (1 if self.use_tpu else 0)) \
            * self.num_workers

    def worker_resources(self) -> dict:
        res = dict(self.resources_per_worker or {"CPU": 1})
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = self.chips_per_worker or 1
        return res

    def as_placement_group_bundles(self) -> list[dict]:
        return [self.worker_resources() for _ in range(self.num_workers)]


@dataclass
class FailureConfig:
    """(reference: air/config.py FailureConfig) max_failures=-1 → unlimited
    retries of the whole training run (gang restart, not per-worker).

    With max_failures != 0, a failed attempt (dead rank, poisoned
    collective group, worker exception) tears the gang down and rebuilds
    it; `restore_from_latest_checkpoint` (default) resumes the train loop
    from the failed attempt's latest successfully persisted checkpoint —
    surfaced to workers via session.get_checkpoint() — instead of
    restarting from step 0. Set it False to restart attempts cold."""

    max_failures: int = 0
    restore_from_latest_checkpoint: bool = True


@dataclass
class CheckpointConfig:
    num_to_keep: int | None = None
    checkpoint_score_attribute: str | None = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0


@dataclass
class RunConfig:
    name: str | None = None
    # a local path is used directly; a URI with a scheme (file://...)
    # stages locally and mirrors through tune.syncer (cloud-sync analog)
    storage_path: str | None = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(
        default_factory=CheckpointConfig)
    stop: dict | None = None
    verbose: int = 1
    callbacks: list | None = None      # tune.Callback instances
    sync_config: object | None = None  # tune.syncer.SyncConfig
