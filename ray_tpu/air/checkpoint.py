"""Framework-agnostic checkpoint, interconvertible between dict / directory /
bytes / URI forms (reference: python/ray/air/checkpoint.py:61,284,432,558,654).

TPU-native notes: jax pytrees of arrays are first-class dict payloads
(device arrays are pulled to host numpy on to_dict); directory checkpoints
are orbax-layout-compatible so `orbax.checkpoint` users can point a
CheckpointManager at the same path.
"""
from __future__ import annotations

import io
import os
import pickle
import shutil
import tarfile
import tempfile
import uuid
import weakref


def _own_tmpdir(owner, path: str) -> str:
    """Tie a mkdtemp'd scratch directory's lifetime to ``owner``: the
    finalizer removes it when the owner is collected (and at interpreter
    exit). Every ``rtpu_ckpt_`` tmpdir this module creates is registered
    here — they used to leak one per from_bytes/to_directory round trip
    (pinned by the tmpdir-counting test in tests/test_zz_sharded_ckpt.py)."""
    weakref.finalize(owner, shutil.rmtree, path, ignore_errors=True)
    return path


class Checkpoint:
    def __init__(self, data: dict | None = None,
                 directory: str | None = None):
        if (data is None) == (directory is None):
            raise ValueError("exactly one of data/directory required")
        self._data = data
        self._directory = directory
        self._materialized: str | None = None   # cached to_directory(None)
        self.id = uuid.uuid4().hex[:8]

    # ---- constructors -------------------------------------------------------

    @classmethod
    def from_dict(cls, data: dict) -> "Checkpoint":
        return cls(data=_tree_to_host(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(directory=path)

    @classmethod
    def from_bytes(cls, blob: bytes) -> "Checkpoint":
        kind, payload = pickle.loads(blob)
        if kind == "dict":
            return cls(data=payload)
        tmp = tempfile.mkdtemp(prefix="rtpu_ckpt_")
        with tarfile.open(fileobj=io.BytesIO(payload), mode="r") as tar:
            tar.extractall(tmp, filter="data")
        ckpt = cls(directory=tmp)
        _own_tmpdir(ckpt, tmp)
        return ckpt

    @classmethod
    def from_jax(cls, pytree, path: str | None = None) -> "Checkpoint":
        """Write a jax pytree (train state, params, opt state) as an
        orbax-format directory checkpoint (reference parity: AIR's
        framework-specific checkpoints; TPU-native form is orbax, the jax
        ecosystem standard for sharded-array checkpoints)."""
        import orbax.checkpoint as ocp

        base = path or tempfile.mkdtemp(prefix="rtpu_ckpt_")
        target = os.path.join(base, "orbax_state")
        if os.path.exists(target):
            shutil.rmtree(target)
        ocp.PyTreeCheckpointer().save(target, pytree)
        ckpt = cls(directory=base)
        if path is None:
            _own_tmpdir(ckpt, base)
        return ckpt

    def to_jax(self):
        """Restore the pytree of an orbax-form checkpoint."""
        import orbax.checkpoint as ocp

        path = self.to_directory()
        target = os.path.join(path, "orbax_state")
        if not os.path.isdir(target):
            raise ValueError("not an orbax-form checkpoint "
                             "(no orbax_state/ subdirectory)")
        return ocp.PyTreeCheckpointer().restore(target)

    # ---- conversions --------------------------------------------------------

    def to_dict(self) -> dict:
        if self._data is not None:
            return self._data
        meta_path = os.path.join(self._directory, "_ckpt_dict.pkl")
        if os.path.exists(meta_path):
            with open(meta_path, "rb") as f:
                return pickle.loads(f.read())
        raise ValueError(
            "directory checkpoint has no dict form (no _ckpt_dict.pkl)")

    def to_directory(self, path: str | None = None) -> str:
        if path is None:
            # scratch materialization: cached (repeat calls reuse one
            # dir) and lifetime-tied to this checkpoint — one leaked
            # tmpdir per call otherwise
            if self._materialized is not None \
                    and os.path.isdir(self._materialized):
                return self._materialized
            path = _own_tmpdir(self,
                               tempfile.mkdtemp(prefix="rtpu_ckpt_"))
            self._materialized = path
        os.makedirs(path, exist_ok=True)
        if self._directory is not None:
            if os.path.abspath(self._directory) != os.path.abspath(path):
                shutil.copytree(self._directory, path, dirs_exist_ok=True)
        else:
            from ray_tpu._private.atomic_write import atomic_write

            atomic_write(os.path.join(path, "_ckpt_dict.pkl"),
                         pickle.dumps(self._data), tag="ckpt",
                         name="ckpt_dict")
        return path

    def to_bytes(self) -> bytes:
        if self._data is not None:
            return pickle.dumps(("dict", self._data))
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tar:
            tar.add(self._directory, arcname=".")
        return pickle.dumps(("dir", buf.getvalue()))

    def to_uri(self, uri: str) -> str:
        """file:// URIs only (no cloud egress in this environment; the
        reference supports s3/gcs through pyarrow.fs)."""
        if not uri.startswith("file://"):
            raise ValueError("only file:// URIs supported")
        return "file://" + self.to_directory(uri[len("file://"):])

    @classmethod
    def from_uri(cls, uri: str) -> "Checkpoint":
        if not uri.startswith("file://"):
            raise ValueError("only file:// URIs supported")
        return cls.from_directory(uri[len("file://"):])

    def __repr__(self):
        kind = "dict" if self._data is not None else f"dir:{self._directory}"
        return f"Checkpoint({kind})"


def _tree_to_host(obj):
    """Pull jax arrays to host numpy so checkpoints pickle cleanly."""
    try:
        import jax
        import numpy as np

        return jax.tree_util.tree_map(
            lambda x: np.asarray(x)
            if isinstance(x, jax.Array) else x, obj)
    except Exception:
        return obj
