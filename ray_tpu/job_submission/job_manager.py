"""JobSupervisor actor + JobSubmissionClient.

Reference: dashboard/modules/job/job_manager.py — JobSupervisor :133 (runs
the entrypoint as a subprocess, streams logs), JobManager :418 (submit /
status / stop bookkeeping). The supervisor is a detached named actor so
jobs survive the submitting client's exit; terminal status + logs are
mirrored to the GCS KV (ns="jobs") so `list_jobs` works after the
supervisor is gone.
"""
from __future__ import annotations

import json
import os
import subprocess
import threading
import time
import uuid

VALID_STATUSES = ("PENDING", "RUNNING", "SUCCEEDED", "FAILED", "STOPPED")


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


class JobSupervisor:
    """Actor body: one per job (reference: job_manager.py:133)."""

    def __init__(self, submission_id: str, entrypoint: str,
                 runtime_env: dict | None):
        from ray_tpu._private.runtime_env import apply_runtime_env
        from ray_tpu._private.worker_runtime import current_worker

        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self._status = JobStatus.PENDING
        self._logs: list[str] = []
        self._lock = threading.Lock()
        self._proc = None
        worker = current_worker()
        self._gcs_call = worker.gcs.call
        dest_root = os.path.join("/tmp/ray_tpu", "runtime_envs")
        os.makedirs(dest_root, exist_ok=True)
        settings = apply_runtime_env(runtime_env, self._gcs_call, dest_root)
        threading.Thread(target=self._run, args=(settings,), daemon=True,
                         name=f"job-{submission_id}").start()

    def _run(self, settings: dict):
        with self._lock:
            if self._status == JobStatus.STOPPED:
                return   # stop() won the race before the subprocess spawned
            self._status = JobStatus.RUNNING
        self._persist()
        try:
            self._proc = subprocess.Popen(
                self.entrypoint, shell=True,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                env=settings["env"], cwd=settings["cwd"], text=True,
                start_new_session=True,
            )
            with self._lock:
                stopped_mid_spawn = self._status == JobStatus.STOPPED
            if stopped_mid_spawn:
                # stop() raced between RUNNING and Popen — it had no
                # process to kill, so kill it here
                import signal as _signal

                try:
                    os.killpg(os.getpgid(self._proc.pid), _signal.SIGTERM)
                except OSError:
                    pass
            for line in self._proc.stdout:
                with self._lock:
                    self._logs.append(line)
                    if len(self._logs) > 10_000:
                        del self._logs[:5_000]
            rc = self._proc.wait()
            with self._lock:
                if self._status != JobStatus.STOPPED:
                    self._status = (JobStatus.SUCCEEDED if rc == 0
                                    else JobStatus.FAILED)
                    if rc != 0:
                        self._logs.append(f"[job exited rc={rc}]\n")
        except BaseException as e:  # noqa: BLE001
            with self._lock:
                self._status = JobStatus.FAILED
                self._logs.append(f"[supervisor error: {e}]\n")
        self._persist()

    def _persist(self):
        try:
            with self._lock:
                record = {"submission_id": self.submission_id,
                          "entrypoint": self.entrypoint,
                          "status": self._status,
                          "logs_tail": "".join(self._logs[-200:]),
                          "updated_at": time.time()}
            self._gcs_call("kv_put", ns="jobs",
                           key=self.submission_id.encode(),
                           value=json.dumps(record).encode())
        except Exception:
            pass

    def status(self) -> str:
        with self._lock:
            return self._status

    def logs(self) -> str:
        with self._lock:
            return "".join(self._logs)

    def stop(self) -> bool:
        import signal

        with self._lock:
            self._status = JobStatus.STOPPED
        if self._proc is not None and self._proc.poll() is None:
            try:
                os.killpg(os.getpgid(self._proc.pid), signal.SIGTERM)
            except OSError:
                pass
        self._persist()
        return True

    def ping(self):
        return True


class JobSubmissionClient:
    """SDK entry (reference: python/ray/job_submission/JobSubmissionClient;
    address-based like the REST client, but speaking actor RPC)."""

    def __init__(self, address: str | None = None):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address)
        self._ray = ray_tpu

    def submit_job(self, *, entrypoint: str, runtime_env: dict | None = None,
                   submission_id: str | None = None) -> str:
        from ray_tpu._private.runtime_env import upload_working_dir
        from ray_tpu._private.worker_runtime import current_worker

        submission_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        runtime_env = dict(runtime_env or {})
        wd = runtime_env.get("working_dir")
        if wd and not wd.startswith("pkg-"):
            runtime_env["working_dir"] = upload_working_dir(
                current_worker().gcs.call, wd)
        supervisor = self._ray.remote(JobSupervisor).options(
            name=f"_job_supervisor:{submission_id}", namespace="_jobs",
            lifetime="detached", max_concurrency=8, num_cpus=0,
        ).remote(submission_id, entrypoint, runtime_env)
        self._ray.get(supervisor.ping.remote())
        return submission_id

    def _supervisor(self, submission_id: str):
        return self._ray.get_actor(f"_job_supervisor:{submission_id}",
                                   namespace="_jobs")

    def get_job_status(self, submission_id: str) -> str:
        try:
            sup = self._supervisor(submission_id)
            return self._ray.get(sup.status.remote(), timeout=10)
        except ValueError:
            record = self._record(submission_id)
            if record is None:
                raise ValueError(f"no job {submission_id!r}") from None
            return record["status"]

    def get_job_logs(self, submission_id: str) -> str:
        try:
            sup = self._supervisor(submission_id)
            return self._ray.get(sup.logs.remote(), timeout=10)
        except ValueError:
            record = self._record(submission_id)
            if record is None:
                raise ValueError(f"no job {submission_id!r}") from None
            return record["logs_tail"]

    def stop_job(self, submission_id: str) -> bool:
        sup = self._supervisor(submission_id)
        return self._ray.get(sup.stop.remote(), timeout=30)

    def list_jobs(self) -> list[dict]:
        from ray_tpu._private.worker_runtime import current_worker

        call = current_worker().gcs.call
        out = []
        for key in call("kv_keys", ns="jobs"):
            blob = call("kv_get", ns="jobs", key=key)
            if blob:
                out.append(json.loads(blob))
        return sorted(out, key=lambda r: r.get("updated_at", 0))

    def wait_until_finish(self, submission_id: str,
                          timeout: float = 300.0) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            status = self.get_job_status(submission_id)
            if status in (JobStatus.SUCCEEDED, JobStatus.FAILED,
                          JobStatus.STOPPED):
                return status
            time.sleep(0.2)
        raise TimeoutError(
            f"job {submission_id} still {status} after {timeout}s")
