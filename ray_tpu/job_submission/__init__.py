"""Job submission — run an entrypoint command on the cluster.

Reference: dashboard/modules/job/job_manager.py:418 (JobManager spawning a
detached JobSupervisor actor per job at :133, entrypoint as a subprocess)
+ python/ray/job_submission/ (JobSubmissionClient SDK). Ours folds the
manager into the client (no dashboard REST hop): the client connects as a
driver, uploads the working_dir package, and creates the named detached
supervisor; status/log queries go straight to the supervisor actor, with
terminal states mirrored into the GCS KV so they outlive it.
"""
from ray_tpu.job_submission.job_manager import (
    JobStatus,
    JobSubmissionClient,
)

__all__ = ["JobStatus", "JobSubmissionClient"]
