"""Per-node physical stats collection (reference:
dashboard/modules/reporter/reporter_agent.py:296 — each node's agent
samples cpu/mem/disk/network/per-worker usage and publishes it for the
dashboard). Here the raylet plays the agent: it calls collect_stats()
on demand (rpc_physical_stats) and the dashboard aggregates across
nodes at /api/reporter.

Pure /proc readers — no psutil dependency (not bundled)."""
from __future__ import annotations

import os
import time


def _read_file(path: str) -> str | None:
    try:
        with open(path) as f:
            return f.read()
    except OSError:
        return None


_last_cpu: dict = {}


def cpu_percent() -> float | None:
    """System-wide CPU utilization since the previous call (first call
    returns None — no interval yet)."""
    raw = _read_file("/proc/stat")
    if not raw:
        return None
    fields = raw.splitlines()[0].split()[1:]
    vals = [int(x) for x in fields[:8]]
    idle = vals[3] + (vals[4] if len(vals) > 4 else 0)
    total = sum(vals)
    prev = _last_cpu.get("v")
    _last_cpu["v"] = (total, idle)
    if prev is None or total == prev[0]:
        return None
    dt_total = total - prev[0]
    dt_idle = idle - prev[1]
    return round(100.0 * (1.0 - dt_idle / dt_total), 1)


def memory_stats() -> dict:
    from ray_tpu._private.memory_monitor import node_memory_usage

    used, total = node_memory_usage()
    return {"used_bytes": used, "total_bytes": total,
            "percent": round(100.0 * used / total, 1) if total else 0.0}


def disk_stats(path: str = "/") -> dict:
    try:
        st = os.statvfs(path)
    except OSError:
        return {}
    total = st.f_blocks * st.f_frsize
    free = st.f_bavail * st.f_frsize
    return {"total_bytes": total, "free_bytes": free,
            "percent": round(100.0 * (total - free) / total, 1)
            if total else 0.0}


def load_avg() -> list[float]:
    try:
        return [round(x, 2) for x in os.getloadavg()]
    except OSError:
        return []


def worker_stats(pids: list[int]) -> list[dict]:
    """RSS + cpu time per worker pid (reporter_agent's workers table)."""
    from ray_tpu._private.memory_monitor import process_rss

    out = []
    tick = os.sysconf("SC_CLK_TCK")
    for pid in pids:
        raw = _read_file(f"/proc/{pid}/stat")
        if raw is None:
            continue
        # fields after the (comm) parens; utime/stime are 14/15 (1-based)
        rest = raw.rsplit(")", 1)[-1].split()
        try:
            cpu_s = (int(rest[11]) + int(rest[12])) / tick
        except (IndexError, ValueError):
            cpu_s = None
        out.append({"pid": pid, "rss_bytes": process_rss(pid),
                    "cpu_seconds": cpu_s})
    return out


def collect_stats(worker_pids: list[int] | None = None) -> dict:
    """One reporter sample (the rpc_physical_stats payload)."""
    return {
        "timestamp": time.time(),
        "hostname": os.uname().nodename,
        "cpu_percent": cpu_percent(),
        "cpus": os.cpu_count(),
        "memory": memory_stats(),
        "disk": disk_stats(),
        "load_avg": load_avg(),
        "workers": worker_stats(worker_pids or []),
    }
