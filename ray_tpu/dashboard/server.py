"""Dashboard HTTP server — JSON state + Prometheus metrics endpoints.

Routes (reference modules in parens — dashboard/modules/*):
    /                       index: route listing (frontend stand-in)
    /api/nodes              (node)
    /api/actors             (actor)
    /api/objects            (state)
    /api/tasks              (state: lease-level running view)
    /api/workers            (reporter)
    /api/placement_groups   (state)
    /api/jobs               (job)
    /api/tenancy            multi-tenant summary: per-job priority/
                            quota/usage/share, preemption + quota
                            rejection rollups, and the job -> Serve
                            app cross-link for jobs backing Serve
                            tenants
    /api/topology           TPU slice topology: per-slice hosts/coords
                            and which placement groups / pipeline
                            stages occupy each slice
    /api/events             structured runtime event log (cluster events)
    /api/collectives        data-plane summary: collective ops,
                            stragglers, compile stats, device gauges
    /api/data               streaming-data-plane summary: per-consumer
                            data wait, prefetch depth, block locality
    /api/steps              step-anatomy summary: per-step/per-rank
                            breakdown, overlap fraction, critical path
    /api/serve              serving-plane summary: app/replica status,
                            request/shed/failover counters, batch stats
    /api/reporter           per-node physical stats (reporter_agent)
    /api/grafana_dashboard  importable Grafana JSON (dashboard factory)
    /api/cluster_status     (`ray status`)
    /api/memory             (`ray memory`)
    /api/timeline           chrome://tracing JSON (timeline)
    /metrics                Prometheus text (reporter_agent.py:296)
    /-/healthz              liveness
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class DashboardServer:
    def __init__(self, address: str | None, host: str = "127.0.0.1",
                 port: int = 8265):
        self.address = address
        dash = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                dash._handle(self)

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="dashboard")

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()

    # ----------------------------------------------------------------- http
    def _handle(self, h: BaseHTTPRequestHandler):
        from ray_tpu.experimental.state import api as state

        path = h.path.split("?")[0]
        try:
            if path == "/-/healthz":
                return self._send(h, 200, b"ok", "text/plain")
            if path == "/metrics":
                text = state.metrics_summary(address=self.address,
                                             prometheus=True)
                return self._send(h, 200, text.encode(), "text/plain")
            if path in ("/", "/index.html"):
                # the browsable UI (reference: dashboard/client React SPA
                # — here one dependency-free page over the JSON routes)
                from ray_tpu.dashboard.web_ui import INDEX_HTML

                return self._send(h, 200, INDEX_HTML.encode(),
                                  "text/html")
            if path == "/api/cluster_status":
                payload = {"summary":
                           state.cluster_status(address=self.address)}
            elif path == "/api/memory":
                payload = {"summary":
                           state.memory_summary(address=self.address),
                           "anatomy":
                           state.summarize_memory(address=self.address)}
            elif path == "/api/nodes":
                payload = state.list_nodes(address=self.address)
            elif path == "/api/actors":
                payload = state.list_actors(address=self.address)
            elif path == "/api/objects":
                payload = state.list_objects(address=self.address)
            elif path == "/api/tasks":
                payload = state.list_tasks(address=self.address)
            elif path == "/api/workers":
                payload = state.list_workers(address=self.address)
            elif path == "/api/placement_groups":
                payload = state.list_placement_groups(address=self.address)
            elif path == "/api/events":
                payload = state.list_cluster_events(address=self.address)
            elif path == "/api/collectives":
                payload = state.summarize_collectives(address=self.address)
            elif path == "/api/data":
                payload = state.summarize_data(address=self.address)
            elif path == "/api/steps":
                payload = state.summarize_steps(address=self.address)
            elif path == "/api/reporter":
                payload = self._reporter()
            elif path == "/api/grafana_dashboard":
                from ray_tpu.dashboard.grafana import (
                    generate_default_dashboard,
                )

                payload = generate_default_dashboard()
            elif path == "/api/jobs":
                payload = self._jobs()
            elif path == "/api/tenancy":
                payload = state.summarize_jobs(address=self.address)
            elif path == "/api/topology":
                payload = state.summarize_topology(address=self.address)
            elif path == "/api/serve":
                payload = self._serve_status()
            elif path == "/api/timeline":
                payload = self._timeline()
            else:
                return self._send(h, 404, b'{"error": "no route"}',
                                  "application/json")
            raw = json.dumps(payload, default=str).encode()
            return self._send(h, 200, raw, "application/json")
        except Exception as e:
            self._send(h, 500, json.dumps({"error": str(e)}).encode(),
                       "application/json")

    def _reporter(self):
        """One physical-stats row per alive node (head + per-node agent
        view; the raylet is the agent — reporter_agent.py:296). Nodes
        are polled CONCURRENTLY: response latency is the slowest node
        (≤5 s), not the sum — a few flapping nodes must not stall the
        dashboard for their combined timeouts."""
        from concurrent.futures import ThreadPoolExecutor

        from ray_tpu._private.protocol import RpcClient
        from ray_tpu.experimental.state.api import _gcs

        with _gcs(self.address) as call:
            nodes = [n for n in call("get_nodes") if n["Alive"]]

        def _poll(n):
            try:
                c = RpcClient((n["NodeManagerAddress"],
                               n["NodeManagerPort"]), timeout=5.0,
                              retry=1)
                try:
                    return c.call("physical_stats", timeout=5.0)
                finally:
                    c.close()
            except Exception:
                return None

        if not nodes:
            return []
        with ThreadPoolExecutor(max_workers=min(16, len(nodes))) as pool:
            rows = list(pool.map(_poll, nodes))
        return [r for r in rows if r is not None]

    def _jobs(self):
        from ray_tpu.experimental.state.api import _gcs

        with _gcs(self.address) as call:
            out = []
            for key in call("kv_keys", ns="jobs"):
                blob = call("kv_get", ns="jobs", key=key)
                if blob:
                    out.append(json.loads(blob))
            return out

    def _serve_status(self):
        """Serve application/deployment status plus the serving-plane
        metrics rollup (reference: dashboard/modules/serve). App status
        queries the controller actor (needs a driver connection); the
        request/batching/event rollup folds the catalog metrics and works
        from any connected process (summarize_serve)."""
        from ray_tpu.experimental.state.api import summarize_serve

        # no is_initialized guard: the metrics/event rollup works from
        # any connected process; summarize_serve itself degrades
        # applications to {} when there is no driver connection
        return summarize_serve(address=self.address)

    def _timeline(self):
        from ray_tpu._private import profiling
        from ray_tpu.experimental.state.api import _each_raylet, _gcs

        with _gcs(self.address) as call:
            events = _each_raylet(call, "profile_events")
        return profiling.to_chrome_trace(events)

    @staticmethod
    def _send(h, status, raw: bytes, ctype: str):
        h.send_response(status)
        h.send_header("Content-Type", ctype)
        h.send_header("Content-Length", str(len(raw)))
        h.end_headers()
        h.wfile.write(raw)
