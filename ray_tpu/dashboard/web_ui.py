"""Browsable dashboard UI — one static page over the JSON routes.

Reference: dashboard/client/ (the React SPA). TPU-first minimalism: a
single dependency-free HTML file rendered by the existing state API
routes — tabs for overview/nodes/actors/tasks/workers/placement
groups/objects/jobs/tenancy/serve, auto-refresh, zero build tooling.
Operators get a browsable view; machines keep the JSON routes.
"""

INDEX_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray-tpu dashboard</title>
<style>
  :root { --bg:#0f1419; --panel:#171d24; --border:#2b3540; --fg:#d8e1e8;
          --dim:#8a99a6; --accent:#4fb3ff; --ok:#4fd68a; --bad:#ff6b6b; }
  * { box-sizing: border-box; }
  body { margin:0; background:var(--bg); color:var(--fg);
         font:14px/1.45 system-ui, sans-serif; }
  header { display:flex; align-items:baseline; gap:16px;
           padding:14px 20px; border-bottom:1px solid var(--border); }
  header h1 { font-size:17px; margin:0; }
  header .sub { color:var(--dim); font-size:12px; }
  nav { display:flex; gap:4px; padding:8px 16px;
        border-bottom:1px solid var(--border); flex-wrap:wrap; }
  nav button { background:none; border:1px solid transparent;
               color:var(--dim); padding:6px 12px; border-radius:6px;
               cursor:pointer; font:inherit; }
  nav button.active { color:var(--fg); border-color:var(--border);
                      background:var(--panel); }
  main { padding:16px 20px; }
  pre.summary { background:var(--panel); border:1px solid var(--border);
                border-radius:8px; padding:14px; overflow-x:auto; }
  table { border-collapse:collapse; width:100%; background:var(--panel);
          border:1px solid var(--border); border-radius:8px;
          overflow:hidden; }
  th, td { text-align:left; padding:7px 12px;
           border-bottom:1px solid var(--border); font-size:13px;
           max-width:420px; overflow:hidden; text-overflow:ellipsis;
           white-space:nowrap; }
  th { color:var(--dim); font-weight:600; background:#131920;
       position:sticky; top:0; }
  tr:last-child td { border-bottom:none; }
  .ok { color:var(--ok); } .bad { color:var(--bad); }
  .meta { color:var(--dim); font-size:12px; margin:10px 2px; }
  .err { color:var(--bad); padding:12px; }
</style>
</head>
<body>
<header>
  <h1>ray-tpu</h1>
  <span class="sub" id="refreshed"></span>
  <span class="sub" style="margin-left:auto">
    <a href="/metrics" style="color:var(--accent)">prometheus</a> &middot;
    <a href="/api/timeline" style="color:var(--accent)">timeline</a> &middot;
    <a href="/api/grafana_dashboard" style="color:var(--accent)">grafana</a>
  </span>
</header>
<nav id="tabs"></nav>
<main id="content"></main>
<script>
const TABS = [
  {id:"overview", label:"Overview"},
  {id:"nodes", label:"Nodes", api:"/api/nodes"},
  {id:"actors", label:"Actors", api:"/api/actors"},
  {id:"tasks", label:"Tasks", api:"/api/tasks"},
  {id:"workers", label:"Workers", api:"/api/workers"},
  {id:"pgs", label:"Placement groups", api:"/api/placement_groups"},
  {id:"topology", label:"Topology", api:"/api/topology"},
  {id:"objects", label:"Objects", api:"/api/objects"},
  {id:"memory", label:"Memory", api:"/api/memory"},
  {id:"jobs", label:"Jobs", api:"/api/jobs"},
  {id:"tenancy", label:"Tenancy", api:"/api/tenancy"},
  {id:"events", label:"Events", api:"/api/events"},
  {id:"steps", label:"Steps", api:"/api/steps"},
  {id:"serve", label:"Serve", api:"/api/serve"},
];
let current = location.hash.slice(1) || "overview";
if (!TABS.some(t => t.id === current)) current = "overview";
let renderGen = 0;   // staleness guard: only the newest render may paint

function fmt(v) {
  if (v === null || v === undefined) return "";
  if (typeof v === "boolean") return v ? "yes" : "no";
  if (typeof v === "object") return JSON.stringify(v);
  return String(v);
}
function esc(s) {
  return String(s).replace(/&/g, "&amp;").replace(/</g, "&lt;")
                  .replace(/>/g, "&gt;").replace(/"/g, "&quot;");
}
function cellClass(k, v) {
  const s = String(v);
  if (/^(ALIVE|CREATED|RUNNING|SUCCEEDED|yes|true)$/i.test(s)) return "ok";
  if (/^(DEAD|FAILED|REMOVED|no|false)$/i.test(s)) return "bad";
  return "";
}
function renderTable(rows) {
  if (!Array.isArray(rows)) rows = rows ? [rows] : [];
  if (!rows.length) return "<div class='meta'>nothing here</div>";
  const cols = [...new Set(rows.flatMap(r => Object.keys(r)))];
  let h = "<table><tr>" + cols.map(c => `<th>${esc(c)}</th>`).join("") + "</tr>";
  for (const r of rows) {
    h += "<tr>" + cols.map(c =>
      `<td class="${cellClass(c, r[c])}" title="${esc(fmt(r[c]))}">${esc(fmt(r[c]))}</td>`
    ).join("") + "</tr>";
  }
  return h + "</table><div class='meta'>" + rows.length + " row(s)</div>";
}
async function jget(url) {
  const r = await fetch(url);
  if (!r.ok) throw new Error(url + " -> " + r.status);
  return r.json();
}
async function render() {
  const el = document.getElementById("content");
  const gen = ++renderGen;
  try {
    let html;
    if (current === "overview") {
      const [status, mem, reporter] = await Promise.all([
        jget("/api/cluster_status"), jget("/api/memory"),
        jget("/api/reporter").catch(() => []),
      ]);
      html =
        "<pre class='summary'>" + esc(status.summary) + "</pre>" +
        "<pre class='summary'>" + esc(mem.summary) + "</pre>" +
        (Array.isArray(reporter) && reporter.length
          ? "<h3>Per-node stats</h3>" + renderTable(reporter) : "");
    } else if (current === "memory") {
      const m = await jget("/api/memory");
      const a = m.anatomy || {};
      const cats = Object.entries(a.categories || {}).map(
        ([category, v]) => ({category, bytes: v.bytes,
                             objects: v.objects}));
      const drops = Object.entries(a.dropped_frees || {}).map(
        ([stage, count]) => ({stage, count}));
      const ts = Object.entries(a.train_state || {}).map(([k, v]) => {
        const [kind, rank] = k.split(":");
        return {kind, rank, bytes: v};
      });
      html =
        "<pre class='summary'>" + esc(m.summary) + "</pre>" +
        "<h3>Live bytes by provenance category</h3>" + renderTable(cats) +
        (a.orphans && a.orphans.length
          ? "<h3 class='bad'>Orphans (" + esc(fmt(a.orphan_bytes)) +
            " bytes)</h3>" + renderTable(a.orphans) : "") +
        (drops.length
          ? "<h3>Dropped frees</h3>" + renderTable(drops) : "") +
        (ts.length
          ? "<h3>Train state per rank</h3>" + renderTable(ts) : "") +
        "<h3>Top owners</h3>" + renderTable(a.top_owners || []);
    } else if (current === "tenancy") {
      const t = await jget("/api/tenancy");
      const apps = Object.entries(t.serve_apps || {}).map(
        ([job, names]) => ({job, serve_apps: names.join(", ")}));
      html = renderTable(t.jobs) +
        "<div class='meta'>preemptions " + esc(fmt(t.preemptions)) +
        " &middot; quota rejections " + esc(fmt(t.quota_rejections)) +
        " &middot; quota violations " +
        (t.quota_violations && t.quota_violations.length
          ? "<span class='bad'>" + esc(fmt(t.quota_violations)) + "</span>"
          : "<span class='ok'>none</span>") + "</div>" +
        (apps.length ? "<h3>Serve tenants</h3>" + renderTable(apps) : "");
    } else {
      const tab = TABS.find(t => t.id === current) || TABS[0];
      html = renderTable(await jget(tab.api));
    }
    if (gen !== renderGen) return;   // a newer render superseded us
    el.innerHTML = html;
    document.getElementById("refreshed").textContent =
      "refreshed " + new Date().toLocaleTimeString();
  } catch (e) {
    if (gen === renderGen) el.innerHTML = "<div class='err'>" + esc(e) + "</div>";
  }
}
function drawTabs() {
  document.getElementById("tabs").innerHTML = TABS.map(t =>
    `<button class="${t.id === current ? 'active' : ''}"
             onclick="go('${t.id}')">${t.label}</button>`).join("");
}
function go(id) {
  if (!TABS.some(t => t.id === id)) id = "overview";
  current = id; location.hash = id; drawTabs(); render();
}
window.addEventListener("hashchange", () => {
  const id = location.hash.slice(1) || "overview";
  if (id !== current) go(id);   // browser back/forward updates the view
});
drawTabs(); render();
setInterval(render, 5000);
</script>
</body>
</html>
"""
