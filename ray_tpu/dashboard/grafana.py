"""Grafana dashboard factory (reference:
dashboard/modules/metrics/grafana_dashboard_factory.py — generates the
default Grafana dashboard JSON over Ray's Prometheus metrics so
operators import one file instead of hand-building panels).

`generate_default_dashboard()` returns importable Grafana JSON wired to
the /metrics exposition this framework serves (util/metrics.py +
dashboard/server.py); write it with `save_default_dashboard(path)` or
fetch it from the dashboard at /api/grafana_dashboard."""
from __future__ import annotations

import json

_PANELS = [
    # (title, promql expr, unit)
    ("Node CPU %", "ray_tpu_node_cpu_percent", "percent"),
    ("Node memory used", "ray_tpu_node_mem_used_bytes", "bytes"),
    ("Object store bytes", "ray_tpu_object_store_bytes_used", "bytes"),
    ("Object store evictions", "rate(ray_tpu_object_store_evictions[5m])",
     "ops"),
    ("Tasks finished", "rate(ray_tpu_tasks_finished_total[1m])", "ops"),
    ("Task failures", "rate(ray_tpu_tasks_failed_total[5m])", "ops"),
    ("Live actors", "ray_tpu_actors_alive", "short"),
    ("Pending lease requests", "ray_tpu_lease_requests_pending", "short"),
    ("Serve QPS", "rate(ray_tpu_serve_requests_total[1m])", "reqps"),
    ("Serve p50 latency",
     "histogram_quantile(0.5, rate(ray_tpu_serve_latency_seconds_bucket"
     "[5m]))", "s"),
]


def generate_default_dashboard(datasource: str = "Prometheus") -> dict:
    panels = []
    for i, (title, expr, unit) in enumerate(_PANELS):
        panels.append({
            "id": i + 1,
            "title": title,
            "type": "timeseries",
            "datasource": datasource,
            "gridPos": {"h": 8, "w": 12,
                        "x": 12 * (i % 2), "y": 8 * (i // 2)},
            "fieldConfig": {"defaults": {"unit": unit}},
            "targets": [{"expr": expr, "refId": "A",
                         "legendFormat": "{{instance}}"}],
        })
    return {
        "title": "ray_tpu",
        "uid": "ray-tpu-default",
        "timezone": "browser",
        "refresh": "10s",
        "schemaVersion": 36,
        "time": {"from": "now-30m", "to": "now"},
        "panels": panels,
    }


def save_default_dashboard(path: str, datasource: str = "Prometheus"):
    with open(path, "w") as f:
        json.dump(generate_default_dashboard(datasource), f, indent=2)
    return path
