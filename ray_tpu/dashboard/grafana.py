"""Grafana dashboard factory (reference:
dashboard/modules/metrics/grafana_dashboard_factory.py — generates the
default Grafana dashboard JSON over Ray's Prometheus metrics so
operators import one file instead of hand-building panels).

`generate_default_dashboard()` returns importable Grafana JSON wired to
the /metrics exposition this framework serves (util/metrics.py +
dashboard/server.py); write it with `save_default_dashboard(path)` or
fetch it from the dashboard at /api/grafana_dashboard."""
from __future__ import annotations

import json

_PANELS = [
    # (title, promql expr, unit) — every expr is over a metric the
    # runtime actually emits (_private/telemetry.py CATALOG + /metrics)
    ("RPC p50 latency",
     "histogram_quantile(0.5, rate(ray_tpu_rpc_latency_seconds_bucket"
     "[5m]))", "s"),
    ("RPC p99 latency",
     "histogram_quantile(0.99, rate(ray_tpu_rpc_latency_seconds_bucket"
     "[5m]))", "s"),
    ("RPC errors", "rate(ray_tpu_rpc_errors_total[5m])", "ops"),
    ("Control-plane retries", "rate(ray_tpu_retry_attempts_total[5m])",
     "ops"),
    ("Retry-budget exhaustion",
     "rate(ray_tpu_retry_budget_exhausted_total[5m])", "ops"),
    ("Injected faults", "rate(ray_tpu_faults_injected_total[5m])", "ops"),
    ("Scheduler queue depth", "ray_tpu_scheduler_queue_tasks", "short"),
    ("Lease grant p50 latency",
     "histogram_quantile(0.5, "
     "rate(ray_tpu_lease_grant_latency_seconds_bucket[5m]))", "s"),
    ("Object store put throughput",
     "rate(ray_tpu_object_store_put_bytes_total[1m])", "Bps"),
    ("Object store gets (hit/miss)",
     "rate(ray_tpu_object_store_get_total[1m])", "ops"),
    ("Pubsub backlog", "ray_tpu_pubsub_backlog_messages", "short"),
    ("GCS store ops", "rate(ray_tpu_gcs_store_ops_total[1m])", "ops"),
    # --- data plane (PR 3: collective / compile / device telemetry) ---
    ("Collective p50 latency",
     "histogram_quantile(0.5, rate(ray_tpu_collective_latency_seconds"
     "_bucket[5m]))", "s"),
    ("Collective p99 latency",
     "histogram_quantile(0.99, rate(ray_tpu_collective_latency_seconds"
     "_bucket[5m]))", "s"),
    ("Collective payload throughput",
     "rate(ray_tpu_collective_bytes_total[1m])", "Bps"),
    ("Collective stragglers",
     "rate(ray_tpu_collective_stragglers_total[5m])", "ops"),
    ("pjit compile time spent",
     "rate(ray_tpu_pjit_compile_seconds_sum[5m])", "s"),
    ("pjit compile cache (hit/miss)",
     "rate(ray_tpu_pjit_cache_total[5m])", "ops"),
    ("Mesh build p50",
     "histogram_quantile(0.5, rate(ray_tpu_mesh_build_seconds_bucket"
     "[5m]))", "s"),
    ("Device HBM", "ray_tpu_device_hbm_bytes", "bytes"),
    # --- gang fault tolerance (PR 5: detection / poisoning / restart) ---
    ("Training gang restarts",
     "rate(ray_tpu_train_gang_restarts_total[5m])", "ops"),
    # --- pipeline parallelism (multi-slice MPMD train plane) ---
    ("Pipeline bubble p50 (per stage)",
     "histogram_quantile(0.5, rate(ray_tpu_pipeline_bubble_seconds"
     "_bucket[5m]))", "s"),
    ("Pipeline step p50 (per stage)",
     "histogram_quantile(0.5, rate(ray_tpu_pipeline_step_seconds"
     "_bucket[5m]))", "s"),
    ("Pipeline microbatch throughput",
     "rate(ray_tpu_pipeline_microbatches_total[1m])", "ops"),
    ("Pipeline bubble fraction",
     "rate(ray_tpu_pipeline_bubble_seconds_sum[5m]) / "
     "rate(ray_tpu_pipeline_step_seconds_sum[5m])", "percentunit"),
    # --- bucketed DDP / async collective plane (overlapped grad sync) ---
    ("Grad-sync overlap fraction (hidden comm share)",
     "1 - (rate(ray_tpu_train_bucket_wait_seconds_sum[5m]) / "
     "rate(ray_tpu_train_bucket_sync_seconds_sum[5m]))", "percentunit"),
    ("Grad-sync buckets launched",
     "sum by (group) (rate(ray_tpu_train_buckets_total[5m]))", "ops"),
    ("Grad-sync comm hidden vs exposed",
     "rate(ray_tpu_train_bucket_sync_seconds_sum[5m]) - "
     "rate(ray_tpu_train_bucket_wait_seconds_sum[5m])", "s"),
    ("Param-gather overlap fraction (ZeRO mode)",
     "1 - (rate(ray_tpu_train_param_gather_wait_seconds_sum[5m]) / "
     "rate(ray_tpu_train_param_gather_seconds_sum[5m]))", "percentunit"),
    ("Optimizer-state bytes per rank (ZeRO shard shrink)",
     "sum by (rank) (ray_tpu_train_state_bytes{kind=\"opt_state\"})",
     "bytes"),
    ("Async collective ops in flight",
     "ray_tpu_collective_async_inflight_tasks", "short"),
    ("Collective groups poisoned",
     "rate(ray_tpu_collective_groups_poisoned_total[5m])", "ops"),
    ("Stale-epoch traffic rejected",
     "rate(ray_tpu_collective_stale_epoch_total[5m])", "ops"),
    # --- step anatomy + flight recorder (PR 11: observability) ---
    ("Train step p50",
     "histogram_quantile(0.5, rate(ray_tpu_step_seconds_bucket[5m]))",
     "s"),
    ("Train step p99",
     "histogram_quantile(0.99, rate(ray_tpu_step_seconds_bucket[5m]))",
     "s"),
    ("Step-time regressions",
     "rate(ray_tpu_step_regressions_total[5m])", "ops"),
    ("Data wait p50 (per consumer)",
     "histogram_quantile(0.5, sum by (consumer, le) "
     "(rate(ray_tpu_data_wait_seconds_bucket[5m])))", "s"),
    ("Flight-recorder dumps",
     "sum by (trigger) (rate(ray_tpu_flight_recorder_dumps_total[5m]))",
     "ops"),
    ("Telemetry ring drops (trace + timeline)",
     "rate(ray_tpu_trace_dropped_total[5m]) + "
     "rate(ray_tpu_timeline_dropped_total[5m])", "ops"),
    # --- memory anatomy (PR 18: provenance ledger / leak attribution) ---
    ("Store bytes by provenance category",
     "sum by (category) (ray_tpu_store_bytes)", "bytes"),
    ("Store objects by provenance category",
     "sum by (category) (ray_tpu_store_objects)", "short"),
    ("Orphaned store bytes (leak sweep)",
     "sum by (category, reason) (ray_tpu_store_orphan_bytes)", "bytes"),
    ("Dropped frees (deletes that never landed)",
     "sum by (stage) (rate(ray_tpu_store_frees_dropped_total[5m]))",
     "ops"),
    ("Free resends recovered (GCS fan-out retry)",
     "rate(ray_tpu_store_free_resends_total[5m])", "ops"),
    ("Train-state bytes per rank",
     "sum by (kind, rank) (ray_tpu_train_state_bytes)", "bytes"),
    # --- serve plane (PR 6: inference router / batcher / autoscaler) ---
    ("Serve QPS",
     "sum by (deployment) (rate(ray_tpu_serve_requests_total[1m]))",
     "reqps"),
    ("Serve p99 latency",
     "histogram_quantile(0.99, rate(ray_tpu_serve_request_latency_seconds"
     "_bucket[5m]))", "s"),
    ("Serve shed rate (admission control)",
     "sum by (deployment) (rate(ray_tpu_serve_shed_total[5m]))", "reqps"),
    ("Serve queue depth",
     "ray_tpu_serve_queue_depth_tasks", "short"),
    ("Serve batch size p50",
     "histogram_quantile(0.5, rate(ray_tpu_serve_batch_size_tasks_bucket"
     "[5m]))", "short"),
    ("Serve batch pad waste",
     "rate(ray_tpu_serve_batch_pad_waste_tasks_sum[5m])", "short"),
    ("Serve replicas (per state)",
     "ray_tpu_serve_replicas_tasks", "short"),
    ("Serve replica restarts",
     "sum by (deployment, reason) "
     "(rate(ray_tpu_serve_replica_restarts_total[5m]))", "ops"),
    ("Serve autoscale decisions",
     "sum by (deployment, direction) "
     "(rate(ray_tpu_serve_autoscale_total[5m]))", "ops"),
    ("Serve failovers (replica death/drain re-dispatch)",
     "sum by (deployment) (rate(ray_tpu_serve_failovers_total[5m]))",
     "ops"),
    # --- serve tenancy (Serve as a first-class job-plane tenant) ---
    ("Serve app dominant share (job plane)",
     "ray_tpu_job_dominant_share_ratio", "percentunit"),
    ("Serve warned-replica capacity (preemption storms)",
     "sum by (deployment) (ray_tpu_serve_warned_replicas_tasks)",
     "short"),
    ("Serve spike-to-placed latency p99",
     "histogram_quantile(0.99, rate(ray_tpu_serve_capacity_wait_seconds"
     "_bucket[5m]))", "s"),
    # --- sharded checkpointing (crash-consistent, world-elastic) ---
    ("Checkpoint shard write p99",
     "histogram_quantile(0.99, rate(ray_tpu_checkpoint_write_seconds"
     "_bucket[5m]))", "s"),
    ("Checkpoint shard size p50",
     "histogram_quantile(0.5, rate(ray_tpu_checkpoint_bytes"
     "_bucket[5m]))", "bytes"),
    ("Checkpoint generations quarantined",
     "sum by (reason) (rate(ray_tpu_checkpoint_quarantined_total[5m]))",
     "ops"),
    ("Checkpoint restore p99",
     "histogram_quantile(0.99, rate(ray_tpu_checkpoint_restore_seconds"
     "_bucket[5m]))", "s"),
]


def generate_default_dashboard(datasource: str = "Prometheus") -> dict:
    panels = []
    for i, (title, expr, unit) in enumerate(_PANELS):
        panels.append({
            "id": i + 1,
            "title": title,
            "type": "timeseries",
            "datasource": datasource,
            "gridPos": {"h": 8, "w": 12,
                        "x": 12 * (i % 2), "y": 8 * (i // 2)},
            "fieldConfig": {"defaults": {"unit": unit}},
            "targets": [{"expr": expr, "refId": "A",
                         "legendFormat": "{{instance}}"}],
        })
    return {
        "title": "ray_tpu",
        "uid": "ray-tpu-default",
        "timezone": "browser",
        "refresh": "10s",
        "schemaVersion": 36,
        "time": {"from": "now-30m", "to": "now"},
        "panels": panels,
    }


def save_default_dashboard(path: str, datasource: str = "Prometheus"):
    with open(path, "w") as f:
        json.dump(generate_default_dashboard(datasource), f, indent=2)
    return path
