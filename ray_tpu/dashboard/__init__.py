"""ray_tpu.dashboard — HTTP observability endpoint.

Reference: dashboard/ (aiohttp head server + React frontend, 21.8k LoC;
SURVEY.md §2.2). Ours serves the same information surface as JSON over a
stdlib HTTP server — every state-API table, the cluster/memory summaries,
Prometheus metrics, jobs, and the chrome-trace timeline — without the
frontend build: point a browser (or curl/Grafana/Prometheus) at it.

    python -m ray_tpu.scripts.cli dashboard --port 8265
"""
from ray_tpu.dashboard.server import DashboardServer

__all__ = ["DashboardServer"]
