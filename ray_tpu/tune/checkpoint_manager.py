"""Per-trial checkpoint manager: persist, score, keep top-K.

Reference: python/ray/tune/execution/checkpoint_manager.py (top-K by
checkpoint_score_attribute) + syncer.py's role of getting checkpoints off
the trial actor (here: into the experiment dir on the shared filesystem).
"""
from __future__ import annotations

import os
import shutil

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import CheckpointConfig


class CheckpointManager:
    def __init__(self, trial_dir: str, config: CheckpointConfig | None):
        self.trial_dir = trial_dir
        self.config = config or CheckpointConfig()
        # [(score, iteration, path)] — kept sorted best-last
        self._kept: list[tuple[float, int, str]] = []
        self.latest_path: str | None = None

    def on_checkpoint(self, checkpoint: Checkpoint, metrics: dict,
                      iteration: int) -> str:
        """Persist a reported checkpoint; enforce num_to_keep. Returns the
        persisted directory path."""
        path = os.path.join(self.trial_dir, f"checkpoint_{iteration:06d}")
        checkpoint.to_directory(path)
        self.latest_path = path
        attr = self.config.checkpoint_score_attribute
        score = float(metrics.get(attr, iteration)) if attr else \
            float(iteration)
        if self.config.checkpoint_score_order == "min":
            score = -score
        self._kept.append((score, iteration, path))
        self._kept.sort()
        keep = self.config.num_to_keep
        if keep is not None and keep > 0:
            while len(self._kept) > keep:
                # evict the worst-scored, but never the latest (resume needs
                # it — same carve-out as the reference)
                for i, (_s, _it, p) in enumerate(self._kept):
                    if p != self.latest_path:
                        shutil.rmtree(p, ignore_errors=True)
                        del self._kept[i]
                        break
                else:
                    break
        return path

    def best_checkpoint(self) -> Checkpoint | None:
        if not self._kept:
            return None
        return Checkpoint.from_directory(self._kept[-1][2])
