"""Result logger callbacks (reference: python/ray/tune/logger/ —
json.py, csv.py, tensorboardx.py, plus the W&B / MLflow integrations
under air/integrations/).

Each trial gets a logdir under the experiment directory; loggers write
per-trial artifacts there as results stream in, so standard dashboards
(TensorBoard pointed at the experiment dir) work out of the box. On a
run without persistence (no name/storage_path), loggers no-op — there
is nowhere durable to write.
"""
from __future__ import annotations

import csv
import json
import numbers
import os

from ray_tpu.tune.callback import Callback


class LoggerCallback(Callback):
    """Per-trial file logger base (reference: logger.py LoggerCallback):
    subclasses implement log_trial_start/result/end against an open
    trial logdir."""

    def __init__(self):
        self._trial_dirs: dict[str, str] = {}

    def setup(self, experiment_dir: str | None):
        self._experiment_dir = experiment_dir

    def _logdir(self, trial) -> str | None:
        if getattr(self, "_experiment_dir", None) is None:
            return None
        d = self._trial_dirs.get(trial.trial_id)
        if d is None:
            d = os.path.join(self._experiment_dir, trial.trial_id)
            os.makedirs(d, exist_ok=True)
            self._trial_dirs[trial.trial_id] = d
        return d

    # subclass surface -----------------------------------------------------
    def log_trial_start(self, trial, logdir: str):
        pass

    def log_trial_result(self, trial, logdir: str, result: dict):
        pass

    def log_trial_end(self, trial, logdir: str):
        pass

    # Callback plumbing ----------------------------------------------------
    def on_trial_start(self, iteration: int, trial):
        d = self._logdir(trial)
        if d is not None:
            self.log_trial_start(trial, d)

    def on_trial_result(self, iteration: int, trial, result: dict):
        d = self._logdir(trial)
        if d is not None:
            self.log_trial_result(trial, d, result)

    def on_trial_complete(self, iteration: int, trial):
        d = self._logdir(trial)
        if d is not None:
            self.log_trial_end(trial, d)

    on_trial_error = on_trial_complete


class JsonLoggerCallback(LoggerCallback):
    """result.json: one JSON line per reported result (reference:
    logger/json.py), plus params.json with the trial config."""

    def log_trial_start(self, trial, logdir):
        with open(os.path.join(logdir, "params.json"), "w") as f:
            json.dump(_jsonable(trial.config), f)

    def log_trial_result(self, trial, logdir, result):
        with open(os.path.join(logdir, "result.json"), "a") as f:
            f.write(json.dumps(_jsonable(result)) + "\n")


class CSVLoggerCallback(LoggerCallback):
    """progress.csv with a stable header union (reference: logger/csv.py
    keys are fixed at first result; later unseen keys are dropped)."""

    def __init__(self):
        super().__init__()
        self._fields: dict[str, list] = {}

    def log_trial_result(self, trial, logdir, result):
        flat = {k: v for k, v in result.items()
                if isinstance(v, (numbers.Number, str, bool))}
        path = os.path.join(logdir, "progress.csv")
        fields = self._fields.get(trial.trial_id)
        if fields is None:
            fields = sorted(flat)
            self._fields[trial.trial_id] = fields
            with open(path, "w", newline="") as f:
                csv.DictWriter(f, fieldnames=fields).writeheader()
        with open(path, "a", newline="") as f:
            csv.DictWriter(f, fieldnames=fields,
                           extrasaction="ignore").writerow(flat)


class TBXLoggerCallback(LoggerCallback):
    """TensorBoard events via torch.utils.tensorboard (the torch CPU
    wheel ships a SummaryWriter; reference: logger/tensorboardx.py).
    Point `tensorboard --logdir <experiment_dir>` at the run."""

    def __init__(self):
        super().__init__()
        self._writers: dict[str, object] = {}

    def log_trial_start(self, trial, logdir):
        from torch.utils.tensorboard import SummaryWriter

        old = self._writers.pop(trial.trial_id, None)
        if old is not None:   # trial restart (PBT exploit): close cleanly
            old.close()
        self._writers[trial.trial_id] = SummaryWriter(log_dir=logdir)

    def log_trial_result(self, trial, logdir, result):
        w = self._writers.get(trial.trial_id)
        if w is None:
            self.log_trial_start(trial, logdir)
            w = self._writers[trial.trial_id]
        step = int(result.get("training_iteration", 0))
        for k, v in result.items():
            if isinstance(v, numbers.Number) and not isinstance(v, bool):
                w.add_scalar(k, float(v), global_step=step)
        w.flush()

    def log_trial_end(self, trial, logdir):
        w = self._writers.pop(trial.trial_id, None)
        if w is not None:
            w.close()


class WandbLoggerCallback(LoggerCallback):
    """Weights & Biases streaming (reference:
    air/integrations/wandb.py). Requires the `wandb` package; raises at
    construction when absent so a misconfigured experiment fails before
    burning trial compute."""

    def __init__(self, project: str, **init_kwargs):
        super().__init__()
        try:
            import wandb  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "WandbLoggerCallback requires the `wandb` package "
                "(not bundled with ray_tpu)") from e
        self._project = project
        self._init_kwargs = init_kwargs
        self._runs: dict[str, object] = {}

    def log_trial_start(self, trial, logdir):
        import wandb

        old = self._runs.pop(trial.trial_id, None)
        if old is not None:   # trial restart: finish the previous run
            old.finish()
        self._runs[trial.trial_id] = wandb.init(
            project=self._project, name=trial.trial_id,
            config=trial.config, dir=logdir, reinit=True,
            **self._init_kwargs)

    def log_trial_result(self, trial, logdir, result):
        run = self._runs.get(trial.trial_id)
        if run is not None:
            run.log({k: v for k, v in result.items()
                     if isinstance(v, numbers.Number)})

    def log_trial_end(self, trial, logdir):
        run = self._runs.pop(trial.trial_id, None)
        if run is not None:
            run.finish()


class MLflowLoggerCallback(LoggerCallback):
    """MLflow tracking (reference: air/integrations/mlflow.py). Requires
    the `mlflow` package; raises at construction when absent."""

    def __init__(self, tracking_uri: str | None = None,
                 experiment_name: str = "ray_tpu"):
        super().__init__()
        try:
            import mlflow  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "MLflowLoggerCallback requires the `mlflow` package "
                "(not bundled with ray_tpu)") from e
        self._tracking_uri = tracking_uri
        self._experiment_name = experiment_name
        self._runs: dict[str, object] = {}

    def log_trial_start(self, trial, logdir):
        import mlflow

        if self._tracking_uri:
            mlflow.set_tracking_uri(self._tracking_uri)
        mlflow.set_experiment(self._experiment_name)
        run = mlflow.start_run(run_name=trial.trial_id, nested=True)
        self._runs[trial.trial_id] = run
        mlflow.log_params({k: v for k, v in (trial.config or {}).items()
                           if isinstance(v, (numbers.Number, str, bool))})

    def log_trial_result(self, trial, logdir, result):
        import mlflow

        if trial.trial_id in self._runs:
            step = int(result.get("training_iteration", 0))
            mlflow.log_metrics(
                {k: float(v) for k, v in result.items()
                 if isinstance(v, numbers.Number)
                 and not isinstance(v, bool)}, step=step)

    def log_trial_end(self, trial, logdir):
        import mlflow

        if self._runs.pop(trial.trial_id, None) is not None:
            mlflow.end_run()


DEFAULT_LOGGERS = (JsonLoggerCallback, CSVLoggerCallback)


def _jsonable(obj):
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        if isinstance(obj, dict):
            return {str(k): _jsonable(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            return [_jsonable(v) for v in obj]
        return repr(obj)
