"""Experiment/checkpoint sync to external storage (reference:
python/ray/tune/syncer.py — checkpoints and experiment state mirror to
`storage_path` so a head-node loss doesn't lose the run).

`RunConfig(storage_path="file:///bucket/exp")` (any URI with a scheme)
makes the runner stage locally and mirror incrementally through a
Syncer after every checkpoint/state save. `file://` ships built in —
the scheme-to-implementation seam is what a real object-store syncer
(gcsfuse path, rsync, boto) plugs into via SyncConfig(syncer=...);
plain local paths never sync (the storage IS the experiment dir)."""
from __future__ import annotations

import os
import shutil
from dataclasses import dataclass


class Syncer:
    """Mirror a local directory tree to a destination URI."""

    def sync_up(self, local_dir: str, remote_uri: str):
        raise NotImplementedError

    def sync_down(self, remote_uri: str, local_dir: str):
        raise NotImplementedError


class _FileSyncer(Syncer):
    """file:// destination: incremental copy by (size, mtime) — the
    local-filesystem stand-in for an object-store syncer."""

    @staticmethod
    def _resolve(uri: str) -> str:
        assert uri.startswith("file://"), uri
        return uri[len("file://"):]

    def sync_up(self, local_dir: str, remote_uri: str):
        self._mirror(local_dir, self._resolve(remote_uri))

    def sync_down(self, remote_uri: str, local_dir: str):
        self._mirror(self._resolve(remote_uri), local_dir)

    @staticmethod
    def _mirror(src: str, dst: str):
        for root, _dirs, files in os.walk(src):
            rel = os.path.relpath(root, src)
            out_dir = os.path.join(dst, rel) if rel != "." else dst
            os.makedirs(out_dir, exist_ok=True)
            for name in files:
                s = os.path.join(root, name)
                d = os.path.join(out_dir, name)
                try:
                    st_s = os.stat(s)
                    if (os.path.exists(d)
                            and os.path.getsize(d) == st_s.st_size
                            and os.path.getmtime(d) >= st_s.st_mtime):
                        continue
                    shutil.copy2(s, d)
                except OSError:
                    continue   # file vanished mid-sync (tmp renames)


@dataclass
class SyncConfig:
    """RunConfig.sync_config (reference: tune/syncer.py SyncConfig)."""

    syncer: Syncer | None = None       # None = pick by URI scheme
    sync_period_s: float = 300.0       # periodic safety net


def get_syncer(storage_path: str | None,
               config: SyncConfig | None) -> tuple[Syncer | None, str | None]:
    """(syncer, remote_uri) for a storage path — (None, None) when the
    path is local (no sync needed)."""
    if not storage_path or "://" not in storage_path:
        return None, None
    if config is not None and config.syncer is not None:
        return config.syncer, storage_path
    if storage_path.startswith("file://"):
        return _FileSyncer(), storage_path
    raise ValueError(
        f"no syncer for {storage_path!r}: pass "
        f"RunConfig(sync_config=SyncConfig(syncer=...)) for this scheme")
