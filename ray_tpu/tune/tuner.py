"""Tuner / TrialRunner — the experiment driver (reference:
python/ray/tune/tune.py:130 tune.run, tuner.py:220 Tuner.fit,
execution/trial_runner.py:236 TrialRunner.step,
execution/ray_trial_executor.py:205 — each Trial is an actor).

Each trial runs its function trainable inside a `_TrialActor`; the runner
polls results, feeds the scheduler, and applies decisions (stop / PBT
exploit). Trials needing gang resources use their own placement groups via
the trainable (e.g. a Trainer.as_trainable()).
"""
from __future__ import annotations

import time
import uuid

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig
from ray_tpu.air.result import Result
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.search import BasicVariantGenerator


class TuneConfig:
    def __init__(self, num_samples: int = 1, max_concurrent_trials: int = 0,
                 metric: str | None = None, mode: str = "max",
                 scheduler=None, seed: int | None = None,
                 search_alg=None):
        self.num_samples = num_samples
        self.max_concurrent_trials = max_concurrent_trials
        self.metric = metric
        self.mode = mode
        self.scheduler = scheduler
        self.seed = seed
        self.search_alg = search_alg


class Trial:
    def __init__(self, config: dict, trial_id: str | None = None):
        self.trial_id = trial_id or uuid.uuid4().hex[:8]
        self.config = config
        self.status = "PENDING"    # RUNNING/TERMINATED/ERROR/STOPPED
        self.results: list[dict] = []
        self.latest_checkpoint: Checkpoint | None = None
        self.error: BaseException | None = None
        self.actor = None
        self.pg = None             # the trial's placement group
        self.iteration = 0

    @property
    def last_result(self) -> dict:
        return self.results[-1] if self.results else {}


class _TrialActor:
    """Actor body hosting one trial's function trainable."""

    def __init__(self):
        self.session = None

    def run(self, fn, config, resume_checkpoint):
        import threading

        from ray_tpu.air import session as _session

        self.session = _session._Session(0, 1)
        self.session.resume_checkpoint = resume_checkpoint
        _session._set_session(self.session)

        def _target():
            try:
                fn(config)
            except BaseException as e:  # noqa: BLE001
                self.session.error = e
            finally:
                self.session.finished.set()

        threading.Thread(target=_target, daemon=True,
                         name="trial-fn").start()
        return True

    def next_result(self, timeout: float = 300.0):
        import queue as _q

        waited = 0.0
        while waited < timeout:
            try:
                return self.session.results.get(timeout=0.1)
            except _q.Empty:
                waited += 0.1
                if self.session.finished.is_set() and \
                        self.session.results.empty():
                    err = self.session.error
                    if err is not None:
                        import pickle

                        try:
                            pickle.dumps(err)
                        except Exception:
                            err = RuntimeError(
                                f"{type(err).__name__}: {err}")
                    return {"done": True, "error": err}
        raise TimeoutError("trial produced no result")


class TrialRunner:
    def __init__(self, trainable, trials: list[Trial], tune_config: TuneConfig,
                 run_config: RunConfig, resources_per_trial: dict | None):
        self.trainable = trainable
        self.trials = trials
        self.tune_config = tune_config
        self.run_config = run_config
        self.resources = resources_per_trial or {"CPU": 1}
        self.scheduler = tune_config.scheduler or sched_mod.FIFOScheduler()
        # BOHB pairing: the scheduler feeds rung-level observations to
        # the model-based searcher (reference: hb_bohb.py + bohb_search
        # cooperate the same way)
        if hasattr(self.scheduler, "attach_searcher") and \
                tune_config.search_alg is not None:
            target = tune_config.search_alg
            # unwrap ConcurrencyLimiter-style decorators
            target = getattr(target, "searcher", target)
            if hasattr(target, "observe_rung"):
                self.scheduler.attach_searcher(target)
        self._pending_exploits: list[tuple] = []
        # experiment persistence (reference: trial_runner checkpointing +
        # tune/execution/experiment_state.py): enabled when the run is named
        # or given a storage path
        self.experiment_dir = None
        self._syncer = None
        self._sync_uri = None
        if run_config.name or run_config.storage_path:
            import os

            from ray_tpu.tune.syncer import get_syncer

            storage = run_config.storage_path
            self._syncer, remote_root = get_syncer(
                storage, run_config.sync_config)
            if self._syncer is not None:
                # remote storage: stage locally, mirror after every
                # checkpoint/state save (reference: tune/syncer.py)
                root = os.path.expanduser("~/.ray_tpu/results")
            else:
                root = storage or os.path.expanduser("~/.ray_tpu/results")
            name = run_config.name or "experiment"
            self.experiment_dir = os.path.join(root, name)
            os.makedirs(self.experiment_dir, exist_ok=True)
            if self._syncer is not None:
                self._sync_uri = remote_root.rstrip("/") + "/" + name
        self._ckpt_managers: dict = {}
        from ray_tpu.tune.callback import _CallbackList

        self.callbacks = _CallbackList(run_config.callbacks)
        self.callbacks.fire("setup", self.experiment_dir)

    def _sync_up(self, force: bool = False):
        """Mirror the experiment tree. force=True (checkpoints, end of
        run — durability moments) syncs immediately; routine state saves
        are throttled by SyncConfig.sync_period_s so a busy poll loop
        doesn't walk the whole tree per reported result."""
        if self._syncer is None:
            return
        if not force:
            period = getattr(self.run_config.sync_config, "sync_period_s",
                             300.0) if self.run_config.sync_config else 300.0
            last = getattr(self, "_last_sync", 0.0)
            if time.monotonic() - last < period:
                return
        try:
            self._syncer.sync_up(self.experiment_dir, self._sync_uri)
            self._last_sync = time.monotonic()
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "experiment sync to %s failed", self._sync_uri,
                exc_info=True)

    def _should_stop(self, metrics: dict) -> bool:
        for key, bound in (self.run_config.stop or {}).items():
            if key in metrics and metrics[key] >= bound:
                return True
        return False

    def _on_trial_checkpoint(self, trial, checkpoint, metrics):
        """Route reported checkpoints through the top-K manager when the
        experiment persists to disk; else keep in memory."""
        if self.experiment_dir is None:
            trial.latest_checkpoint = checkpoint
            return
        import os

        from ray_tpu.air.checkpoint import Checkpoint
        from ray_tpu.tune.checkpoint_manager import CheckpointManager

        cm = self._ckpt_managers.get(trial.trial_id)
        if cm is None:
            cm = CheckpointManager(
                os.path.join(self.experiment_dir, trial.trial_id),
                self.run_config.checkpoint_config)
            self._ckpt_managers[trial.trial_id] = cm
        path = cm.on_checkpoint(checkpoint, metrics, trial.iteration)
        trial.latest_checkpoint = Checkpoint.from_directory(path)
        self.callbacks.fire("on_checkpoint", trial.iteration, trial, path)
        self._sync_up(force=True)

    def save_experiment_state(self):
        if self.experiment_dir is None:
            return
        import json
        import os
        import tempfile

        state = {"trials": [{
            "trial_id": t.trial_id,
            "config": t.config,
            "status": t.status,
            "iteration": t.iteration,
            "last_result": _jsonable(t.last_result),
            "checkpoint_dir": (self._ckpt_managers[t.trial_id].latest_path
                               if t.trial_id in self._ckpt_managers
                               else None),
        } for t in self.trials]}
        fd, tmp = tempfile.mkstemp(dir=self.experiment_dir)
        with os.fdopen(fd, "w") as f:
            json.dump(state, f)
        os.replace(tmp, os.path.join(self.experiment_dir,
                                     "experiment_state.json"))
        self._sync_up()

    def _notify_searcher(self, trial: Trial):
        searcher = self.tune_config.search_alg
        if searcher is None:
            return
        try:
            searcher.on_trial_complete(
                trial.trial_id, result=trial.last_result or None,
                error=trial.status == "ERROR")
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "searcher.on_trial_complete failed for trial %s",
                trial.trial_id, exc_info=True)

    def get_trial(self, trial_id: str) -> Trial | None:
        for t in self.trials:
            if t.trial_id == trial_id:
                return t
        return None

    def exploit(self, trial: Trial, source: Trial, new_config: dict):
        """PBT exploit: restart `trial` from `source`'s checkpoint with the
        explored config (reference: pbt.py _exploit)."""
        self._pending_exploits.append((trial, source, new_config))

    def run(self) -> list[Trial]:
        from ray_tpu.tune.search import Searcher as _Searcher

        searcher = self.tune_config.search_alg
        limit = (self.tune_config.max_concurrent_trials
                 or (len(self.trials) if searcher is None else 4))
        active: list[Trial] = []
        # restored experiments carry finished trials — don't re-run them
        queue = [t for t in self.trials
                 if t.status not in ("TERMINATED", "STOPPED")]
        searcher_done = searcher is None
        while queue or active or not searcher_done:
            # adaptive mode: ask the searcher for configs while slots free
            while (not searcher_done and not queue
                   and len(active) < limit
                   and len(self.trials) < self.tune_config.num_samples):
                trial = Trial(None)
                config = searcher.suggest(trial.trial_id)
                if config is _Searcher.FINISHED:
                    searcher_done = True
                    break
                if config is None:     # limiter saturated / not ready
                    break
                trial.config = config
                self.trials.append(trial)
                queue.append(trial)
            if (not searcher_done
                    and len(self.trials) >= self.tune_config.num_samples):
                searcher_done = True
            while queue and len(active) < limit:
                trial = queue.pop(0)
                self._start_trial(trial)
                active.append(trial)
            progressed = False
            for trial in list(active):
                row = self._poll(trial)
                if row is None:
                    continue
                progressed = True
                if row.get("done"):
                    trial.status = ("ERROR" if row.get("error")
                                    else "TERMINATED")
                    trial.error = row.get("error")
                    self._stop_actor(trial)
                    active.remove(trial)
                    self._notify_searcher(trial)
                    self.callbacks.fire(
                        "on_trial_error" if row.get("error")
                        else "on_trial_complete", trial.iteration, trial)
                    self.save_experiment_state()
                    continue
                trial.iteration = row.get("iteration", trial.iteration + 1)
                metrics = dict(row["metrics"])
                metrics.setdefault("training_iteration", trial.iteration)
                trial.results.append(metrics)
                self.callbacks.fire("on_trial_result", trial.iteration,
                                    trial, metrics)
                if searcher is not None:
                    try:
                        searcher.on_trial_result(trial.trial_id, metrics)
                    except Exception:
                        import logging

                        logging.getLogger(__name__).warning(
                            "searcher.on_trial_result failed for trial %s",
                            trial.trial_id, exc_info=True)
                if row.get("checkpoint") is not None:
                    self._on_trial_checkpoint(trial, row["checkpoint"],
                                              metrics)
                if self._should_stop(metrics):
                    trial.status = "TERMINATED"
                    self._stop_actor(trial)
                    active.remove(trial)
                    self._notify_searcher(trial)
                    self.callbacks.fire("on_trial_complete",
                                        trial.iteration, trial)
                    self.save_experiment_state()
                    continue
                decision = self.scheduler.on_result(trial, metrics, self)
                if decision == sched_mod.STOP:
                    trial.status = "STOPPED"
                    self._stop_actor(trial)
                    active.remove(trial)
                    self._notify_searcher(trial)
                    self.callbacks.fire("on_trial_complete",
                                        trial.iteration, trial)
                self.save_experiment_state()
            for trial, source, new_config in self._pending_exploits:
                if trial in active:
                    self._stop_actor(trial, release_pg=False)
                    trial.config = new_config
                    trial.latest_checkpoint = source.latest_checkpoint
                    self._start_trial(
                        trial, resume=source.latest_checkpoint)
            self._pending_exploits.clear()
            if not progressed:
                time.sleep(0.05)
        self.callbacks.fire("on_experiment_end", self.trials)
        self._sync_up(force=True)
        return self.trials

    def _start_trial(self, trial: Trial, resume=None):
        from ray_tpu.util.placement_group import placement_group
        from ray_tpu.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy,
        )

        actor_cls = ray_tpu.remote(_TrialActor)
        opts = dict(self.resources)
        # Gang-schedule every trial in its own placement group (reference:
        # tune/execution/placement_groups.py wraps each Trial in a PG).
        # Atomic reservation means two concurrent multi-resource trials
        # can't deadlock-interleave; TPU bundles additionally get the
        # ICI-contiguous STRICT_PACK placement from the GCS scheduler.
        bundles = opts.pop("bundles", None) or [dict(opts) or {"CPU": 1}]
        if trial.pg is None:
            trial.pg = placement_group(bundles, strategy="STRICT_PACK",
                                       name=f"trial-{trial.trial_id}")
        trial.actor = actor_cls.options(
            num_cpus=bundles[0].get("CPU", 0),
            resources={k: v for k, v in bundles[0].items() if k != "CPU"}
                      or None,
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                trial.pg, placement_group_bundle_index=0),
        ).remote()
        # Fully async: actor creation may queue behind running trials for
        # resources — blocking here would starve the poll loop that frees
        # them. run() and the first next_result() chain in submission order.
        trial.actor.run.remote(
            self.trainable, trial.config,
            resume if resume is not None else trial.latest_checkpoint)
        trial.status = "RUNNING"
        trial._pending = trial.actor.next_result.remote()
        self.callbacks.fire("on_trial_start", trial.iteration, trial)

    def _poll(self, trial: Trial):
        ready, _ = ray_tpu.wait([trial._pending], num_returns=1, timeout=0.01)
        if not ready:
            return None
        try:
            row = ray_tpu.get(ready[0])
        except Exception as e:  # actor died etc.
            return {"done": True, "error": e}
        if not row.get("done"):
            trial._pending = trial.actor.next_result.remote()
        return row

    def _stop_actor(self, trial: Trial, release_pg: bool = True):
        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        if release_pg and trial.pg is not None:
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(trial.pg)
            except Exception:
                pass
            trial.pg = None


def _jsonable(d: dict) -> dict:
    out = {}
    for k, v in (d or {}).items():
        try:
            import json

            json.dumps(v)
            out[k] = v
        except (TypeError, ValueError):
            out[k] = repr(v)
    return out


class ResultGrid:
    def __init__(self, trials: list[Trial], metric: str | None,
                 mode: str = "max"):
        self.trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self.trials)

    def __getitem__(self, i) -> Result:
        t = self.trials[i]
        return Result(metrics=t.last_result, checkpoint=t.latest_checkpoint,
                      error=t.error, metrics_history=t.results)

    def get_best_result(self, metric: str | None = None,
                        mode: str | None = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [t for t in self.trials
                  if t.results and metric in t.last_result]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        best = (max if mode == "max" else min)(
            scored, key=lambda t: t.last_result[metric])
        return Result(metrics=best.last_result,
                      checkpoint=best.latest_checkpoint,
                      error=best.error, metrics_history=best.results)

    @property
    def errors(self):
        return [t.error for t in self.trials if t.error is not None]


class Tuner:
    """(reference: tune/tuner.py:220)"""

    def __init__(self, trainable, *, param_space: dict | None = None,
                 tune_config: TuneConfig | None = None,
                 run_config: RunConfig | None = None,
                 resources_per_trial: dict | None = None):
        if hasattr(trainable, "as_trainable"):   # a Trainer
            trainable = trainable.as_trainable()
        import inspect

        from ray_tpu.tune.trainable import Trainable, wrap_trainable_cls

        if inspect.isclass(trainable) and issubclass(trainable, Trainable):
            trainable = wrap_trainable_cls(trainable)
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self.resources_per_trial = resources_per_trial

    def _init_searcher(self):
        """Hand the searcher its param space + metric/mode (adaptive mode;
        reference: trial runner + SearchGenerator). Called for fresh AND
        restored experiments — a restored run keeps suggesting up to
        num_samples."""
        searcher = self.tune_config.search_alg
        for s in (searcher, getattr(searcher, "searcher", None)):
            if s is not None and hasattr(s, "param_space") \
                    and s.param_space is None and self.param_space:
                # only a real space; a searcher left with None fails fast in
                # suggest() instead of silently proposing empty configs
                s.param_space = self.param_space
        searcher.set_search_properties(self.tune_config.metric,
                                       self.tune_config.mode)
        # a searcher configured directly wins for result selection too
        if self.tune_config.metric is None:
            self.tune_config.metric = (
                getattr(searcher, "metric", None)
                or getattr(getattr(searcher, "searcher", None),
                           "metric", None))

    def fit(self) -> ResultGrid:
        if self.tune_config.search_alg is not None:
            self._init_searcher()
        if getattr(self, "_restored_trials", None) is not None:
            trials = self._restored_trials
        elif self.tune_config.search_alg is not None:
            trials = []
        else:
            configs = BasicVariantGenerator(
                self.param_space, self.tune_config.num_samples,
                seed=self.tune_config.seed).generate()
            trials = [Trial(c) for c in configs]
        runner = TrialRunner(self.trainable, trials, self.tune_config,
                             self.run_config, self.resources_per_trial)
        runner.run()
        return ResultGrid(runner.trials, self.tune_config.metric,
                          self.tune_config.mode)

    @classmethod
    def restore(cls, path: str, trainable, *,
                param_space: dict | None = None,
                tune_config: TuneConfig | None = None,
                run_config: RunConfig | None = None,
                resources_per_trial: dict | None = None) -> "Tuner":
        """Resume an experiment from its state file (reference:
        tuner.py Tuner.restore): finished trials keep their results,
        unfinished ones re-run from their latest persisted checkpoint.
        Pass the original run_config to preserve stop criteria and
        checkpoint policy (they are not serialized in the state file);
        name/storage_path are overridden to point at `path`. Pass the
        original param_space when resuming with a search_alg so it can
        keep suggesting."""
        import dataclasses
        import json
        import os

        from ray_tpu.air.checkpoint import Checkpoint

        with open(os.path.join(path, "experiment_state.json")) as f:
            state = json.load(f)
        base = run_config or RunConfig()
        run_config = dataclasses.replace(
            base,
            name=os.path.basename(path.rstrip("/")),
            storage_path=os.path.dirname(path.rstrip("/")))
        tuner = cls(trainable, param_space=param_space,
                    tune_config=tune_config, run_config=run_config,
                    resources_per_trial=resources_per_trial)
        trials = []
        for row in state["trials"]:
            t = Trial(row["config"], trial_id=row["trial_id"])
            t.iteration = row.get("iteration", 0)
            if row.get("checkpoint_dir") and                     os.path.isdir(row["checkpoint_dir"]):
                t.latest_checkpoint = Checkpoint.from_directory(
                    row["checkpoint_dir"])
            if row["status"] in ("TERMINATED", "STOPPED"):
                t.status = row["status"]
                if row.get("last_result"):
                    t.results.append(row["last_result"])
            trials.append(t)
        tuner._restored_trials = trials
        return tuner


def run(trainable, *, config: dict | None = None, num_samples: int = 1,
        metric: str | None = None, mode: str = "max", scheduler=None,
        resources_per_trial: dict | None = None, **_ignored) -> ResultGrid:
    """Functional entry point (reference: tune/tune.py:130)."""
    tuner = Tuner(
        trainable, param_space=config,
        tune_config=TuneConfig(num_samples=num_samples, metric=metric,
                               mode=mode, scheduler=scheduler),
        resources_per_trial=resources_per_trial)
    return tuner.fit()


def with_parameters(trainable, **heavy_kwargs):
    """Attach large objects to a trainable WITHOUT baking them into every
    pickled trial config (reference: tune/trainable/util.py
    with_parameters — ships them once through the object store; each trial
    actor fetches the ref instead of a copy per config)."""
    import functools

    refs = {k: ray_tpu.put(v) for k, v in heavy_kwargs.items()}

    @functools.wraps(trainable)
    def wrapped(config):
        # the closure cell over `refs` keeps the driver-side pin alive for
        # as long as the trainable exists
        resolved = {k: ray_tpu.get(r) for k, r in refs.items()}
        return trainable(config, **resolved)

    return wrapped
