"""Class-based Trainable API.

Reference: python/ray/tune/trainable/trainable.py:314 (Trainable with
setup/step/save_checkpoint/load_checkpoint) — the API RLlib's Algorithm
and long-running experiments use. The runner wraps a Trainable subclass
into the function-trainable protocol: setup once (restoring from a
checkpoint if resuming), then report a result per step() until a stop
condition or scheduler decision ends the trial.
"""
from __future__ import annotations

from ray_tpu.air.checkpoint import Checkpoint


class Trainable:
    checkpoint_frequency: int = 1   # steps between checkpoints (0 = never)

    def __init__(self, config: dict | None = None):
        self.config = dict(config or {})
        self.iteration = 0
        self.setup(self.config)

    # -- override these ----------------------------------------------------
    def setup(self, config: dict):
        pass

    def step(self) -> dict:
        raise NotImplementedError

    def save_checkpoint(self) -> dict:
        return {}

    def load_checkpoint(self, checkpoint: dict):
        pass

    def cleanup(self):
        pass

    # -- runner protocol ---------------------------------------------------
    def train(self) -> dict:
        self.iteration += 1
        metrics = self.step()
        metrics.setdefault("training_iteration", self.iteration)
        return metrics


def wrap_trainable_cls(cls):
    """Trainable subclass → function trainable driving the session loop."""

    def fn(config):
        from ray_tpu.air import session

        t = cls(config)
        resume = session.get_checkpoint()
        if resume is not None:
            state = resume.to_dict()
            t.iteration = state.get("_iteration", 0)
            t.load_checkpoint(state.get("_user", {}))
        try:
            while True:
                metrics = t.train()
                ckpt = None
                freq = getattr(t, "checkpoint_frequency", 1)
                if freq and t.iteration % freq == 0:
                    ckpt = Checkpoint.from_dict(
                        {"_iteration": t.iteration,
                         "_user": t.save_checkpoint()})
                session.report(metrics, checkpoint=ckpt)
                if metrics.get("done"):
                    break
        finally:
            t.cleanup()

    fn.__name__ = getattr(cls, "__name__", "trainable")
    return fn
