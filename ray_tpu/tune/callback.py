"""Tune user callbacks (reference: python/ray/tune/callback.py).

Callbacks observe the experiment loop: RunConfig(callbacks=[...]) wires
them into the TrialRunner, which invokes each hook synchronously on the
driver. LoggerCallbacks (tune/logger.py here) build on this surface —
exactly the reference's split between Callback and LoggerCallback.
"""
from __future__ import annotations

import logging

logger = logging.getLogger(__name__)


class Callback:
    """Base class; override any subset of hooks. Hook failures are
    logged, never fatal to the experiment (reference behavior)."""

    def setup(self, experiment_dir: str | None):
        """Called once before the first trial starts."""

    def on_trial_start(self, iteration: int, trial):
        pass

    def on_trial_result(self, iteration: int, trial, result: dict):
        pass

    def on_checkpoint(self, iteration: int, trial, checkpoint_path: str):
        pass

    def on_trial_complete(self, iteration: int, trial):
        pass

    def on_trial_error(self, iteration: int, trial):
        pass

    def on_experiment_end(self, trials: list):
        pass


class _CallbackList:
    """Fans hooks out to every callback, isolating failures."""

    def __init__(self, callbacks):
        self._callbacks = list(callbacks or [])

    def __bool__(self):
        return bool(self._callbacks)

    def fire(self, hook: str, *args, **kwargs):
        for cb in self._callbacks:
            fn = getattr(cb, hook, None)
            if fn is None:
                continue
            try:
                fn(*args, **kwargs)
            except Exception:
                logger.warning("tune callback %s.%s failed",
                               type(cb).__name__, hook, exc_info=True)
