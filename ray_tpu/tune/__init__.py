from ray_tpu.tune.schedulers import (  # noqa: F401
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandForBOHB,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
)
from ray_tpu.tune.search import (  # noqa: F401
    BOHBSearcher,
    ConcurrencyLimiter,
    ExternalSearcher,
    OptunaSearch,
    Searcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.callback import Callback  # noqa: F401
from ray_tpu.tune.logger import (  # noqa: F401
    CSVLoggerCallback,
    JsonLoggerCallback,
    LoggerCallback,
    MLflowLoggerCallback,
    TBXLoggerCallback,
    WandbLoggerCallback,
)
from ray_tpu.tune.syncer import SyncConfig, Syncer  # noqa: F401
from ray_tpu.tune.trainable import Trainable  # noqa: F401
from ray_tpu.tune.tuner import (  # noqa: F401
    ResultGrid,
    Trial,
    TrialRunner,
    TuneConfig,
    Tuner,
    run,
)
