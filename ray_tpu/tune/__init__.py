from ray_tpu.tune.schedulers import (  # noqa: F401
    AsyncHyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from ray_tpu.tune.search import (  # noqa: F401
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.tuner import (  # noqa: F401
    ResultGrid,
    Trial,
    TrialRunner,
    TuneConfig,
    Tuner,
    run,
)
