"""Search spaces and the basic variant generator (reference:
python/ray/tune/search/ — sample.py domains, basic_variant.py grid/random
expansion).
"""
from __future__ import annotations

import random


class Domain:
    def sample(self, rng: random.Random):
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low, high):
        import math

        self.log_low, self.log_high = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.log_low, self.log_high))


class Randint(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> Randint:
    return Randint(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


class BasicVariantGenerator:
    """Expand grid axes (cartesian product) × num_samples random draws
    (reference: tune/search/basic_variant.py)."""

    def __init__(self, param_space: dict, num_samples: int = 1,
                 seed: int | None = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)

    def generate(self) -> list[dict]:
        grids = self._grid_axes(self.param_space)
        combos = [{}]
        for path, values in grids:
            combos = [dict(c, **{path: v}) for c in combos for v in values]
        configs = []
        for _ in range(self.num_samples):
            for combo in combos:
                configs.append(self._materialize(self.param_space, combo))
        return configs

    def _grid_axes(self, space, prefix=""):
        axes = []
        for key, value in space.items():
            path = f"{prefix}{key}"
            if isinstance(value, GridSearch):
                axes.append((path, value.values))
            elif isinstance(value, dict):
                axes.extend(self._grid_axes(value, prefix=f"{path}."))
        return axes

    def _materialize(self, space, grid_values, prefix=""):
        out = {}
        for key, value in space.items():
            path = f"{prefix}{key}"
            if isinstance(value, GridSearch):
                out[key] = grid_values[path]
            elif isinstance(value, Domain):
                out[key] = value.sample(self.rng)
            elif isinstance(value, dict):
                out[key] = self._materialize(value, grid_values,
                                             prefix=f"{path}.")
            else:
                out[key] = value
        return out


# --------------------------------------------------------------- searchers
def flatten_domains(space: dict, prefix: str = "") -> dict:
    """Nested param space → {dotted.path: domain-or-constant}."""
    flat = {}
    for key, value in space.items():
        path = f"{prefix}{key}"
        if isinstance(value, dict):
            flat.update(flatten_domains(value, prefix=f"{path}."))
        else:
            flat[path] = value
    return flat


def build_config(flat_values: dict, space: dict, prefix: str = "") -> dict:
    """{dotted.path: value} → nested config shaped like `space`."""
    out = {}
    for key, value in space.items():
        path = f"{prefix}{key}"
        if isinstance(value, dict):
            out[key] = build_config(flat_values, value, prefix=f"{path}.")
        elif isinstance(value, (Domain, GridSearch)):
            out[key] = flat_values[path]
        else:
            out[key] = value
    return out


def flatten_config(config: dict, space: dict, prefix: str = "") -> dict:
    """Nested config → {dotted.path: value} for the sampled dimensions."""
    flat = {}
    for key, value in space.items():
        path = f"{prefix}{key}"
        if isinstance(value, dict):
            flat.update(flatten_config(config[key], value,
                                       prefix=f"{path}."))
        elif isinstance(value, (Domain, GridSearch)):
            flat[path] = config[key]
    return flat


class Searcher:
    """Adaptive search algorithm interface (reference:
    tune/search/searcher.py). ``suggest`` returns the next config, or None
    when no suggestion is currently available, or FINISHED when the search
    space is exhausted."""

    FINISHED = object()

    def set_search_properties(self, metric: str | None, mode: str | None):
        """Fill in metric/mode from the TuneConfig — only where the
        searcher wasn't already configured directly (the reference's
        set_search_properties returns False for the same reason: the
        searcher's own settings must not be silently clobbered)."""
        if getattr(self, "metric", None) is None:
            self.metric = metric
        if getattr(self, "mode", None) is None:
            self.mode = mode or "max"

    def suggest(self, trial_id: str):
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: dict):
        pass

    def on_trial_complete(self, trial_id: str, result: dict | None = None,
                          error: bool = False):
        pass


class ConcurrencyLimiter(Searcher):
    """Cap in-flight suggestions (reference:
    tune/search/concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set[str] = set()

    def set_search_properties(self, metric, mode):
        super().set_search_properties(metric, mode)
        self.searcher.set_search_properties(metric, mode)

    def suggest(self, trial_id):
        if len(self._live) >= self.max_concurrent:
            return None
        config = self.searcher.suggest(trial_id)
        if config is not None and config is not Searcher.FINISHED:
            self._live.add(trial_id)
        return config

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (the Optuna/HyperOpt default;
    reference integrations: tune/search/optuna/optuna_search.py,
    tune/search/hyperopt/hyperopt_search.py — implemented natively here
    since neither library is vendored).

    After ``n_startup_trials`` random draws, observations are split at the
    ``gamma`` quantile into good/bad sets; per-dimension Parzen (KDE)
    densities l(x) and g(x) are built over each set and the candidate
    maximizing l(x)/g(x) among ``n_candidates`` draws from l is suggested.
    Numeric domains use Gaussian kernels (log-space for LogUniform);
    Choice/Randint use smoothed categorical counts.
    """

    def __init__(self, param_space: dict | None = None,
                 metric: str | None = None, mode: str | None = None,
                 n_startup_trials: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24, seed: int | None = None):
        self.param_space = param_space
        self.metric = metric
        self.mode = mode
        self.n_startup_trials = n_startup_trials
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self._observations: list[tuple[dict, float]] = []
        self._pending: dict[str, dict] = {}

    # -- domain helpers -----------------------------------------------
    def _random_flat(self):
        flat = {}
        for path, dom in flatten_domains(self.param_space).items():
            if isinstance(dom, GridSearch):
                flat[path] = self.rng.choice(dom.values)
            elif isinstance(dom, Domain):
                flat[path] = dom.sample(self.rng)
            else:
                flat[path] = dom
        return flat

    # -- TPE core ------------------------------------------------------
    def _sample_dim(self, dom, good_vals):
        """Draw one value from the Parzen density fit to good_vals."""
        import math

        if isinstance(dom, (Choice, GridSearch)):
            cats = dom.categories if isinstance(dom, Choice) else dom.values
            weights = [1.0 + sum(1 for v in good_vals if v == c)
                       for c in cats]
            total = sum(weights)
            r = self.rng.uniform(0, total)
            acc = 0.0
            for cat, w in zip(cats, weights):
                acc += w
                if r <= acc:
                    return cat
            return cats[-1]
        if isinstance(dom, Randint):
            center = self.rng.choice(good_vals)
            width = max(1, round((dom.high - dom.low) * 0.2))
            lo = max(dom.low, center - width)
            hi = min(dom.high, center + width + 1)
            return self.rng.randrange(lo, hi)
        if isinstance(dom, LogUniform):
            center = math.log(self.rng.choice(good_vals))
            sigma = max((dom.log_high - dom.log_low) * 0.15, 1e-12)
            val = self.rng.gauss(center, sigma)
            val = min(max(val, dom.log_low), dom.log_high)
            return math.exp(val)
        if isinstance(dom, Uniform):
            center = self.rng.choice(good_vals)
            sigma = max((dom.high - dom.low) * 0.15, 1e-12)
            val = self.rng.gauss(center, sigma)
            return min(max(val, dom.low), dom.high)
        return dom

    def _log_density(self, dom, vals, x):
        import math

        if not vals:
            return 0.0
        if isinstance(dom, (Choice, GridSearch)):
            cats = dom.categories if isinstance(dom, Choice) else dom.values
            count = 1.0 + sum(1 for v in vals if v == x)
            return math.log(count / (len(vals) + len(cats)))
        if isinstance(dom, LogUniform):
            xs = [math.log(v) for v in vals]
            xq = math.log(x)
            sigma = max((dom.log_high - dom.log_low) * 0.15, 1e-12)
        elif isinstance(dom, Randint):
            xs = [float(v) for v in vals]
            xq = float(x)
            sigma = max((dom.high - dom.low) * 0.2, 1.0)
        else:
            xs = [float(v) for v in vals]
            xq = float(x)
            sigma = max((dom.high - dom.low) * 0.15, 1e-12)
        dens = sum(math.exp(-0.5 * ((xq - c) / sigma) ** 2) for c in xs)
        return math.log(max(dens / (len(xs) * sigma), 1e-300))

    def suggest(self, trial_id):
        if self.param_space is None:
            raise ValueError("TPESearcher needs a param_space (pass it to "
                             "the searcher or via Tuner(param_space=...))")
        if len(self._observations) < self.n_startup_trials:
            flat = self._random_flat()
        else:
            scored = sorted(self._observations, key=lambda o: o[1],
                            reverse=((self.mode or "max") == "max"))
            n_good = max(1, int(len(scored) * self.gamma))
            good = [flatten_config(c, self.param_space)
                    for c, _ in scored[:n_good]]
            bad = [flatten_config(c, self.param_space)
                   for c, _ in scored[n_good:]]
            domains = flatten_domains(self.param_space)
            best_flat, best_score = None, -float("inf")
            for _ in range(self.n_candidates):
                cand = {}
                score = 0.0
                for path, dom in domains.items():
                    if not isinstance(dom, (Domain, GridSearch)):
                        cand[path] = dom
                        continue
                    good_vals = [g[path] for g in good]
                    bad_vals = [b[path] for b in bad]
                    x = self._sample_dim(dom, good_vals)
                    cand[path] = x
                    score += (self._log_density(dom, good_vals, x)
                              - self._log_density(dom, bad_vals, x))
                if score > best_score:
                    best_flat, best_score = cand, score
            flat = best_flat
        config = build_config(flat, self.param_space)
        self._pending[trial_id] = config
        return config

    def on_trial_complete(self, trial_id, result=None, error=False):
        config = self._pending.pop(trial_id, None)
        if config is None or error or not result:
            return
        if self.metric and self.metric in result:
            self._observations.append((config, float(result[self.metric])))


class BOHBSearcher(TPESearcher):
    """BOHB's model half (reference: tune/search/bohb/bohb_search.py):
    TPE fit on rung-level observations fed by HyperBandForBOHB — the
    model always trains on the HIGHEST rung (budget) that has enough
    data, so early low-fidelity scores guide sampling until
    high-fidelity results exist, then stop polluting the model."""

    def __init__(self, *args, min_rung_points: int | None = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.min_rung_points = (min_rung_points
                                if min_rung_points is not None
                                else self.n_startup_trials)
        self._rungs: dict[int, list[tuple[dict, float]]] = {}

    def observe_rung(self, config: dict, iteration: int, score: float):
        self._rungs.setdefault(int(iteration), []).append(
            (dict(config), float(score)))

    def suggest(self, trial_id):
        pool = None
        for rung in sorted(self._rungs, reverse=True):
            if len(self._rungs[rung]) >= self.min_rung_points:
                pool = self._rungs[rung]
                break
        if pool is not None:
            # COPY: aliasing the rung list would let the inherited
            # on_trial_complete append final-fidelity results into the
            # rung, polluting its budget-pure data
            self._observations = list(pool)
        return super().suggest(trial_id)


class ExternalSearcher(Searcher):
    """Adapter for third-party search libraries (the reference's
    integration shape: tune/search/optuna/optuna_search.py,
    hyperopt/hyperopt_search.py). Wraps any backend exposing the
    ask/tell protocol:

        ask()  -> (handle, config_dict)   # next configuration
        tell(handle, value, error=False)  # report the (mode-signed)
                                          # final metric

    The adapter owns trial_id -> handle bookkeeping and metric/mode
    normalization; the backend never sees tune types.
    """

    def __init__(self, backend, metric: str | None = None,
                 mode: str | None = None):
        if not hasattr(backend, "ask") or not hasattr(backend, "tell"):
            raise TypeError("ExternalSearcher backend must expose "
                            "ask()/tell()")
        self.backend = backend
        self.metric = metric
        self.mode = mode
        self._handles: dict[str, object] = {}

    def suggest(self, trial_id):
        out = self.backend.ask()
        if out is None:
            return Searcher.FINISHED
        handle, config = out
        self._handles[trial_id] = handle
        return dict(config)

    def on_trial_complete(self, trial_id, result=None, error=False):
        handle = self._handles.pop(trial_id, None)
        if handle is None:
            return
        value = None
        if result and self.metric and self.metric in result:
            value = float(result[self.metric])
            if (self.mode or "max") == "min":
                value = -value
        try:
            self.backend.tell(handle, value, error=error or value is None)
        except TypeError:
            self.backend.tell(handle, value)


class OptunaSearch(ExternalSearcher):
    """Optuna integration over the ask/tell adapter (reference:
    tune/search/optuna/optuna_search.py). Translates the tune Domain
    space into optuna distributions; requires optuna installed."""

    def __init__(self, param_space: dict, metric: str | None = None,
                 mode: str | None = None, seed: int | None = None):
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires optuna (not bundled in this "
                "image); use the native TPESearcher for the same "
                "algorithm, or wrap another library via "
                "ExternalSearcher") from e

        domains = flatten_domains(param_space)
        study = optuna.create_study(
            sampler=optuna.samplers.TPESampler(seed=seed),
            direction="maximize")

        class _Backend:
            def ask(self):
                trial = study.ask()
                flat = {}
                for path, dom in domains.items():
                    if isinstance(dom, LogUniform):
                        flat[path] = trial.suggest_float(
                            path, dom.low, dom.high, log=True)
                    elif isinstance(dom, Uniform):
                        flat[path] = trial.suggest_float(
                            path, dom.low, dom.high)
                    elif isinstance(dom, Randint):
                        flat[path] = trial.suggest_int(
                            path, dom.low, dom.high - 1)
                    elif isinstance(dom, (Choice, GridSearch)):
                        cats = (dom.categories if isinstance(dom, Choice)
                                else dom.values)
                        flat[path] = trial.suggest_categorical(path, cats)
                    else:
                        flat[path] = dom
                return trial, build_config(flat, param_space)

            def tell(self, trial, value, error=False):
                state = (optuna.trial.TrialState.FAIL if error
                         else optuna.trial.TrialState.COMPLETE)
                study.tell(trial, value, state=state)

        super().__init__(_Backend(), metric=metric, mode=mode)
        self.param_space = param_space
