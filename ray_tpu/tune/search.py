"""Search spaces and the basic variant generator (reference:
python/ray/tune/search/ — sample.py domains, basic_variant.py grid/random
expansion).
"""
from __future__ import annotations

import random


class Domain:
    def sample(self, rng: random.Random):
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low, high):
        import math

        self.log_low, self.log_high = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.log_low, self.log_high))


class Randint(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> Randint:
    return Randint(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


class BasicVariantGenerator:
    """Expand grid axes (cartesian product) × num_samples random draws
    (reference: tune/search/basic_variant.py)."""

    def __init__(self, param_space: dict, num_samples: int = 1,
                 seed: int | None = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)

    def generate(self) -> list[dict]:
        grids = self._grid_axes(self.param_space)
        combos = [{}]
        for path, values in grids:
            combos = [dict(c, **{path: v}) for c in combos for v in values]
        configs = []
        for _ in range(self.num_samples):
            for combo in combos:
                configs.append(self._materialize(self.param_space, combo))
        return configs

    def _grid_axes(self, space, prefix=""):
        axes = []
        for key, value in space.items():
            path = f"{prefix}{key}"
            if isinstance(value, GridSearch):
                axes.append((path, value.values))
            elif isinstance(value, dict):
                axes.extend(self._grid_axes(value, prefix=f"{path}."))
        return axes

    def _materialize(self, space, grid_values, prefix=""):
        out = {}
        for key, value in space.items():
            path = f"{prefix}{key}"
            if isinstance(value, GridSearch):
                out[key] = grid_values[path]
            elif isinstance(value, Domain):
                out[key] = value.sample(self.rng)
            elif isinstance(value, dict):
                out[key] = self._materialize(value, grid_values,
                                             prefix=f"{path}.")
            else:
                out[key] = value
        return out
